"""Fleet autoscaler: a control loop over aggregated serve.health.

ROADMAP item 5(a): the serving tier can heal itself (supervisor) and
describe itself (serve_stats rolling windows with a staleness stamp) —
this module closes the remaining loop by *sizing* the fleet. The design
splits cleanly in two:

- a **pure decision core** (``decide`` over ``ReplicaSnapshot`` /
  ``ScalingPolicy`` / ``ControllerState``) with no clocks, threads or IO
  — every watermark crossing, hysteresis band, cooldown and clamping
  rule is unit-testable with hand-built snapshots;
- a thin **collection/actuation shell** (``FleetController``) that
  gathers one ``serve.health`` snapshot per supervised replica each
  tick, feeds the core, and acts: scale-up starts a supervised replica
  (``ReplicaSupervisor.scale_up``), scale-down retires the least-loaded
  one through the graceful-drain path (``scale_down`` → SIGTERM → drain
  → registry retraction) so zero in-flight queries drop.

**Pressure** folds the three load signals into one scalar per replica —
footprint pressure (``device_budget_fraction``), queue pressure
(admission queue depth over the replica's concurrency), and optionally
latency pressure (window p99 over ``serving.fleet.p99ObjectiveSeconds``)
— and averages across *healthy* replicas. DEGRADED slots and replicas
whose serve_stats series has gone stale past
``serving.stats.staleAfterSeconds`` are excluded from both the average
and the healthy count: a wedged replica must not dilute the fleet's
pressure reading, and a crash-looping slot is not capacity.

**Hysteresis** keeps the fleet from flapping: pressure must sit past a
watermark for N consecutive ticks (``scaleUp/DownStableTicks``) before
an action fires, an in-band reading resets both streaks, and per-
direction cooldowns (``scaleUp/DownCooldownSeconds``, measured from the
last action in *either* direction) space actions out. Targets clamp to
``serving.fleet.{min,max}Replicas``; a fleet below its floor scales up
regardless of pressure.
"""
from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.serving import wire
from spark_rapids_tpu.shuffle.transport import TransactionStatus
from spark_rapids_tpu.utils import metrics as um


# ---- the pure decision core -------------------------------------------------

@dataclass(frozen=True)
class ReplicaSnapshot:
    """One replica's load as the controller sees it — built from a
    serve.health payload, or by hand in unit tests."""
    addr: str
    state: str                          # UP | DRAINING (server-reported)
    age_s: Optional[float]              # serve_stats staleness (None: new)
    queue_depth: int = 0
    budget_fraction: float = 0.0
    p99_wall_s: float = 0.0
    queries_open: int = 0

    @staticmethod
    def from_health(addr: str, payload: Dict[str, Any]) -> "ReplicaSnapshot":
        ss = payload.get("serve_stats") or {}
        now = ss.get("now") or {}
        return ReplicaSnapshot(
            addr=addr,
            state=str(payload.get("state", "UP")),
            age_s=ss.get("age_s"),
            queue_depth=int(now.get("admission_queue_depth", 0) or 0),
            budget_fraction=float(now.get("device_budget_fraction", 0.0)
                                  or 0.0),
            p99_wall_s=float(ss.get("p99_wall_s", 0.0) or 0.0),
            queries_open=int(payload.get("queries_open", 0) or 0))


@dataclass(frozen=True)
class ScalingPolicy:
    """The immutable knobs of the control loop (all from conf)."""
    min_replicas: int = 1
    max_replicas: int = 4
    up_watermark: float = 0.8
    down_watermark: float = 0.25
    up_stable_ticks: int = 2
    down_stable_ticks: int = 5
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 30.0
    stale_after_s: float = 10.0
    queue_norm: int = 4                 # queue depth == concurrency → 1.0
    p99_objective_s: float = 0.0        # 0: latency component disabled

    @staticmethod
    def from_conf(conf) -> "ScalingPolicy":
        return ScalingPolicy(
            min_replicas=conf.get(cfg.SERVING_FLEET_MIN_REPLICAS),
            max_replicas=conf.get(cfg.SERVING_FLEET_MAX_REPLICAS),
            up_watermark=conf.get(cfg.SERVING_FLEET_SCALE_UP_WATERMARK),
            down_watermark=conf.get(cfg.SERVING_FLEET_SCALE_DOWN_WATERMARK),
            up_stable_ticks=conf.get(cfg.SERVING_FLEET_SCALE_UP_STABLE_TICKS),
            down_stable_ticks=conf.get(
                cfg.SERVING_FLEET_SCALE_DOWN_STABLE_TICKS),
            up_cooldown_s=conf.get(cfg.SERVING_FLEET_SCALE_UP_COOLDOWN),
            down_cooldown_s=conf.get(cfg.SERVING_FLEET_SCALE_DOWN_COOLDOWN),
            stale_after_s=conf.get(cfg.SERVING_STATS_STALE_AFTER),
            queue_norm=conf.get(cfg.SERVING_MAX_CONCURRENT),
            p99_objective_s=conf.get(cfg.SERVING_FLEET_P99_OBJECTIVE))


@dataclass
class ControllerState:
    """The loop's only mutable memory: hysteresis streaks + the cooldown
    clock. ``last_action_at`` starts at -inf so the first decision is
    never cooldown-suppressed."""
    up_streak: int = 0
    down_streak: int = 0
    last_action_at: float = field(default=float("-inf"))


@dataclass(frozen=True)
class Decision:
    """What one control tick concluded, with its inputs on record."""
    action: int                         # +1 scale up, -1 scale down, 0 hold
    pressure: Optional[float]           # None: no healthy signal this tick
    healthy: int
    reason: str


def replica_pressure(snap: ReplicaSnapshot, policy: ScalingPolicy) -> float:
    """One replica's load scalar: the HOTTEST of its signals (a replica
    whose queue is deep is saturated even with device budget to spare,
    and vice versa). Can exceed 1.0 — a queue past the concurrency bound
    reads as over-saturated, which is exactly right."""
    parts = [snap.budget_fraction,
             snap.queue_depth / max(1, policy.queue_norm)]
    if policy.p99_objective_s > 0:
        parts.append(snap.p99_wall_s / policy.p99_objective_s)
    return max(parts)


def healthy_snapshots(snaps: List[ReplicaSnapshot],
                      policy: ScalingPolicy) -> List[ReplicaSnapshot]:
    """Replicas the controller may trust and count as capacity: state UP
    (a DRAINING replica is already leaving) with a serve_stats series
    that hasn't flat-lined past the staleness bound. ``age_s`` of None
    means the replica just started and hasn't a prior sample — fresh,
    not stale."""
    return [s for s in snaps
            if s.state == "UP"
            and (s.age_s is None or s.age_s <= policy.stale_after_s)]


def decide(snaps: List[ReplicaSnapshot], active_count: int,
           state: ControllerState, policy: ScalingPolicy,
           now: float) -> Decision:
    """One pure control step. ``active_count`` is the supervisor's view
    of slots that are (or are coming back) up — BACKOFF counts, DEGRADED
    does not. Mutates ``state`` (streaks, cooldown clock) in place."""
    # floor/ceiling clamps outrank pressure: a fleet below its floor is
    # under-provisioned by definition (e.g. crash-loop breakers removed
    # slots), and one above its ceiling must shrink
    if active_count < policy.min_replicas:
        state.up_streak = state.down_streak = 0
        state.last_action_at = now
        return Decision(+1, None, len(healthy_snapshots(snaps, policy)),
                        f"below floor: {active_count} active < "
                        f"min {policy.min_replicas}")
    if active_count > policy.max_replicas:
        state.up_streak = state.down_streak = 0
        state.last_action_at = now
        return Decision(-1, None, len(healthy_snapshots(snaps, policy)),
                        f"above ceiling: {active_count} active > "
                        f"max {policy.max_replicas}")
    healthy = healthy_snapshots(snaps, policy)
    if not healthy:
        # every series is stale or draining: no trustworthy signal —
        # hold rather than flap on noise (the supervisor, not the
        # autoscaler, owns dead/wedged replicas)
        state.up_streak = state.down_streak = 0
        return Decision(0, None, 0, "no healthy signal: hold")
    pressure = round(sum(replica_pressure(s, policy)
                         for s in healthy) / len(healthy), 4)
    if pressure >= policy.up_watermark:
        state.up_streak += 1
        state.down_streak = 0
    elif pressure <= policy.down_watermark:
        state.down_streak += 1
        state.up_streak = 0
    else:
        # in-band: hysteresis resets — a single excursion must not be
        # remembered across an interleaved calm reading
        state.up_streak = state.down_streak = 0
        return Decision(0, pressure, len(healthy),
                        f"in band ({policy.down_watermark} < {pressure} "
                        f"< {policy.up_watermark})")
    since_action = now - state.last_action_at
    if state.up_streak >= policy.up_stable_ticks:
        if active_count >= policy.max_replicas:
            return Decision(0, pressure, len(healthy),
                            f"at ceiling {policy.max_replicas}: hold")
        if since_action < policy.up_cooldown_s:
            return Decision(0, pressure, len(healthy),
                            f"up cooldown ({since_action:.1f}s < "
                            f"{policy.up_cooldown_s}s)")
        state.up_streak = state.down_streak = 0
        state.last_action_at = now
        return Decision(+1, pressure, len(healthy),
                        f"pressure {pressure} >= {policy.up_watermark} "
                        f"for {policy.up_stable_ticks} ticks")
    if state.down_streak >= policy.down_stable_ticks:
        if active_count <= policy.min_replicas:
            return Decision(0, pressure, len(healthy),
                            f"at floor {policy.min_replicas}: hold")
        if since_action < policy.down_cooldown_s:
            return Decision(0, pressure, len(healthy),
                            f"down cooldown ({since_action:.1f}s < "
                            f"{policy.down_cooldown_s}s)")
        state.up_streak = state.down_streak = 0
        state.last_action_at = now
        return Decision(-1, pressure, len(healthy),
                        f"pressure {pressure} <= {policy.down_watermark} "
                        f"for {policy.down_stable_ticks} ticks")
    return Decision(0, pressure, len(healthy),
                    f"streak building (up {state.up_streak}/"
                    f"{policy.up_stable_ticks}, down {state.down_streak}/"
                    f"{policy.down_stable_ticks})")


def pick_scale_down_target(healthy: List[ReplicaSnapshot],
                           policy: ScalingPolicy) -> Optional[str]:
    """The replica to retire: the least-loaded healthy one (fewest open
    queries, then lowest pressure) — draining it strands the least work
    and finishes fastest."""
    if not healthy:
        return None
    return min(healthy, key=lambda s: (s.queries_open,
                                       replica_pressure(s, policy))).addr


# ---- the collection/actuation shell ----------------------------------------

class FleetController:
    """Periodic control loop binding the decision core to a supervised
    fleet: collect serve.health per replica, decide, actuate."""

    def __init__(self, conf, supervisor):
        self.conf = conf
        self.supervisor = supervisor
        self.policy = ScalingPolicy.from_conf(conf)
        self._interval = conf.get(cfg.SERVING_FLEET_CONTROL_INTERVAL)
        self._probe_timeout = conf.get(cfg.SERVING_HEALTH_PROBE_TIMEOUT)
        self._transport = None
        self._lock = threading.Lock()
        self.state = ControllerState()
        self.last_decision: Optional[Decision] = None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- collection --------------------------------------------------------
    def _ensure_transport(self):
        with self._lock:
            if self._transport is None:
                self._transport = wire.make_serving_transport(
                    "fleet-controller", self.conf, listen_port=0)
            return self._transport

    def _health(self, addr: str) -> Optional[Dict[str, Any]]:
        try:
            conn = self._ensure_transport().connect(addr)
            tx = conn.request(wire.REQ_HEALTH, b"", lambda t: None)
            tx.wait(self._probe_timeout)
            if tx.status is not TransactionStatus.SUCCESS:
                return None
            return json.loads(tx.response)
        except (OSError, TimeoutError, ValueError):
            # unreachable/garbled: the supervisor's liveness machinery
            # owns dead replicas; the controller just loses one sample
            return None

    def collect(self) -> List[ReplicaSnapshot]:
        snaps: List[ReplicaSnapshot] = []
        for addr in self.supervisor.addresses():
            payload = self._health(addr)
            if payload is not None:
                snaps.append(ReplicaSnapshot.from_health(addr, payload))
        return snaps

    # ---- actuation ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Decision:
        """One collect→decide→act pass; public so tests and CI drive the
        loop deterministically."""
        now = time.monotonic() if now is None else now
        snaps = self.collect()
        decision = decide(snaps, self.supervisor.active_count(),
                          self.state, self.policy, now)
        if decision.action > 0:
            self.supervisor.scale_up()
            um.SERVING_METRICS[um.SERVING_SCALE_UPS].add(1)
        elif decision.action < 0:
            target = pick_scale_down_target(
                healthy_snapshots(snaps, self.policy), self.policy)
            # scale-down goes through the supervisor's graceful path:
            # terminate == the SIGTERM drain contract, so every running
            # query finishes and the registry entry is retracted
            if self.supervisor.scale_down(target) is not None:
                um.SERVING_METRICS[um.SERVING_SCALE_DOWNS].add(1)
            else:
                decision = replace(decision, action=0,
                                   reason=decision.reason
                                   + " (no retirable replica)")
        self.last_decision = decision
        return decision

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="fleet-controller")
            self._thread.start()

    def _loop(self) -> None:
        # Event.wait is the bounded sleep (R010); collection/actuation IO
        # happens without the controller lock (R006)
        while not self._stop_event.wait(self._interval):
            self.tick()

    def stop(self) -> None:
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            transport, self._transport = self._transport, None
        if transport is not None:
            transport.shutdown()
