"""Replica health: circuit breakers, liveness discovery, routing scores.

The client-side half of fleet resilience. PR 12's routing client was a
bare round-robin over static addresses: a dead replica stayed in rotation
forever (every Nth submission failed), and there was no signal to send
the whale anywhere smarter than "next". This module supplies the three
pieces the router needs:

- **CircuitBreaker** — per-replica failure containment. CLOSED passes
  submissions; ``serving.failover.breakerFailureThreshold`` consecutive
  failures flip it OPEN (counted in ``serving.breaker_opens``): an OPEN
  replica receives ZERO submissions, only health probes on the
  deterministic exponential-backoff schedule (shuffle/retry.py — the
  same jittered schedule every retry layer in this engine uses). A due
  probe moves the breaker HALF_OPEN (one trial): probe success closes
  it, failure re-opens it with a deeper backoff.
- **ReplicaState** — one replica's routing record: address, breaker,
  the latest ``serve.health`` snapshot (the PR 13 serve_stats
  time-series), its DRAINING flag, and which tables were successfully
  registered there (the deferred re-register ledger).
- **routing_score** — the load-aware routing policy's scalar: free
  device budget after footprint charges (the dominant term — the whale
  must land where it fits), penalized by queue depth + running count
  and by the replica's p99 wall over the stats window.

Liveness itself rides the shuffle registry-dir rendezvous
(``shuffle/tcp.py``): replicas publish ``<dir>/<executor_id>`` and
refresh its mtime as a heartbeat; ``scan_registry`` with the
``serving.health.livenessWindowSeconds`` window skips AND
garbage-collects entries whose heartbeat stopped (a SIGKILL'd replica
cannot retract its own file).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Set

from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.utils import metrics as um

#: breaker states (strings so they serialize into stats snapshots as-is)
BREAKER_CLOSED = "CLOSED"
BREAKER_OPEN = "OPEN"
BREAKER_HALF_OPEN = "HALF_OPEN"


class CircuitBreaker:
    """Per-replica circuit breaker: consecutive-failure threshold ->
    OPEN with exponentially backed-off probes -> HALF_OPEN trial."""

    def __init__(self, threshold: int = 3, backoff_ms: float = 200.0,
                 seed: int = 0, key: str = "", trial_timeout_s: float = 30.0):
        self.threshold = max(1, int(threshold))
        self.backoff_ms = float(backoff_ms)
        self.seed = seed
        self.key = key
        #: how long one HALF_OPEN trial owns the probe slot before the
        #: breaker re-offers it (a prober that crashed without reporting
        #: must not wedge the breaker HALF_OPEN forever)
        self.trial_timeout_s = float(trial_timeout_s)
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self._failures = 0          # consecutive, reset by any success
        self._opens = 0             # lifetime opens: the backoff exponent
        self._probe_at = 0.0        # monotonic time the next probe is due
        self._trial_deadline = 0.0  # current HALF_OPEN trial's claim

    def allow_submit(self) -> bool:
        """Only a CLOSED breaker passes submissions — OPEN and HALF_OPEN
        replicas see health probes exclusively until one succeeds."""
        with self._lock:
            return self.state == BREAKER_CLOSED

    def probe_due(self, now: Optional[float] = None) -> bool:
        """True when an OPEN breaker's backoff has elapsed — the call
        moves it HALF_OPEN and the caller owns the ONE probe trial in
        flight. While HALF_OPEN, further callers are refused until the
        trial reports (or its claim times out: a prober that crashed
        without reporting must not wedge the breaker), so concurrent
        submissions cannot pile probes onto one dead replica."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state == BREAKER_HALF_OPEN:
                if now < self._trial_deadline:
                    return False        # a trial is in flight
                self._trial_deadline = now + self.trial_timeout_s
                return True
            if self.state == BREAKER_OPEN and now >= self._probe_at:
                self.state = BREAKER_HALF_OPEN
                self._trial_deadline = now + self.trial_timeout_s
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.state = BREAKER_CLOSED
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self.state == BREAKER_HALF_OPEN:
                self._reopen_locked()       # failed trial: deeper backoff
            elif (self.state == BREAKER_CLOSED
                  and self._failures >= self.threshold):
                um.SERVING_METRICS[um.SERVING_BREAKER_OPENS].add(1)
                self._reopen_locked()

    def _reopen_locked(self) -> None:
        self.state = BREAKER_OPEN
        delay_ms = retry.backoff_ms(self._opens, self.backoff_ms,
                                    self.seed, key=f"breaker:{self.key}")
        self._opens += 1
        self._probe_at = time.monotonic() + delay_ms / 1e3

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self.state, "failures": self._failures,
                    "opens": self._opens}


class ReplicaState:
    """One replica as the routing client sees it."""

    __slots__ = ("addr", "breaker", "stats", "draining", "last_probe",
                 "registered", "discovered", "incarnation")

    def __init__(self, addr: str, breaker: CircuitBreaker,
                 discovered: bool = False):
        self.addr = addr
        self.breaker = breaker
        #: latest serve.health ``serve_stats`` payload (None until probed)
        self.stats: Optional[Dict[str, Any]] = None
        self.draining = False
        self.last_probe = float("-inf")
        #: the replica PROCESS behind this address (its per-process
        #: transport executor id, carried in serve.health): when it
        #: changes, the address was taken over by a restarted process
        #: that has none of the old incarnation's temp views
        self.incarnation: Optional[str] = None
        #: table names successfully registered on THIS replica — the
        #: deferred re-register ledger: a replica that was down (or not
        #: yet discovered) during the broadcast gets the missing views
        #: replayed before the first submission routed to it
        self.registered: Set[str] = set()
        self.discovered = discovered

    @property
    def routable(self) -> bool:
        return self.breaker.allow_submit() and not self.draining


def routing_score(stats: Optional[Dict[str, Any]]) -> float:
    """Load-aware routing score over one replica's serve_stats snapshot
    (higher is better). Free device budget after footprint charges is
    the dominant term — a footprint-saturated replica scores near its
    floor while an idle one scores ~1.0 — with queue depth + running
    count and the window p99 wall as congestion penalties. A replica
    with no snapshot yet scores neutral (0.5): routable, but never
    preferred over a replica known to be free."""
    if not stats:
        return 0.5
    now = stats.get("now") or {}
    budget = now.get("device_budget_bytes") or 0
    in_use = now.get("device_budget_in_use") or 0
    free = 1.0 - min(1.0, in_use / budget) if budget else 0.5
    waiting = (now.get("admission_queue_depth") or 0) + sum(
        (now.get("running_by_tenant") or {}).values())
    p99 = stats.get("p99_wall_s") or 0.0
    return free - 0.5 * waiting - 0.05 * min(p99, 10.0)
