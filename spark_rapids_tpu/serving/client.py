"""Query service client: resilient routing front end + streaming handles.

One ``QueryServiceClient`` speaks to N server replicas (server.py) through
ONE shuffle-transport instance — each replica is a dialed peer of the
PR 2 TCP stack, addressed ``host:port``. Replicas come from explicit
addresses, from registry-dir discovery (``serving.net.registryDir``: the
shuffle rendezvous, heartbeat-mtime liveness, stale entries skipped and
garbage-collected), or both.

Routing is health-checked and load-aware (serving/health.py):

- every replica sits behind a **circuit breaker** — consecutive
  probe/submit/stream failures flip it OPEN, after which it receives
  ZERO submissions; only ``serve.health`` probes on the deterministic
  exponential-backoff schedule go there, and one success closes it;
- healthy replicas are scored by their latest ``serve.health`` snapshot
  (free device budget after footprint charges, queue depth, p99 wall)
  under ``serving.routing.policy=loadaware`` — the whale lands on the
  replica with free budget instead of round-robin roulette;
- a DRAINING replica (graceful drain in progress) is rerouted around
  transparently: its rejection is a retryable redirect, never a
  caller-visible error.

``RemoteQueryHandle.batches()`` streams partial results as the server
produces them. Fault handling mirrors the shuffle client: a checksum
mismatch on a result frame is a RETRYABLE fetch (deterministic backoff,
the parked server copy retransmits); a dead REPLICA mid-stream triggers
**failover with stream resume** for idempotent queries (the default for
pure SELECTs): the query is resubmitted to a healthy replica with
``resume_from=<last seq delivered>`` — the new replica re-runs and skips
already-delivered frames (dedup by seq), so ``collect()`` through a
mid-stream replica kill returns bit-identical results (float-agg
carve-out) with zero client-visible error. Non-idempotent or
failover-exhausted queries fail the handle with ``WireQueryError``
carrying ``batches_delivered`` — never a hang (every wait is bounded by
``serving.net.rpcTimeoutSeconds``).
"""
from __future__ import annotations

import itertools
import json
import threading
import time
import uuid
from typing import Dict, List, Optional

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.serving import wire
from spark_rapids_tpu.serving.health import (CircuitBreaker, ReplicaState,
                                             routing_score)
from spark_rapids_tpu.serving.lifecycle import (OverloadedError,
                                                QuotaExceededError)
from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.shuffle.codec import ChecksumError, verify_checksum
from spark_rapids_tpu.shuffle.tcp import scan_registry
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                TransactionStatus)
from spark_rapids_tpu.utils import metrics as um
from spark_rapids_tpu.utils.errors import (OpaqueWireError, absorb,
                                           decode_error, triage_boundary)


class WireQueryError(RuntimeError):
    """A wire query failed (server error, lost connection, exhausted
    retries). ``batches_delivered`` counts result batches that arrived
    intact before the failure — the partial-progress contract.
    ``retryable`` distinguishes replica/transport-level failures (the
    query can fail over to another replica) from query-level ones (the
    SQL itself failed; rerunning elsewhere would fail the same way)."""

    def __init__(self, message: str, batches_delivered: int = 0,
                 retryable: bool = False):
        super().__init__(message)
        self.batches_delivered = batches_delivered
        self.retryable = retryable


def _decode_wire_error(blob) -> BaseException:
    """Rebuild the server-side exception from a NEXT_ERROR payload (the
    utils/errors.py wire codec); anything undecodable — including frames
    from a pre-codec server — degrades to OpaqueWireError."""
    try:
        payload = json.loads(blob)
        if not isinstance(payload, dict):
            raise ValueError(blob)
    except (TypeError, ValueError):
        return OpaqueWireError(str(blob))
    return decode_error(payload)


def _is_draining_error(err: BaseException) -> bool:
    """A DRAINING rejection is a retryable redirect, not a replica
    failure.  Decoded wire errors carry their taxonomy code; errors that
    rode a transport error-message string (the submit path) fall back to
    the type name the server put on the wire."""
    code = getattr(err, "wire_code", None)
    if code is not None:
        return code == "SCHEDULER_DRAINING"
    return "DrainingError" in str(err)


class RemoteQueryHandle:
    """Client-side identity of one wire-submitted query (its server-side
    incarnation may move between replicas across failovers)."""

    def __init__(self, client: "QueryServiceClient", replica: str, conn,
                 query_id: int, label: str, sql: str = "",
                 tenant: str = "default", timeout: float = 0.0,
                 idempotent: bool = True):
        self._client = client
        self._conn = conn
        self.replica = replica
        self.query_id = query_id
        self.label = label
        self.sql = sql
        self.tenant = tenant
        self.timeout_s = timeout
        #: whether a replica death mid-stream may resubmit this query to
        #: another replica (stream-resume failover). Auto-detected for
        #: SQL submissions: pure SELECTs are idempotent by default.
        self.idempotent = idempotent
        self.batches_delivered = 0
        #: completed failovers: each is one resubmission to a new replica
        self.failovers = 0
        #: terminal per-query snapshot from the server's DONE frame
        #: (queue/admission waits, program-cache hits incl. disk_hits,
        #: stream/preemption counts — the QueryHandle.snapshot() keys)
        self.metrics: Dict = {}
        self._tables: List[pa.Table] = []
        self._schema_ipc: bytes = b""
        self._done = False
        self._consumed = False
        #: highest batch seq delivered intact — what a failover resumes
        #: from (the new replica skips frames with seq <= this)
        self._last_seq = -1
        self._ack = -1
        #: True once THIS handle asked the server to cancel — separates
        #: a requested cancellation (terminal) from a server-side
        #: peer-lost/shutdown cancellation (replica loss: may fail over)
        self._cancel_sent = False

    # ---- streaming ---------------------------------------------------------
    def batches(self):
        """Yield result batches as the server streams them (partial
        results: the first batch arrives before the final one exists).
        Batches are NOT retained client-side — streaming consumption is
        memory-bounded; use ``result()`` instead for the assembled table.
        Abandoning the iterator early cancels the server-side query so
        its producer, permits and buffers release promptly."""
        yield from self._drive(retain=False)

    def _drive(self, retain: bool):
        if self._consumed:
            raise RuntimeError("batches() already consumed")
        self._consumed = True
        try:
            while True:
                try:
                    yield from self._stream_once(retain)
                    return
                except WireQueryError as e:
                    # replica death mid-stream: fail over with stream
                    # resume (idempotent queries only) — otherwise the
                    # error surfaces with its batches_delivered count
                    if not self._maybe_failover(e):
                        raise
        finally:
            # abandoned mid-stream (early break / GeneratorExit / error):
            # cancel server-side so the producer, its device permit and
            # the parked frames release now, not at client disconnect
            if not self._done:
                try:
                    self.cancel()
                except WireQueryError as e:
                    # terminal absorption: a cancel that failed while the
                    # stream is already unwinding must not mask the
                    # primary failure — counted, not propagated
                    absorb(e, "serving.client.stream_abandon_cancel")

    def _stream_once(self, retain: bool):
        """Drive the stream against the CURRENT replica until DONE; a
        replica/transport failure raises a retryable WireQueryError the
        failover layer above may absorb."""
        while True:
            resp = self._client._rpc(
                self._conn, wire.REQ_NEXT,
                wire.NextRequest(self.query_id, self._ack).to_bytes(),
                delivered=self.batches_delivered)
            self._ack = -1
            nr = wire.NextResponse.from_bytes(resp)
            if nr.kind == wire.NEXT_WAIT:
                continue
            if nr.kind == wire.NEXT_DONE:
                self.metrics = json.loads(nr.metrics_json or b"{}")
                self._schema_ipc = nr.schema_ipc
                self._done = True
                return
            if nr.kind == wire.NEXT_ERROR:
                # the QUERY failed server-side — rerunning it on another
                # replica would fail identically, so not retryable; the
                # decoded cause's taxonomy code rides along so callers
                # can classify (cancellation vs permanent) without
                # string-sniffing. One carve-out: a cancellation THIS
                # handle never requested is the replica's peer-lost /
                # shutdown cleanup racing the stream (the socket lived
                # just long enough to deliver the error) — that is
                # replica loss, not query failure, so it may fail over
                decoded = _decode_wire_error(nr.error)
                code = getattr(decoded, "wire_code", "OPAQUE")
                err = WireQueryError(
                    str(decoded), self.batches_delivered,
                    retryable=(code == "QUERY_CANCELLED"
                               and not self._cancel_sent))
                err.wire_code = code
                raise err
            table = self._fetch(nr)
            self.batches_delivered += 1
            self._last_seq = nr.seq
            self._ack = nr.seq
            if retain:
                self._tables.append(table)
            yield table

    @triage_boundary
    def _maybe_failover(self, err: WireQueryError) -> bool:
        """Resubmit to a healthy replica with ``resume_from=last seq
        delivered``; True when the stream may continue on a new conn,
        False when the original error should surface. Raises the
        structured rejection instead when the resubmission was shed or
        quota-bounced (retryable with a hint — not a dead fleet)."""
        c = self._client
        if not (err.retryable and self.idempotent and c.failover_enabled):
            return False
        if self.failovers >= c.failover_max_attempts:
            return False
        failed = self.replica
        st = c._replica_state(failed)
        if st is not None and not _is_draining_error(err):
            c._note_replica_failure(st)
        try:
            addr, conn, qid = c._submit_routed(
                self.sql, self.tenant, self.timeout_s, self.label,
                resume_from=self._last_seq, exclude={failed})
        except (OverloadedError, QuotaExceededError):
            # the failover resubmission was rejected at the front door:
            # the fleet is alive, just saturated (or the caller's quota
            # is burned). Surface the structured retryable rejection
            # WITH its retry-after hint — not the stale stream error —
            # so a displaced query rides the caller's normal overload
            # retry loop like any other resubmission
            raise
        except WireQueryError:
            # no healthy replica took it: surface the ORIGINAL stream
            # error with its batches_delivered count
            return False
        self.failovers += 1
        um.SERVING_METRICS[um.SERVING_FAILOVERS].add(1)
        self.replica, self._conn, self.query_id = addr, conn, qid
        self._ack = -1
        return True

    def _fetch(self, nr: wire.NextResponse) -> pa.Table:
        """Pull one parked frame: post a receive on a fresh tag, ask the
        server to push, verify the crc32. Corruption retries with the
        shuffle stack's deterministic backoff — the server retransmits
        its parked copy."""
        c = self._client
        last_err = "fetch failed"
        for attempt in range(c.max_retries + 1):
            tag = next(c._tags)
            buf = bytearray(nr.nbytes)
            rtx = self._conn.receive(
                AddressLengthTag(buf, nr.nbytes, tag), lambda tx: None)
            try:
                c._rpc(self._conn, wire.REQ_FETCH,
                       wire.FetchRequest(self.query_id, nr.seq,
                                         tag).to_bytes(),
                       delivered=self.batches_delivered)
                rtx.wait(c.rpc_timeout)
            except TimeoutError:
                # abandon the posted receive so the stale tag neither pins
                # its frame-sized buffer nor swallows a late retransmit
                self._cancel_receive(tag)
                last_err = (f"result frame seq {nr.seq} timed out after "
                            f"{c.rpc_timeout}s")
                self._backoff(attempt, nr.seq)
                continue
            except WireQueryError:
                self._cancel_receive(tag)
                raise
            if rtx.status is not TransactionStatus.SUCCESS:
                raise WireQueryError(
                    f"result stream lost at seq {nr.seq}: "
                    f"{rtx.error_message}", self.batches_delivered,
                    retryable=True)
            data = bytes(buf[:nr.nbytes])
            try:
                verify_checksum(data, nr.checksum,
                                context=f"query {self.query_id} "
                                        f"seq {nr.seq}")
            except ChecksumError as e:
                last_err = str(e)
                um.SERVING_METRICS[um.SERVING_WIRE_RETRIES].add(1)
                self._cancel_receive(tag)       # drop a straggling dup too
                self._backoff(attempt, nr.seq)
                continue
            # purge any duplicate frame (dup_frame chaos) that already
            # landed for this tag — it would otherwise park in the
            # transport's early-data table until the cap evicts it
            self._cancel_receive(tag)
            return wire.ipc_to_table(data)
        raise WireQueryError(
            f"{last_err} ({c.max_retries + 1} attempts)",
            self.batches_delivered, retryable=True)

    def _cancel_receive(self, tag: int) -> None:
        cancel = getattr(self._conn, "cancel_receive", None)
        if cancel is not None:
            cancel(tag)

    def _backoff(self, attempt: int, seq: int) -> None:
        time.sleep(retry.backoff_ms(
            attempt, self._client.backoff_ms, self._client.retry_seed,
            key=f"serve-fetch:{self.query_id}:{seq}") / 1e3)

    # ---- terminal results --------------------------------------------------
    def result(self) -> pa.Table:
        """Drain the stream and assemble the full table — bit-identical
        to the in-process ``collect()`` (float-agg carve-out per the
        documented contract), including through a mid-stream replica
        failover. A stream consumed via ``batches()`` was deliberately
        not retained; assemble it caller-side instead."""
        if not self._done:
            if self._consumed:
                raise RuntimeError(
                    "stream partially consumed; drain batches() first")
            for _ in self._drive(retain=True):
                pass
        if self._tables:
            return pa.concat_tables(self._tables)
        if self.batches_delivered:
            raise RuntimeError(
                "stream was consumed via batches() (not retained); "
                "assemble the batches caller-side or re-submit")
        return wire.ipc_to_table(self._schema_ipc)

    def cancel(self) -> None:
        self._cancel_sent = True
        self._client._rpc(self._conn, wire.REQ_CANCEL,
                          wire.CancelRequest(self.query_id).to_bytes(),
                          delivered=self.batches_delivered)


class QueryServiceClient:
    """Front end over N replicas: explicit ``["host:port", ...]``
    addresses, registry-dir discovery, or both."""

    def __init__(self, addresses=None, conf=None,
                 registry_dir: Optional[str] = None):
        from spark_rapids_tpu.config import TpuConf
        self.conf = conf or TpuConf()
        if isinstance(addresses, str):
            addresses = [a.strip() for a in addresses.split(",") if a.strip()]
        self.addresses = list(addresses or [])
        self.registry_dir = (registry_dir if registry_dir is not None
                             else self.conf.get(cfg.SERVING_NET_REGISTRY))
        if not self.addresses and not self.registry_dir:
            raise ValueError(
                "QueryServiceClient needs >= 1 server address or a "
                "registry dir (serving.net.registryDir) to discover from")
        self.rpc_timeout = self.conf.get(cfg.SERVING_NET_RPC_TIMEOUT)
        self.max_retries = self.conf.shuffle_max_retries
        self.backoff_ms = self.conf.shuffle_retry_backoff_ms
        self.retry_seed = self.conf.get(cfg.SERVING_NET_FAULTS_SEED)
        self.failover_enabled = self.conf.get(cfg.SERVING_FAILOVER_ENABLED)
        self.failover_max_attempts = self.conf.get(
            cfg.SERVING_FAILOVER_MAX_ATTEMPTS)
        #: extra rotation passes when EVERY replica shed the submission
        #: (OverloadedError): each pass honors the shed retry-after hint,
        #: floored by the deterministic backoff for that attempt
        self.overload_retries = self.conf.get(
            cfg.SERVING_OVERLOAD_CLIENT_RETRIES)
        self.routing_policy = self.conf.get(cfg.SERVING_ROUTING_POLICY)
        self.probe_interval = self.conf.get(cfg.SERVING_HEALTH_PROBE_INTERVAL)
        self.probe_timeout = self.conf.get(cfg.SERVING_HEALTH_PROBE_TIMEOUT)
        self.liveness_window = self.conf.get(
            cfg.SERVING_HEALTH_LIVENESS_WINDOW)
        self._breaker_threshold = self.conf.get(cfg.SERVING_BREAKER_THRESHOLD)
        self._breaker_backoff_ms = self.conf.get(
            cfg.SERVING_BREAKER_BACKOFF_MS)
        # the client never passes a registry dir to its OWN transport —
        # publishing would list the client as a replica
        self._transport = wire.make_serving_transport(
            f"serve-client-{uuid.uuid4().hex[:8]}", self.conf, listen_port=0)
        self._lock = threading.Lock()
        #: addr -> ReplicaState (breaker, latest health snapshot,
        #: deferred-registration ledger); insertion order is the
        #: round-robin rotation
        self._replicas: "Dict[str, ReplicaState]" = {}
        #: registered temp views by name -> wire RegisterRequest bytes,
        #: replayed onto replicas that were down (or undiscovered) at
        #: broadcast time before the first submission routed to them
        self._registered: "Dict[str, bytes]" = {}
        self._last_scan = float("-inf")
        self._rr = itertools.count()
        #: client-chosen receive tags, unique across queries and retries
        self._tags = itertools.count(1 << 32)
        for addr in self.addresses:
            self._add_replica(addr, discovered=False)
        self._refresh_replicas(force=True)

    # ---- replica table -----------------------------------------------------
    def _add_replica(self, addr: str, discovered: bool) -> ReplicaState:
        st = ReplicaState(
            addr, CircuitBreaker(self._breaker_threshold,
                                 self._breaker_backoff_ms,
                                 seed=self.retry_seed, key=addr),
            discovered=discovered)
        self._replicas[addr] = st
        if addr not in self.addresses:
            self.addresses.append(addr)     # stable pin table
        return st

    def _replica_state(self, addr: str) -> Optional[ReplicaState]:
        with self._lock:
            return self._replicas.get(addr)

    def _refresh_replicas(self, force: bool = False) -> None:
        """Re-scan the registry dir (liveness-windowed: stale entries are
        skipped and garbage-collected) and fold the live set into the
        replica table — new replicas join the rotation, discovered ones
        whose entry aged out leave it."""
        if not self.registry_dir:
            return
        now = time.monotonic()
        with self._lock:
            # check-and-set under the lock: two submitting threads must
            # not both decide the scan is due and double-scan (R012)
            if not force and now - self._last_scan < self.probe_interval:
                return
            self._last_scan = now
        try:
            live = scan_registry(self.registry_dir,
                                 stale_after_s=self.liveness_window)
        except OSError:
            return      # registry unreadable RIGHT NOW (transient FS
            # hiccup) — keep the previous view; an empty fleet is only
            # believed when the scan actually succeeded
        addrs = set(live.values())
        with self._lock:
            for addr in sorted(addrs):
                if addr not in self._replicas:
                    self._add_replica(addr, discovered=True)
            for addr, st in list(self._replicas.items()):
                if st.discovered and addr not in addrs:
                    del self._replicas[addr]    # heartbeat stopped: dead

    def replica_states(self) -> List[ReplicaState]:
        with self._lock:
            return list(self._replicas.values())

    # ---- plumbing ----------------------------------------------------------
    def _connection(self, addr: str):
        # the transport caches live connections and EVICTS dead ones
        # (peer-lost handling in tcp.py / the fault wrapper), so asking it
        # each time re-dials a dropped replica; a second cache here would
        # pin a dead socket past its eviction
        return self._transport.connect(addr)

    def _rpc(self, conn, req_type: str, payload: bytes,
             delivered: int = 0, timeout: Optional[float] = None) -> bytes:
        tx = conn.request(req_type, payload, lambda t: None)
        try:
            tx.wait(timeout if timeout is not None else self.rpc_timeout)
        except TimeoutError:
            raise WireQueryError(
                f"{req_type} timed out after {self.rpc_timeout}s",
                delivered, retryable=True) from None
        if tx.status is not TransactionStatus.SUCCESS:
            raise WireQueryError(
                f"{req_type} failed: {tx.error_message}", delivered,
                retryable=True)
        return tx.response

    # ---- health + routing --------------------------------------------------
    @triage_boundary
    def _note_replica_failure(self, st: ReplicaState) -> None:
        """Feed one failure to the replica's breaker; a breaker that just
        OPENED declares the replica dead, so its registration ledger is
        reset — a NEW process behind the same address (restart) has none
        of the old incarnation's temp views and must get them replayed.
        The ledger is a plain set shared by every submitting thread, so
        every mutation takes the client lock (R012)."""
        st.breaker.record_failure()
        if not st.breaker.allow_submit():
            with self._lock:
                st.registered.clear()

    def _probe(self, st: ReplicaState) -> bool:
        """One serve.health probe: refresh the replica's stats/DRAINING
        flag and feed its breaker. Failures are breaker failures."""
        st.last_probe = time.monotonic()
        try:
            payload = self._rpc(self._connection(st.addr), wire.REQ_HEALTH,
                                b"", timeout=self.probe_timeout)
            doc = json.loads(payload)
        except (WireQueryError, ConnectionError, OSError, ValueError):
            st.stats = None
            self._note_replica_failure(st)
            return False
        st.stats = doc.get("serve_stats") or {}
        st.draining = doc.get("state") == "DRAINING"
        incarnation = doc.get("replica_id")
        if incarnation:
            if st.incarnation is not None and st.incarnation != incarnation:
                # a DIFFERENT process answered on this address (restart
                # faster than the breaker threshold could notice): it has
                # none of the old incarnation's temp views — replay them
                with self._lock:
                    st.registered.clear()
            st.incarnation = incarnation
        st.breaker.record_success()
        return True

    def _pick(self, exclude) -> str:
        """Choose the replica for one new submission: probe what's due,
        drop OPEN-breaker and DRAINING replicas, then score the healthy
        set (loadaware) or rotate (roundrobin)."""
        self._refresh_replicas()
        with self._lock:
            states = [s for a, s in self._replicas.items()
                      if a not in exclude]
        if not states:
            raise WireQueryError("no replicas known (every address "
                                 "excluded or discovery found none)")
        now = time.monotonic()
        probed_dead = set()
        for st in states:
            if st.breaker.allow_submit():
                if now - st.last_probe >= self.probe_interval:
                    if not self._probe(st):
                        # the probe JUST failed: even if the breaker is
                        # still CLOSED (under threshold), don't route a
                        # submission into the failed dial we predicted
                        # milliseconds ago
                        probed_dead.add(st.addr)
            elif st.breaker.probe_due(now):
                # OPEN breaker past its backoff: ONE health-probe trial —
                # submissions never route here until a probe succeeds
                self._probe(st)
        candidates = [s for s in states
                      if s.routable and s.addr not in probed_dead]
        if not candidates:
            raise WireQueryError(
                f"no healthy replica ({len(states)} known: all behind an "
                f"OPEN breaker or DRAINING)")
        if self.routing_policy == "loadaware":
            scores = [routing_score(s.stats) for s in candidates]
            best = max(scores)
            tied = [s for s, sc in zip(candidates, scores)
                    if sc >= best - 1e-9]
        else:
            tied = candidates
        return tied[next(self._rr) % len(tied)].addr

    def _route(self, replica: Optional[int]) -> str:
        """Pinned routing (tests / per-replica introspection): index into
        the stable pin table, bypassing health checks."""
        if replica is not None:
            with self._lock:
                # discovery appends to the pin table concurrently (R012)
                addresses = list(self.addresses)
            return addresses[replica % len(addresses)]
        return self._pick(exclude=())

    def _ensure_registered(self, st: ReplicaState, conn) -> None:
        """Replay any temp-view registrations this replica missed (it was
        down, DRAINING, or undiscovered during the broadcast) before
        routing a submission to it — the deferred re-register contract."""
        with self._lock:
            missing = [(n, req) for n, req in self._registered.items()
                       if n not in st.registered]
        for name, req in missing:
            # the RPC stays OUTSIDE the lock (R006); only the ledger
            # mutation itself takes it (R012)
            self._rpc(conn, wire.REQ_REGISTER, req)
            with self._lock:
                st.registered.add(name)

    # ---- API ---------------------------------------------------------------
    @staticmethod
    def _sql_idempotent(sql: str) -> bool:
        """Pure reads are safe to re-run on another replica; anything
        else must opt in explicitly via ``submit(idempotent=True)``."""
        head = sql.lstrip().lstrip("(").lstrip().lower()
        return head.startswith(("select", "with", "values", "show",
                                "describe", "explain"))

    def _submit_routed(self, sql: str, tenant: str, timeout: float,
                       label: str, resume_from: int = -1,
                       replica: Optional[int] = None, exclude=()):
        """Route one submission, rerouting around dead and DRAINING
        replicas; returns ``(addr, conn, query_id)``. Pinned submissions
        (``replica=``) never reroute — tests rely on the pin being
        absolute. When EVERY routable replica SHEDS the submission
        (structured OverloadedError), the rotation retries up to
        ``serving.overload.clientRetries`` more passes, sleeping the shed
        retry-after hint (floored by the deterministic backoff for the
        attempt) between passes, then surfaces the OverloadedError."""
        req = wire.SubmitRequest(sql, tenant, timeout, label,
                                 resume_from).to_bytes()
        shed: Optional[OverloadedError] = None
        for attempt in range(self.overload_retries + 1):
            if shed is not None:
                # every replica shed last pass: honor the server's hint —
                # the whole point of retry-after is that the SERVER knows
                # its drain rate — but never sleep less than the seeded
                # backoff schedule for this attempt (thundering-herd
                # hygiene when many clients got the same hint)
                hint = getattr(shed, "retry_after_s", 0.0) or 0.0
                floor_s = retry.backoff_ms(
                    attempt - 1, self.backoff_ms, self.retry_seed,
                    key=f"serve-overload:{label or sql[:48]}") / 1e3
                time.sleep(max(hint, floor_s))
            try:
                return self._submit_pass(req, replica, set(exclude))
            except OverloadedError as e:
                shed = e
                if replica is not None:
                    raise               # pinned: the pin is the contract
        raise shed

    def _submit_pass(self, req: bytes, replica: Optional[int], exclude):
        """One rotation pass over the routable replicas. Raises
        OverloadedError when nobody accepted and at least one replica
        shed (the caller's retry-after loop owns that — a shed is a live
        replica that will take the query later); QuotaExceededError
        surfaces immediately — the quota is per CLIENT, so shopping the
        submission to another replica just burns its quota there too."""
        with self._lock:
            bound = len(self._replicas) + 1
        last_err: Optional[WireQueryError] = None
        last_shed: Optional[OverloadedError] = None
        for _ in range(max(2, bound)):
            if replica is not None:
                addr = self._route(replica)
            else:
                try:
                    addr = self._pick(exclude)
                except WireQueryError:
                    # routing exhausted: a shed outranks everything — it
                    # proves a LIVE replica that will take the query
                    # later, and it carries the actionable retry-after
                    # hint; a dial/submission error outranks only the
                    # generic no-replica error
                    if last_shed is not None:
                        raise last_shed
                    if last_err is not None:
                        raise last_err
                    raise
            st = self._replica_state(addr)
            try:
                conn = self._connection(addr)
                if st is not None:
                    self._ensure_registered(st, conn)
                resp = wire.SubmitResponse.from_bytes(
                    self._rpc(conn, wire.REQ_SUBMIT, req))
            except (WireQueryError, ConnectionError, OSError) as e:
                err = (e if isinstance(e, WireQueryError)
                       else WireQueryError(str(e), retryable=True))
                if replica is not None:
                    raise err           # pinned: the pin is the contract
                if st is not None:
                    if _is_draining_error(err):
                        # retryable redirect: the replica is healthy but
                        # leaving — reroute without a breaker failure
                        st.draining = True
                    else:
                        self._note_replica_failure(st)
                exclude.add(addr)
                last_err = err
                continue
            if st is not None:
                # any structured answer — accept, shed or quota — is a
                # LIVE replica: the breaker tracks reachability, not load
                st.breaker.record_success()
            if resp.error_json:
                decoded = _decode_wire_error(resp.error_json)
                if isinstance(decoded, QuotaExceededError):
                    raise decoded
                if isinstance(decoded, OverloadedError):
                    if replica is not None:
                        raise decoded
                    exclude.add(addr)
                    last_shed = decoded
                    continue
                raise decoded           # unknown structured rejection
            return addr, conn, resp.query_id
        # a shed outranks a dead-replica error: mixed passes (one replica
        # down, another at its bound) surface the structured retryable
        # signal with its hint, not the opaque dial failure
        if last_shed is not None:
            raise last_shed
        raise last_err or WireQueryError(
            "no replica accepted the submission")

    def submit(self, sql: str, tenant: str = "default",
               timeout: float = 0.0, label: str = "",
               replica: Optional[int] = None,
               idempotent: Optional[bool] = None) -> RemoteQueryHandle:
        """Submit SQL to one replica (health-checked load-aware routing
        unless pinned); returns a streaming handle immediately.
        ``idempotent=None`` auto-detects (pure SELECTs may fail over with
        stream resume; anything else fails the handle on replica death)."""
        if idempotent is None:
            idempotent = self._sql_idempotent(sql)
        addr, conn, query_id = self._submit_routed(
            sql, tenant, timeout, label, replica=replica)
        return RemoteQueryHandle(self, addr, conn, query_id, label,
                                 sql=sql, tenant=tenant, timeout=timeout,
                                 idempotent=idempotent)

    def register_table(self, name: str, table: pa.Table) -> None:
        """Register ``table`` as a temp view on EVERY reachable replica.
        A down replica does NOT brick the client: its registration is
        deferred and replayed on the first successful route to it (see
        ``_ensure_registered``); only zero reachable replicas raise."""
        data = wire.table_to_ipc(table)
        req = wire.RegisterRequest(name, data).to_bytes()
        self._refresh_replicas()
        with self._lock:
            self._registered[name] = req
            states = list(self._replicas.values())
        delivered = 0
        errors: List[str] = []
        for st in states:
            try:
                self._rpc(self._connection(st.addr), wire.REQ_REGISTER, req)
            except (WireQueryError, ConnectionError, OSError) as e:
                self._note_replica_failure(st)
                errors.append(f"{st.addr}: {e}")
                continue
            with self._lock:
                st.registered.add(name)
            st.breaker.record_success()
            delivered += 1
        if states and not delivered:
            raise WireQueryError(
                f"register_table {name!r} reached no replica: "
                f"{'; '.join(errors)}", retryable=True)

    def drain_replica(self, replica: int = 0) -> Dict:
        """Ask one replica to drain gracefully (running queries finish,
        new submissions reroute); returns the server's drain ack."""
        addr = self._route(replica)
        out = json.loads(self._rpc(self._connection(addr),
                                   wire.REQ_DRAIN, b""))
        st = self._replica_state(addr)
        if st is not None:
            st.draining = True
        return out

    def health(self, replica: int = 0) -> Dict:
        """One replica's serve.health payload (state + serve_stats)."""
        addr = self._route(replica)
        return json.loads(self._rpc(self._connection(addr),
                                    wire.REQ_HEALTH, b"",
                                    timeout=self.probe_timeout))

    def stats(self, replica: int = 0) -> Dict:
        """One replica's scheduler/program-cache/serving counters (the
        warm-start probe reads disk_hits here)."""
        addr = self._route(replica)
        return json.loads(self._rpc(self._connection(addr),
                                    wire.REQ_STATS, b""))

    def close(self) -> None:
        self._transport.shutdown()
