"""Query service client: connection-routing front end + streaming handles.

One ``QueryServiceClient`` speaks to N server replicas (server.py) through
ONE shuffle-transport instance — each replica is just a dialed peer of the
PR 2 TCP stack, addressed ``host:port`` (no registry). Submissions route
round-robin across replicas (the connection-routing front end: replicas
share the on-disk program-cache index, so any of them serves any shape
warm); ``register_table`` broadcasts to every replica so the catalog is
identical behind the router.

``RemoteQueryHandle.batches()`` streams partial results as the server
produces them — batch 1 arrives while the query is still RUNNING. Fault
handling mirrors the shuffle client: a checksum mismatch on a result
frame is a RETRYABLE fetch (deterministic backoff, the parked server copy
retransmits); a dropped connection or exhausted retries fails the handle
with ``WireQueryError`` carrying ``batches_delivered`` — never a hang
(every wait is bounded by ``serving.net.rpcTimeoutSeconds``).
"""
from __future__ import annotations

import itertools
import json
import time
import uuid
from typing import Dict, List, Optional

import pyarrow as pa

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.serving import wire
from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.shuffle.codec import ChecksumError, verify_checksum
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                TransactionStatus)
from spark_rapids_tpu.utils import metrics as um


class WireQueryError(RuntimeError):
    """A wire query failed (server error, lost connection, exhausted
    retries). ``batches_delivered`` counts result batches that arrived
    intact before the failure — the partial-progress contract."""

    def __init__(self, message: str, batches_delivered: int = 0):
        super().__init__(message)
        self.batches_delivered = batches_delivered


class RemoteQueryHandle:
    """Client-side identity of one wire-submitted query."""

    def __init__(self, client: "QueryServiceClient", replica: str, conn,
                 query_id: int, label: str):
        self._client = client
        self._conn = conn
        self.replica = replica
        self.query_id = query_id
        self.label = label
        self.batches_delivered = 0
        #: terminal per-query snapshot from the server's DONE frame
        #: (queue/admission waits, program-cache hits incl. disk_hits,
        #: stream/preemption counts — the QueryHandle.snapshot() keys)
        self.metrics: Dict = {}
        self._tables: List[pa.Table] = []
        self._schema_ipc: bytes = b""
        self._done = False
        self._consumed = False

    # ---- streaming ---------------------------------------------------------
    def batches(self):
        """Yield result batches as the server streams them (partial
        results: the first batch arrives before the final one exists).
        Batches are NOT retained client-side — streaming consumption is
        memory-bounded; use ``result()`` instead for the assembled table.
        Abandoning the iterator early cancels the server-side query so
        its producer, permits and buffers release promptly."""
        yield from self._drive(retain=False)

    def _drive(self, retain: bool):
        if self._consumed:
            raise RuntimeError("batches() already consumed")
        self._consumed = True
        ack = -1
        try:
            while True:
                resp = self._client._rpc(
                    self._conn, wire.REQ_NEXT,
                    wire.NextRequest(self.query_id, ack).to_bytes(),
                    delivered=self.batches_delivered)
                ack = -1
                nr = wire.NextResponse.from_bytes(resp)
                if nr.kind == wire.NEXT_WAIT:
                    continue
                if nr.kind == wire.NEXT_DONE:
                    self.metrics = json.loads(nr.metrics_json or b"{}")
                    self._schema_ipc = nr.schema_ipc
                    self._done = True
                    return
                if nr.kind == wire.NEXT_ERROR:
                    raise WireQueryError(nr.error, self.batches_delivered)
                table = self._fetch(nr)
                self.batches_delivered += 1
                ack = nr.seq
                if retain:
                    self._tables.append(table)
                yield table
        finally:
            # abandoned mid-stream (early break / GeneratorExit / error):
            # cancel server-side so the producer, its device permit and
            # the parked frames release now, not at client disconnect
            if not self._done:
                try:
                    self.cancel()
                except WireQueryError:
                    pass

    def _fetch(self, nr: wire.NextResponse) -> pa.Table:
        """Pull one parked frame: post a receive on a fresh tag, ask the
        server to push, verify the crc32. Corruption retries with the
        shuffle stack's deterministic backoff — the server retransmits
        its parked copy."""
        c = self._client
        last_err = "fetch failed"
        for attempt in range(c.max_retries + 1):
            tag = next(c._tags)
            buf = bytearray(nr.nbytes)
            rtx = self._conn.receive(
                AddressLengthTag(buf, nr.nbytes, tag), lambda tx: None)
            try:
                c._rpc(self._conn, wire.REQ_FETCH,
                       wire.FetchRequest(self.query_id, nr.seq,
                                         tag).to_bytes(),
                       delivered=self.batches_delivered)
                rtx.wait(c.rpc_timeout)
            except TimeoutError:
                # abandon the posted receive so the stale tag neither pins
                # its frame-sized buffer nor swallows a late retransmit
                self._cancel_receive(tag)
                last_err = (f"result frame seq {nr.seq} timed out after "
                            f"{c.rpc_timeout}s")
                self._backoff(attempt, nr.seq)
                continue
            except WireQueryError:
                self._cancel_receive(tag)
                raise
            if rtx.status is not TransactionStatus.SUCCESS:
                raise WireQueryError(
                    f"result stream lost at seq {nr.seq}: "
                    f"{rtx.error_message}", self.batches_delivered)
            data = bytes(buf[:nr.nbytes])
            try:
                verify_checksum(data, nr.checksum,
                                context=f"query {self.query_id} "
                                        f"seq {nr.seq}")
            except ChecksumError as e:
                last_err = str(e)
                um.SERVING_METRICS[um.SERVING_WIRE_RETRIES].add(1)
                self._cancel_receive(tag)       # drop a straggling dup too
                self._backoff(attempt, nr.seq)
                continue
            # purge any duplicate frame (dup_frame chaos) that already
            # landed for this tag — it would otherwise park in the
            # transport's early-data table until the cap evicts it
            self._cancel_receive(tag)
            return wire.ipc_to_table(data)
        raise WireQueryError(
            f"{last_err} ({c.max_retries + 1} attempts)",
            self.batches_delivered)

    def _cancel_receive(self, tag: int) -> None:
        cancel = getattr(self._conn, "cancel_receive", None)
        if cancel is not None:
            cancel(tag)

    def _backoff(self, attempt: int, seq: int) -> None:
        time.sleep(retry.backoff_ms(
            attempt, self._client.backoff_ms, self._client.retry_seed,
            key=f"serve-fetch:{self.query_id}:{seq}") / 1e3)

    # ---- terminal results --------------------------------------------------
    def result(self) -> pa.Table:
        """Drain the stream and assemble the full table — bit-identical
        to the in-process ``collect()`` (float-agg carve-out per the
        documented contract). A stream consumed via ``batches()`` was
        deliberately not retained; assemble it caller-side instead."""
        if not self._done:
            if self._consumed:
                raise RuntimeError(
                    "stream partially consumed; drain batches() first")
            for _ in self._drive(retain=True):
                pass
        if self._tables:
            return pa.concat_tables(self._tables)
        if self.batches_delivered:
            raise RuntimeError(
                "stream was consumed via batches() (not retained); "
                "assemble the batches caller-side or re-submit")
        return wire.ipc_to_table(self._schema_ipc)

    def cancel(self) -> None:
        self._client._rpc(self._conn, wire.REQ_CANCEL,
                          wire.CancelRequest(self.query_id).to_bytes(),
                          delivered=self.batches_delivered)


class QueryServiceClient:
    """Front end over N replica addresses (``["host:port", ...]``)."""

    def __init__(self, addresses, conf=None):
        from spark_rapids_tpu.config import TpuConf
        self.conf = conf or TpuConf()
        if isinstance(addresses, str):
            addresses = [a.strip() for a in addresses.split(",") if a.strip()]
        if not addresses:
            raise ValueError("QueryServiceClient needs >= 1 server address")
        self.addresses = list(addresses)
        self.rpc_timeout = self.conf.get(cfg.SERVING_NET_RPC_TIMEOUT)
        self.max_retries = self.conf.shuffle_max_retries
        self.backoff_ms = self.conf.shuffle_retry_backoff_ms
        self.retry_seed = self.conf.get(cfg.SERVING_NET_FAULTS_SEED)
        self._transport = wire.make_serving_transport(
            f"serve-client-{uuid.uuid4().hex[:8]}", self.conf, listen_port=0)
        self._rr = itertools.count()
        #: client-chosen receive tags, unique across queries and retries
        self._tags = itertools.count(1 << 32)

    # ---- plumbing ----------------------------------------------------------
    def _connection(self, addr: str):
        # the transport caches live connections and EVICTS dead ones
        # (peer-lost handling in tcp.py / the fault wrapper), so asking it
        # each time re-dials a dropped replica; a second cache here would
        # pin a dead socket past its eviction
        return self._transport.connect(addr)

    def _rpc(self, conn, req_type: str, payload: bytes,
             delivered: int = 0) -> bytes:
        tx = conn.request(req_type, payload, lambda t: None)
        try:
            tx.wait(self.rpc_timeout)
        except TimeoutError:
            raise WireQueryError(
                f"{req_type} timed out after {self.rpc_timeout}s",
                delivered) from None
        if tx.status is not TransactionStatus.SUCCESS:
            raise WireQueryError(
                f"{req_type} failed: {tx.error_message}", delivered)
        return tx.response

    def _route(self, replica: Optional[int]) -> str:
        if replica is not None:
            return self.addresses[replica % len(self.addresses)]
        return self.addresses[next(self._rr) % len(self.addresses)]

    # ---- API ---------------------------------------------------------------
    def submit(self, sql: str, tenant: str = "default",
               timeout: float = 0.0, label: str = "",
               replica: Optional[int] = None) -> RemoteQueryHandle:
        """Submit SQL to one replica (round-robin unless pinned); returns
        a streaming handle immediately."""
        addr = self._route(replica)
        conn = self._connection(addr)
        resp = wire.SubmitResponse.from_bytes(self._rpc(
            conn, wire.REQ_SUBMIT,
            wire.SubmitRequest(sql, tenant, timeout, label).to_bytes()))
        return RemoteQueryHandle(self, addr, conn, resp.query_id, label)

    def register_table(self, name: str, table: pa.Table) -> None:
        """Register ``table`` as a temp view on EVERY replica, so routed
        submissions see one catalog."""
        data = wire.table_to_ipc(table)
        req = wire.RegisterRequest(name, data).to_bytes()
        for addr in self.addresses:
            self._rpc(self._connection(addr), wire.REQ_REGISTER, req)

    def stats(self, replica: int = 0) -> Dict:
        """One replica's scheduler/program-cache/serving counters (the
        warm-start probe reads disk_hits here)."""
        addr = self._route(replica)
        return json.loads(self._rpc(self._connection(addr),
                                    wire.REQ_STATS, b""))

    def close(self) -> None:
        self._transport.shutdown()
