"""serve.stats: a rolling per-replica time-series of serving gauges.

ROADMAP item 4's load-aware replica routing is blocked on exactly this
feed: a router cannot send the whale to the replica with free budget, or
route interactive queries away from a saturated replica, on the strength
of a point-in-time counter snapshot. ``ServeStatsWindow`` keeps a bounded
rolling window (``serving.stats.windowSeconds``) of:

- **query wall samples** — recorded at every terminal transition; p50/p99
  over the window is the replica's observed latency profile;
- **gauge samples** — device budget in use (footprint-admission charged
  bytes + the device store's resident bytes against the budget), admission
  queue depth, running/queued counts per tenant. A sample is appended at
  every query completion and on every ``serve.stats`` request, so the
  series is dense while traffic flows and costs nothing while idle.

The wire surface: ``QueryServiceClient.stats()`` returns the scheduler
snapshot plus this window under ``"serve_stats"`` — ``now`` (the freshest
sample), ``series`` (the rolling samples, oldest first), and the window's
p50/p99 wall. Everything is computed server-side; the client ships JSON.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.utils.metrics import percentile

#: hard bounds independent of the time window, so a burst cannot grow the
#: deques without limit between trims
_MAX_WALL_SAMPLES = 2048
_MAX_GAUGE_SAMPLES = 512


class ServeStatsWindow:
    """Rolling window of one replica's serving gauges + wall samples."""

    def __init__(self, window_s: float = 300.0):
        self.window_s = max(1.0, float(window_s))
        self._lock = threading.Lock()
        #: (monotonic_t, wall_s) of terminal queries
        self._walls: deque = deque(maxlen=_MAX_WALL_SAMPLES)
        #: gauge sample dicts (see _sample_locked)
        self._samples: deque = deque(maxlen=_MAX_GAUGE_SAMPLES)
        #: monotonic time of the newest appended sample — snapshot() stamps
        #: its age so consumers (the autoscaler above all) can tell a fresh
        #: series from one that flat-lined when the replica wedged
        self._last_sample_t: Optional[float] = None

    # ---- producers ---------------------------------------------------------
    def record_wall(self, wall_s: Optional[float]) -> None:
        if wall_s is None:
            return
        with self._lock:
            self._walls.append((time.monotonic(), float(wall_s)))

    def sample(self, scheduler) -> Dict[str, Any]:
        """Take one gauge sample from the live scheduler state, append it
        to the series, and return it."""
        gauges = self._gauges(scheduler)
        with self._lock:
            self._trim_locked()
            self._samples.append(gauges)
            self._last_sample_t = gauges["t"]
        return gauges

    def age_s(self) -> Optional[float]:
        """Seconds since the newest sample was appended; None before the
        first sample. This is the staleness signal: the periodic sampler
        tick keeps it near the tick interval on a healthy replica, so a
        large age means the sampler (and likely the replica) is wedged."""
        with self._lock:
            if self._last_sample_t is None:
                return None
            return max(0.0, time.monotonic() - self._last_sample_t)

    # ---- gauge collection --------------------------------------------------
    @staticmethod
    def _gauges(scheduler) -> Dict[str, Any]:
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        from spark_rapids_tpu.plan.footprint import device_budget_estimate
        from spark_rapids_tpu.serving.lifecycle import QueryState
        with scheduler._cv:
            queued_by_tenant = {t: len(q)
                                for t, q in scheduler._queues.items() if q}
            running_by_tenant: Dict[str, int] = {}
            for h in scheduler._handles:
                if h.state in (QueryState.ADMITTED, QueryState.RUNNING):
                    running_by_tenant[h.tenant] = \
                        running_by_tenant.get(h.tenant, 0) + 1
            active = scheduler._active
        admission = scheduler.admission.stats()
        budget = device_budget_estimate(scheduler.session.conf)
        dm = DeviceManager.peek()
        store = dm.device_store if dm is not None else None
        resident = store.used_bytes if store is not None else 0
        charged = admission.get("charged_bytes", 0)
        in_use = max(charged, resident)
        return {
            "t": round(time.monotonic(), 3),
            #: UP | DRAINING — a draining replica keeps reporting a live
            #: series (its running queries still finish here) but routers
            #: must stop sending new submissions
            "state": ("DRAINING" if getattr(scheduler, "draining", False)
                      else "UP"),
            "device_budget_bytes": budget or 0,
            #: budget in use: the admission ledger's charged estimates or
            #: the store's actually-resident bytes, whichever is larger —
            #: charged covers admitted-but-not-yet-resident queries,
            #: resident covers cached/spill-tier occupancy admission never
            #: charged
            "device_budget_in_use": in_use,
            "device_budget_fraction": (round(in_use / budget, 4)
                                       if budget else 0.0),
            "admission_queue_depth": sum(queued_by_tenant.values()),
            "queued_by_tenant": queued_by_tenant,
            "running_by_tenant": running_by_tenant,
            "active_workers": active,
        }

    # ---- consumers ---------------------------------------------------------
    def _trim_locked(self) -> None:
        horizon = time.monotonic() - self.window_s
        while self._walls and self._walls[0][0] < horizon:
            self._walls.popleft()
        while self._samples and self._samples[0]["t"] < horizon:
            self._samples.popleft()

    def snapshot(self, scheduler) -> Dict[str, Any]:
        """The full serve.stats payload: one fresh sample + the rolling
        series + window latency percentiles. ``age_s`` is the staleness of
        the series BEFORE this call's inline sample — a health RPC always
        samples fresh on its way out, so the inline sample's own age says
        nothing about whether the background tick is alive; the pre-call
        age does."""
        pre_age = self.age_s()
        now = self.sample(scheduler)
        with self._lock:
            walls = sorted(w for _, w in self._walls)
            series = list(self._samples)
        return {
            "window_s": self.window_s,
            #: seconds the series had gone without a sample when this
            #: snapshot was requested (None: no sample ever) — the
            #: autoscaler treats ages past serving.stats.staleAfterSeconds
            #: as an unhealthy replica
            "age_s": (round(pre_age, 3) if pre_age is not None else None),
            "now": now,
            "series": series,
            "wall_samples": len(walls),
            "p50_wall_s": round(percentile(walls, 50.0), 6),
            "p99_wall_s": round(percentile(walls, 99.0), 6),
        }
