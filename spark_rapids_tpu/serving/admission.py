"""Footprint admission: admit RUNNING queries against the device budget.

``serving.maxConcurrentQueries`` bounds in-flight queries by COUNT — a
number with no relation to HBM. Theseus's argument (PAPERS.md) is that an
accelerated query platform must admit by data movement / memory, and PR 11
made the inputs real: every device operator declares a
``working_set_estimate`` and the out-of-core layer honors the budget it is
admitted under. This module closes the loop:

- at worker pickup, the planned query's peak device working set
  (``plan/footprint.plan_working_set_estimate`` — the max over device
  operators) is charged against the device budget
  (``plan/footprint.device_budget_estimate``);
- a query whose estimate does not fit the FREE budget waits (bounded
  poll + its own cancel/deadline check) until running queries release
  their share — it never OOMs a running query;
- a query larger than the WHOLE budget can never fit; it is admitted
  under a **grace hint**, charged the out-of-core HEADROOM share of the
  budget (``memory.outOfCore.headroomFraction``) rather than its
  impossible estimate — the grace/spill tiers complete it by
  partitioning within that share ("fits or spills, always completes").
  Charging the headroom share instead of the full budget deliberately
  leaves the remaining fraction free, so small interactive queries
  still admit alongside a whale and reach the DEVICE semaphore — where
  the preemption governor can see them starve and make the whale yield
  (charging the whole budget would park them here, invisible to
  preemption, for the whale's entire runtime);
- estimates of None (no device operator declares one) admit freely, as
  before the footprint contract existed.

Every wait increments ``serving.admission_rejections_footprint`` once and
stamps the handle (``admission_footprint_wait_s``, ``footprint_est_bytes``,
``admission_grace_hint``), so admission decisions are visible in metrics.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from spark_rapids_tpu.utils import metrics as um

_POLL_S = 0.05


class FootprintAdmission:
    """Device-budget ledger shared by one scheduler's workers."""

    def __init__(self, conf):
        from spark_rapids_tpu import config as cfg
        self.enabled = conf.get(cfg.SERVING_ADMIT_FOOTPRINT)
        self._conf = conf
        self._cv = threading.Condition()
        #: query_id -> charged bytes (min(estimate, budget))
        self._holds: Dict[int, int] = {}
        self._used = 0

    def _budget(self) -> Optional[int]:
        """Re-derived per admission: the DeviceManager is created lazily,
        and its configured budget supersedes the conf-derived estimate."""
        from spark_rapids_tpu.plan.footprint import device_budget_estimate
        return device_budget_estimate(self._conf)

    def try_admit(self, handle, estimate: Optional[int]) -> bool:
        """One non-blocking admission attempt: True charges ``estimate``
        to ``handle`` (or the query is exempt), False means it does not
        fit the free budget RIGHT NOW. The scheduler requeues a rejected
        handle instead of blocking — a worker parked inside admission
        would pin its slot and head-of-line-block small queries that
        would fit (the whole point of footprint admission)."""
        if not self.enabled or estimate is None or estimate <= 0:
            return True
        budget = self._budget()
        if not budget:
            return True
        from spark_rapids_tpu import config as cfg
        handle.note_metric("footprint_est_bytes", int(estimate))
        grace = int(estimate) > budget
        if grace:
            # over-the-whole-budget whale: the OOC layer will partition
            # and spill within the headroom share it is admitted under,
            # so charge THAT — not the impossible estimate and not the
            # full budget (which would park interactive queries here,
            # invisible to the preemption governor, for the whale's
            # whole runtime)
            charged = max(1, int(budget
                                 * self._conf.get(cfg.OOC_HEADROOM)))
            handle.note_metric("admission_grace_hint", True)
        else:
            charged = int(estimate)
        with self._cv:
            if self._used > 0 and self._used + charged > budget:
                if handle._admission_rejected_at is None:
                    handle._admission_rejected_at = time.perf_counter()
                    um.SERVING_METRICS[
                        um.SERVING_ADMISSION_REJECTIONS].add(1)
                return False
            self._holds[handle.query_id] = charged
            self._used += charged
        if handle._admission_rejected_at is not None:
            handle.note_metric("admission_footprint_wait_s", round(
                time.perf_counter() - handle._admission_rejected_at, 6))
        return True

    def admit(self, handle, estimate: Optional[int]) -> None:
        """Blocking form of ``try_admit`` (bounded cancellable poll) for
        callers without a queue to return to; re-raises the handle's
        cancellation/deadline error without charging."""
        while not self.try_admit(handle, estimate):
            with self._cv:
                self._cv.wait(_POLL_S)
            handle.check_cancelled()

    def release(self, handle) -> None:
        with self._cv:
            charged = self._holds.pop(handle.query_id, 0)
            self._used -= charged
            if charged:
                self._cv.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"admitted": len(self._holds),
                    "charged_bytes": self._used}
