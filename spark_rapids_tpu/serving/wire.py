"""Serving wire protocol: Arrow IPC over the PR 2 TCP shuffle machinery.

No new plumbing: the query service speaks through the existing
``ShuffleTransport`` traits — the framed TCP socket layer (shuffle/tcp.py:
kind/tag/length frames, hello handshake, per-peer reader threads,
peer-lost scoped failure), the deterministic retry/backoff schedule
(shuffle/retry.py), the crc32 checksum discipline (shuffle/codec.py) and
the chaos harness (shuffle/faults.py ``FaultInjectingTransport``, selected
by ``serving.net.faults.plan``). Control messages ride ``request()`` RPCs
(struct-packed like shuffle/messages.py); result batches ride
tag-addressed data frames as Arrow IPC streams, verified client-side
against the server's crc32 — corruption is a retryable fetch, exactly the
shuffle TransferResponse contract.

The stream protocol (pull-based, one parked batch per query — bounded
state on both ends):

1. ``serve.submit`` {sql, tenant, timeout, label} -> {query_id}
2. loop ``serve.next`` {query_id, ack_seq} ->
   WAIT (nothing ready inside the bounded server poll; re-ask)
   | BATCH {seq, nbytes, crc32}  (parked server-side until acked)
   | DONE {batches, metrics json, schema ipc}
   | ERROR {message}
3. on BATCH: post a receive for a fresh client tag, ``serve.fetch``
   {query_id, seq, tag} -> the server pushes the Arrow-IPC frame to that
   tag. Checksum mismatch -> backoff + re-fetch (the parked copy
   retransmits); the NEXT ``serve.next`` carries ack_seq, releasing it.
4. ``serve.cancel`` {query_id} / client disconnect both release every
   server-side resource through the cooperative-cancel chain.

``serve.register`` uploads an Arrow-IPC table to register as a temp view
(how tests and the routing client seed every replica identically), and
``serve.stats`` exposes scheduler + program-cache + serving counters —
the two-replica warm-start probe reads its ``disk_hits`` through this.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.shuffle.codec import checksum_of, verify_checksum

REQ_SUBMIT = "serve.submit"
REQ_NEXT = "serve.next"
REQ_FETCH = "serve.fetch"
REQ_CANCEL = "serve.cancel"
REQ_REGISTER = "serve.register"
REQ_STATS = "serve.stats"
#: liveness + load probe: {"state": "UP"|"DRAINING", "serve_stats": ...}
#: (the PR 13 rolling time-series) — what circuit-breaker probes and
#: load-aware routing consume; deliberately cheaper than serve.stats
REQ_HEALTH = "serve.health"
#: graceful drain: flip the replica to DRAINING (new submits are
#: rejected with a retryable redirect, running queries finish, streams
#: flush, then the server deregisters and exits)
REQ_DRAIN = "serve.drain"

#: serve.next response kinds
NEXT_WAIT = 0
NEXT_BATCH = 1
NEXT_DONE = 2
NEXT_ERROR = 3

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")


def _pack_str(s: str) -> bytes:
    b = s.encode()
    return _U32.pack(len(b)) + b


def _unpack_str(buf: bytes, pos: int) -> Tuple[str, int]:
    n, = _U32.unpack_from(buf, pos)
    pos += 4
    return buf[pos:pos + n].decode(), pos + n


def _pack_blob(b: bytes) -> bytes:
    return _U32.pack(len(b)) + b


def _unpack_blob(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n, = _U32.unpack_from(buf, pos)
    pos += 4
    return buf[pos:pos + n], pos + n


# ------------------------------------------------------------ Arrow IPC
def table_to_ipc(table: pa.Table) -> bytes:
    """Arrow IPC stream bytes of one result batch (the wire format the
    paper's client surface speaks; deterministic for a given table)."""
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(data)) as reader:
        return reader.read_all()


def schema_to_ipc(schema: pa.Schema) -> bytes:
    """Schema-only IPC stream (the DONE frame carries it so a zero-batch
    result still assembles to the correctly-typed empty table)."""
    return table_to_ipc(schema.empty_table())


# ------------------------------------------------------------- messages
@dataclass(frozen=True)
class SubmitRequest:
    sql: str
    tenant: str = "default"
    timeout: float = 0.0
    label: str = ""
    #: stream-resume failover: the last batch sequence number the client
    #: already holds from a replica that died mid-stream. The server
    #: re-runs the query and SKIPS frames with seq <= resume_from (dedup
    #: by seq — exactly-once delivery to the caller); -1 is a fresh run.
    resume_from: int = -1

    def to_bytes(self) -> bytes:
        return (_pack_str(self.sql) + _pack_str(self.tenant)
                + _F64.pack(self.timeout) + _pack_str(self.label)
                + _I64.pack(self.resume_from))

    @staticmethod
    def from_bytes(buf: bytes) -> "SubmitRequest":
        sql, pos = _unpack_str(buf, 0)
        tenant, pos = _unpack_str(buf, pos)
        timeout, = _F64.unpack_from(buf, pos)
        pos += 8
        label, pos = _unpack_str(buf, pos)
        resume_from, = _I64.unpack_from(buf, pos)
        return SubmitRequest(sql, tenant, timeout, label, resume_from)


@dataclass(frozen=True)
class SubmitResponse:
    query_id: int
    #: structured front-door rejection (overload shed / quota): an
    #: ``encode_error`` JSON payload — query_id 0, no handle was created.
    #: Empty for accepted submissions. Rides the SUCCESS response path
    #: because the transport's error path is a bare string (type: message)
    #: that cannot carry the taxonomy's structured fields (retry_after_s).
    error_json: bytes = b""

    def to_bytes(self) -> bytes:
        return _U64.pack(self.query_id) + _pack_blob(self.error_json)

    @staticmethod
    def from_bytes(buf: bytes) -> "SubmitResponse":
        qid, = _U64.unpack_from(buf, 0)
        if len(buf) <= 8:       # pre-elasticity peer: no rejection blob
            return SubmitResponse(qid)
        ej, _pos = _unpack_blob(buf, 8)
        return SubmitResponse(qid, error_json=ej)


@dataclass(frozen=True)
class NextRequest:
    query_id: int
    ack_seq: int = -1           # -1: nothing to acknowledge

    def to_bytes(self) -> bytes:
        return _U64.pack(self.query_id) + _I64.pack(self.ack_seq)

    @staticmethod
    def from_bytes(buf: bytes) -> "NextRequest":
        qid, = _U64.unpack_from(buf, 0)
        ack, = _I64.unpack_from(buf, 8)
        return NextRequest(qid, ack)


@dataclass(frozen=True)
class NextResponse:
    kind: int                   # NEXT_WAIT | NEXT_BATCH | NEXT_DONE | NEXT_ERROR
    seq: int = 0                # BATCH
    nbytes: int = 0             # BATCH
    checksum: int = 0           # BATCH (crc32 over the IPC frame)
    batches: int = 0            # DONE: total batches streamed
    metrics_json: bytes = b""   # DONE: the handle's terminal snapshot
    schema_ipc: bytes = b""     # DONE: schema-only IPC stream
    error: str = ""             # ERROR

    def to_bytes(self) -> bytes:
        head = struct.pack("<B", self.kind)
        if self.kind == NEXT_BATCH:
            return head + struct.pack("<III", self.seq, self.nbytes,
                                      self.checksum)
        if self.kind == NEXT_DONE:
            return (head + _U32.pack(self.batches)
                    + _pack_blob(self.metrics_json)
                    + _pack_blob(self.schema_ipc))
        if self.kind == NEXT_ERROR:
            return head + _pack_str(self.error)
        return head

    @staticmethod
    def from_bytes(buf: bytes) -> "NextResponse":
        kind, = struct.unpack_from("<B", buf, 0)
        if kind == NEXT_BATCH:
            seq, nbytes, crc = struct.unpack_from("<III", buf, 1)
            return NextResponse(kind, seq=seq, nbytes=nbytes, checksum=crc)
        if kind == NEXT_DONE:
            batches, = _U32.unpack_from(buf, 1)
            mj, pos = _unpack_blob(buf, 5)
            si, pos = _unpack_blob(buf, pos)
            return NextResponse(kind, batches=batches, metrics_json=mj,
                                schema_ipc=si)
        if kind == NEXT_ERROR:
            err, _pos = _unpack_str(buf, 1)
            return NextResponse(kind, error=err)
        return NextResponse(kind)


@dataclass(frozen=True)
class FetchRequest:
    query_id: int
    seq: int
    tag: int                    # client-chosen tag the frame is pushed to

    def to_bytes(self) -> bytes:
        return _U64.pack(self.query_id) + _U32.pack(self.seq) \
            + _U64.pack(self.tag)

    @staticmethod
    def from_bytes(buf: bytes) -> "FetchRequest":
        qid, = _U64.unpack_from(buf, 0)
        seq, = _U32.unpack_from(buf, 8)
        tag, = _U64.unpack_from(buf, 12)
        return FetchRequest(qid, seq, tag)


@dataclass(frozen=True)
class CancelRequest:
    query_id: int

    def to_bytes(self) -> bytes:
        return _U64.pack(self.query_id)

    @staticmethod
    def from_bytes(buf: bytes) -> "CancelRequest":
        return CancelRequest(_U64.unpack_from(buf, 0)[0])


@dataclass(frozen=True)
class RegisterRequest:
    name: str
    ipc: bytes
    checksum: int = 0

    def to_bytes(self) -> bytes:
        return (_pack_str(self.name) + _pack_blob(self.ipc)
                + _U32.pack(self.checksum or checksum_of(self.ipc)))

    @staticmethod
    def from_bytes(buf: bytes) -> "RegisterRequest":
        name, pos = _unpack_str(buf, 0)
        ipc, pos = _unpack_blob(buf, pos)
        crc, = _U32.unpack_from(buf, pos)
        verify_checksum(ipc, crc, context=f"register {name!r}")
        return RegisterRequest(name, ipc, crc)


# ------------------------------------------------------ transport wiring
def make_serving_transport(executor_id: str, conf, listen_port: Optional[int]
                           = None, registry_dir: str = ""):
    """Build the query service's transport from the serving.net.* conf:
    the configured transport class (TCP by default) bound to the serving
    listen port, wrapped in the FaultInjectingTransport when a wire-chaos
    plan is set — the shuffle chaos harness applied verbatim to the
    serving wire. ``registry_dir`` (servers only: replica discovery +
    liveness heartbeats ride the registry file's mtime) defaults to ""
    so CLIENTS never publish themselves as replicas."""
    import importlib
    from spark_rapids_tpu import config as cfg
    overrides = {
        cfg.SHUFFLE_TCP_PORT.key: (listen_port if listen_port is not None
                                   else conf.get(cfg.SERVING_NET_PORT)),
        cfg.SHUFFLE_TCP_REGISTRY.key: registry_dir,
    }
    plan = conf.get(cfg.SERVING_NET_FAULTS_PLAN)
    cls_name = conf.get(cfg.SERVING_NET_TRANSPORT)
    if plan:
        overrides[cfg.SHUFFLE_FAULTS_TRANSPORT.key] = cls_name
        overrides[cfg.SHUFFLE_FAULTS_PLAN.key] = plan
        overrides[cfg.SHUFFLE_FAULTS_SEED.key] = conf.get(
            cfg.SERVING_NET_FAULTS_SEED)
        cls_name = ("spark_rapids_tpu.shuffle.faults."
                    "FaultInjectingTransport")
    tconf = conf.with_overrides(overrides)
    mod_name, _, cls = cls_name.rpartition(".")
    return getattr(importlib.import_module(mod_name), cls)(executor_id,
                                                           tconf)
