"""Cross-query compiled-program cache with an on-disk plan-key index.

Flare's compile-once/serve-many result, applied to the engine's XLA
programs: every operator pipeline the execs jit is keyed on canonical plan
structure (operator name + config), dtype signature (the output schema is
part of every key) and SHAPE BUCKET (capacities are already padded to
powers of two by ``bucket_capacity`` — conf ``serving.shapeBuckets`` keeps
that discipline switchable for debugging), so row-count drift between
batches and BETWEEN QUERIES reuses one compiled program instead of
re-tracing (tpu-lint R001's dynamic counterpart).

Two persistence layers compose:

- jax's persistent compilation cache (wired at import in device.py) stores
  the serialized XLA executables, so a recompile of a known computation is
  a cheap deserialize;
- this module's PLAN-KEY INDEX records which cache keys this server (or a
  previous incarnation of it) has compiled, in a small JSON file next to
  the compilation cache. A restarted server that misses in memory but
  hits the index counts a ``disk_hit``: the program warms from disk
  instead of compiling cold — the observable warm-start the bench
  ``concurrent`` section asserts.

Concurrency: one in-flight latch per key — when two queries miss on the
same key simultaneously, one builds while the other waits, mirroring the
scan-cache upload latch (a double compile wastes minutes on the remote
tunnel). Attribution: hits/misses/disk-hits and first-call compile time
land on ``current_query()`` when a query is bound.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from spark_rapids_tpu.serving.lifecycle import current_query

_INDEX_FILENAME = "serving-program-index.json"


def stable_key_hash(key: Any) -> str:
    """Process-independent identity of a cache key. Keys are tuples of
    operator names/config scalars, frozen-dataclass expressions, Schema
    objects and capacity buckets — all with deterministic reprs."""
    return hashlib.sha1(repr(key).encode()).hexdigest()


class _Program:
    """A cached compiled program. ``jax.jit`` returns without tracing, so
    the real compile happens on the FIRST invocation — this wrapper times
    that call and attributes it to the triggering query's ``compile_s``
    (an upper bound: it includes the first execution)."""

    __slots__ = ("fn", "_cache", "_first_pending", "_lock")

    def __init__(self, fn: Callable, cache: "ProgramCache"):
        self.fn = fn
        self._cache = cache
        self._first_pending = True
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        if not self._first_pending:
            return self.fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        with self._lock:
            first, self._first_pending = self._first_pending, False
        if first:
            self._cache._note_compile(dt)
            q = current_query()
            if q is not None:
                q.note_compile(dt)
        return out


class ProgramCache:
    """LRU of compiled programs + the persistent plan-key index."""

    def __init__(self, max_programs: int = 4096,
                 index_path: Optional[str] = None):
        self.max_programs = max_programs
        self._lock = threading.Lock()
        self._programs: "OrderedDict[Any, _Program]" = OrderedDict()
        self._building: Dict[Any, threading.Event] = {}
        self._disk_index: Dict[str, int] = {}
        self._index_path: Optional[str] = None
        self._counters = {"hits": 0, "misses": 0, "disk_hits": 0,
                          "evictions": 0, "compile_s": 0.0}
        self.set_index_path(index_path)

    # ---- the cache ---------------------------------------------------------
    def get_or_build(self, key: Any, builder: Callable[[], Callable]):
        """Return the compiled program for ``key``, building (once, under a
        per-key latch) on miss. ``builder`` returns the callable to cache —
        typically ``jax.jit(...)`` over a traced pipeline."""
        while True:
            with self._lock:
                prog = self._programs.get(key)
                if prog is not None:
                    self._programs.move_to_end(key)
                    self._counters["hits"] += 1
                else:
                    ev = self._building.get(key)
                    if ev is None:
                        ev = threading.Event()
                        self._building[key] = ev
                        break           # we build
            if prog is not None:
                # per-query attribution OUTSIDE the cache lock: the hit
                # path runs once per batch per operator and must not
                # serialize workers on handle locks
                q = current_query()
                if q is not None:
                    q.count_program(hit=True)
                return prog
            # someone else is building this key: wait, then re-check (on
            # builder failure the waiter becomes the next builder). Poll
            # the bound query's cancel/deadline flag — a compile can take
            # minutes over the remote tunnel, and a cancelled query must
            # not wait out a program it will never run
            waiter_q = current_query()
            while not ev.wait(0.05):
                if waiter_q is not None:
                    waiter_q.check_cancelled()
        try:
            fn = builder()
            prog = _Program(fn, self)
            khash = stable_key_hash(key)
            xla_cache_live = _default_index_dir() is not None
            with self._lock:
                # a disk hit means the jax persistent compilation cache
                # can actually serve this compile — claim one only when
                # our index is real AND the XLA cache is wired (an
                # index-known key whose executable jax never persisted —
                # sub-threshold compile time — still counts: the claim is
                # 'known plan shape, warm where the XLA cache has it')
                from_disk = (self._index_path is not None
                             and xla_cache_live
                             and khash in self._disk_index)
                self._counters["misses"] += 1
                if from_disk:
                    self._counters["disk_hits"] += 1
                self._programs[key] = prog
                self._disk_index[khash] = self._disk_index.get(khash, 0) + 1
                while len(self._programs) > self.max_programs:
                    self._programs.popitem(last=False)
                    self._counters["evictions"] += 1
        finally:
            with self._lock:
                waiter = self._building.pop(key, None)
            if waiter is not None:
                waiter.set()
        # post-build bookkeeping AFTER the latch releases: waiters of this
        # key must not stay blocked on query attribution or the index
        # file's read-merge-rewrite
        q = current_query()
        if q is not None:
            q.count_program(hit=False, from_disk=from_disk)
        self._save_index()
        return prog

    def _note_compile(self, seconds: float) -> None:
        with self._lock:
            self._counters["compile_s"] += seconds

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._counters)
            out["compile_s"] = round(out["compile_s"], 4)
            out["programs"] = len(self._programs)
            out["indexed_keys"] = len(self._disk_index)
            total = out["hits"] + out["misses"]
            out["hit_rate"] = round(out["hits"] / total, 4) if total else None
            return out

    def snapshot_counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    # ---- persistence -------------------------------------------------------
    def set_index_path(self, path: Optional[str]) -> None:
        """(Re)wire the on-disk index. ``path`` may be a directory (the
        index file lands inside) or a file path; None falls back to the
        process compilation-cache directory; 'off' disables persistence."""
        if path is None:
            path = _default_index_dir()
        if not path or str(path).lower() == "off":
            with self._lock:
                self._index_path = None
            return
        if not str(path).endswith(".json"):
            path = os.path.join(path, _INDEX_FILENAME)
        loaded = _load_index(path)
        with self._lock:
            self._index_path = path
            for k, v in loaded.items():
                self._disk_index[k] = max(self._disk_index.get(k, 0), v)
        # persist immediately: keys compiled BEFORE the index was wired
        # (e.g. warmup actions preceding scheduler construction) must reach
        # disk even if no further miss ever triggers a save
        self._save_index()

    def _save_index(self) -> None:
        with self._lock:
            path = self._index_path
            if path is None:
                return
            mine = dict(self._disk_index)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            # merge-with-current so concurrent server processes sharing one
            # cache directory extend, rather than clobber, the index
            merged = _load_index(path)
            for k, v in mine.items():
                merged[k] = max(merged.get(k, 0), v)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": 1, "keys": merged}, f)
            os.replace(tmp, path)
        except OSError:
            pass                        # the index is an optimization only

    # ---- test / lifecycle hooks -------------------------------------------
    def clear(self, drop_index: bool = False) -> None:
        """Drop the in-memory programs (conftest calls this between test
        modules alongside jax.clear_caches(); compiled-executable memory
        otherwise accumulates). The disk index survives unless asked.
        In-flight build latches are NOT touched: clearing them would leave
        their waiters blocked on an Event the builder's finally can no
        longer find and set."""
        with self._lock:
            self._programs.clear()
            if drop_index:
                self._disk_index.clear()
            for k in self._counters:
                self._counters[k] = 0.0 if k == "compile_s" else 0


def _default_index_dir() -> Optional[str]:
    """The jax persistent compilation-cache directory wired in device.py:
    the plan-key index lives next to the executables it describes."""
    try:
        import jax
        return getattr(jax.config, "jax_compilation_cache_dir", None)
    except Exception:       # noqa: BLE001 - persistence is optional
        return None


def _load_index(path: str) -> Dict[str, int]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        keys = data.get("keys", {})
        return {str(k): int(v) for k, v in keys.items()}
    except (OSError, ValueError):
        return {}


_GLOBAL: Optional[ProgramCache] = None
_GLOBAL_LOCK = threading.Lock()


def global_program_cache() -> ProgramCache:
    """The process-wide cache every exec's jit construction routes through
    (tpu_execs._cached_jit, PhysicalExec.cached_program). One per process,
    like the device itself."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ProgramCache()
        return _GLOBAL


def configure_from_conf(conf) -> ProgramCache:
    """Apply serving.* cache settings (scheduler construction path)."""
    from spark_rapids_tpu import config as cfg
    cache = global_program_cache()
    cache.max_programs = conf.get(cfg.SERVING_CACHE_MAX_PROGRAMS)
    d = conf.get(cfg.SERVING_CACHE_DIR)
    cache.set_index_path(d if d else None)
    return cache


# ---------------------------------------------------------------- plan keys
def plan_key(plan, conf=None) -> str:
    """Canonical signature of a physical plan: operator structure + dtype
    signature + partitioning, with row-count estimates bucketed to powers
    of two (conf ``serving.shapeBuckets``). Two submissions of the same
    query shape — whatever their exact row counts — share one key; the
    scheduler stamps it on the handle so cache behavior is attributable
    per plan shape."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.columnar.dtypes import bucket_capacity
    bucketed = True if conf is None else bool(conf.get(cfg.SERVING_SHAPE_BUCKETS))

    def walk(node) -> Tuple:
        est = node.size_estimate()
        if est is not None:
            est = bucket_capacity(int(est), bucketed=bucketed)
        sig = tuple(f.dtype.value for f in node.output)
        return (node.name, sig, node.num_partitions, est,
                tuple(walk(c) for c in node.children))

    return stable_key_hash(walk(plan))
