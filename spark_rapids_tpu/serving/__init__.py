"""Concurrent query serving: scheduler, program cache, query lifecycle.

The layer that turns the one-query-at-a-time engine into a multi-tenant
server (ROADMAP item 4; Theseus's admission-controlled many-queries-in-
flight platform + Flare's compile-once/serve-many result):

- ``lifecycle``: QueryHandle state machine (QUEUED -> ADMITTED -> RUNNING
  -> {DONE, FAILED, CANCELLED}) with cooperative cancellation, per-query
  deadlines, and per-query metric snapshots;
- ``program_cache``: the cross-query compiled-program cache keyed on
  canonical plan structure + dtype signature + shape bucket, with an
  on-disk plan-key index over the jax persistent compilation cache so a
  restarted server warms from disk;
- ``scheduler``: the session scheduler running N concurrent queries over
  a shared worker pool with fair-share tenant admission layered on the
  device-admission semaphore.
"""
from spark_rapids_tpu.serving.lifecycle import (QueryCancelledError,
                                                QueryHandle, QueryState,
                                                QueryTimeoutError,
                                                current_query)
from spark_rapids_tpu.serving.program_cache import (ProgramCache,
                                                    global_program_cache,
                                                    plan_key)
from spark_rapids_tpu.serving.scheduler import SessionScheduler

__all__ = [
    "ProgramCache", "QueryCancelledError", "QueryHandle", "QueryState",
    "QueryTimeoutError", "SessionScheduler", "current_query",
    "global_program_cache", "plan_key",
]
