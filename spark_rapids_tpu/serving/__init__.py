"""Concurrent query serving: scheduler, program cache, lifecycle, wire.

The layer that turns the one-query-at-a-time engine into a multi-tenant
NETWORK service (ROADMAP items 2 and 4; Theseus's admission-controlled
many-queries-in-flight platform + Flare's compile-once/serve-many
result):

- ``lifecycle``: QueryHandle state machine (QUEUED -> ADMITTED -> RUNNING
  -> {DONE, FAILED, CANCELLED}) with cooperative cancellation, per-query
  deadlines, per-query metric snapshots, streaming result sinks
  (ResultStream) and batch-granularity preemption checkpoints;
- ``program_cache``: the cross-query compiled-program cache keyed on
  canonical plan structure + dtype signature + shape bucket, with an
  on-disk plan-key index over the jax persistent compilation cache so a
  restarted server (or a SECOND replica) warms from disk;
- ``scheduler``: the session scheduler running N concurrent queries over
  a shared worker pool with fair-share tenant admission layered on the
  device-admission semaphore;
- ``admission``: footprint admission — RUNNING queries charged their
  working_set_estimate against the device budget, not a bare count;
- ``wire`` / ``server`` / ``client``: the Arrow-IPC wire protocol over
  the PR 2 TCP shuffle machinery — streaming partial results, retryable
  checksum failures, disconnect-as-cancel, N routed replicas;
- ``health``: fleet resilience — per-replica circuit breakers, liveness
  discovery through the shuffle registry-dir rendezvous (heartbeat
  mtime, stale-entry GC), and the load-aware routing score; together
  with stream-resume failover and graceful drain, replica death becomes
  a recoverable, observable event instead of a client-visible error;
- ``supervisor`` / ``controller``: the elastic self-healing fleet —
  replica slots supervised with deterministic restart backoff and a
  crash-loop breaker, plus the autoscaling control loop (pure decision
  core over serve.health pressure with hysteresis and cooldowns) whose
  scale-down routes through the graceful-drain path; overload sheds at
  the front door as structured retryable OverloadedError rejections
  carrying a retry-after hint.
"""
from spark_rapids_tpu.serving.admission import FootprintAdmission
from spark_rapids_tpu.serving.controller import (ControllerState, Decision,
                                                 FleetController,
                                                 ReplicaSnapshot,
                                                 ScalingPolicy, decide)
from spark_rapids_tpu.serving.health import (CircuitBreaker, ReplicaState,
                                             routing_score)
from spark_rapids_tpu.serving.lifecycle import (OverloadedError,
                                                QueryCancelledError,
                                                QueryHandle, QueryState,
                                                QueryTimeoutError,
                                                QuotaExceededError,
                                                ResultStream,
                                                SchedulerDrainingError,
                                                current_query)
from spark_rapids_tpu.serving.program_cache import (ProgramCache,
                                                    global_program_cache,
                                                    plan_key)
from spark_rapids_tpu.serving.scheduler import SessionScheduler
from spark_rapids_tpu.serving.supervisor import (ReplicaSupervisor, SlotState)

__all__ = [
    "CircuitBreaker", "ControllerState", "Decision", "FleetController",
    "FootprintAdmission", "OverloadedError", "ProgramCache",
    "QueryCancelledError", "QueryHandle", "QueryState", "QueryTimeoutError",
    "QuotaExceededError", "ReplicaSnapshot", "ReplicaState",
    "ReplicaSupervisor", "ResultStream", "ScalingPolicy",
    "SchedulerDrainingError", "SessionScheduler", "SlotState",
    "current_query", "decide", "global_program_cache", "plan_key",
    "routing_score",
]
