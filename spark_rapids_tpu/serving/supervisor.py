"""Replica supervisor: the fleet's self-healing tier.

PR 14 made replica death RECOVERABLE (registry liveness, breakers,
stream-resume failover) — but a dead replica stayed dead until a human
restarted it. ``ReplicaSupervisor`` owns N replica *slots*, each running
one serving subprocess, and closes the loop:

- **death detection, two ways**: the process EXITING (``proc.poll()``)
  and the process WEDGING — its registry heartbeat ages past the
  liveness window while the process is still alive (a hung event loop
  heartbeats nothing); a wedged replica is killed and treated as dead.
- **deterministic restart backoff**: a dead slot respawns after the
  shuffle/retry.py schedule (seeded, keyed by slot index — replayable
  chaos runs restart on identical schedules), and the attempt counter
  resets after ``serving.fleet.stableUptimeSeconds`` of healthy uptime.
- **crash-loop breaker**: ``serving.fleet.crashLoopThreshold`` deaths
  inside ``serving.fleet.crashLoopWindowSeconds`` stops the restart
  storm — the slot is marked DEGRADED (no further restarts, surfaced in
  ``fleet_stats()``, excluded from the autoscaler's healthy count)
  instead of burning CPU forever; ``reset_slot()`` re-arms it once the
  operator fixes the cause.
- **graceful retirement**: ``scale_down()`` routes through the PR 14
  drain path (SIGTERM → running queries finish, streams flush, registry
  entry retracted at exit) so a controller shrinking the fleet drops
  zero in-flight queries; an intentional stop is never counted as a
  death.

The spawn seam is injectable (``spawn(slot_index) -> replica process``)
so unit tests drive the state machine with fake processes and the
in-process chaos suite supervises real ``QueryServer`` instances; the
default spawns ``python -m spark_rapids_tpu.serving.server`` and reads
its ``SERVING <host> <port>`` banner. Lock discipline: decisions happen
under the supervisor lock, process actions (spawn / kill / wait) happen
outside it (R006/R012).
"""
from __future__ import annotations

import enum
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.shuffle.tcp import scan_registry
from spark_rapids_tpu.utils import metrics as um


class SlotState(enum.Enum):
    STARTING = "STARTING"   # spawned, waiting for the address banner
    UP = "UP"               # process alive (and heartbeating, if registry)
    BACKOFF = "BACKOFF"     # died; restart scheduled on the retry schedule
    DEGRADED = "DEGRADED"   # crash-loop breaker fired: no more restarts
    DRAINING = "DRAINING"   # intentional retirement in progress
    STOPPED = "STOPPED"     # retired; slot kept for fleet_stats history


class _SubprocessReplica:
    """Default spawn product: one serving-server subprocess. ``addr`` is
    filled by a banner-reader thread once the child prints ``SERVING
    <host> <port>`` (stderr goes to DEVNULL — a chatty child must not
    fill an undrained pipe and wedge itself)."""

    def __init__(self, args: List[str]):
        self.proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                                     stderr=subprocess.DEVNULL, text=True)
        self.addr: Optional[str] = None
        threading.Thread(target=self._read_banner, daemon=True,
                         name="supervisor-banner").start()

    def _read_banner(self) -> None:
        try:
            for line in self.proc.stdout:
                parts = line.split()
                if len(parts) == 3 and parts[0] == "SERVING":
                    self.addr = f"{parts[1]}:{parts[2]}"
                    break
            # keep draining so the child never blocks on a full pipe
            for _line in self.proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self) -> None:
        try:
            self.proc.terminate()       # SIGTERM == graceful drain
        except OSError:
            pass

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass


class ReplicaSlot:
    """Supervisor-side state of one replica slot (all fields guarded by
    the supervisor lock except the spawned process's own attributes)."""

    __slots__ = ("index", "state", "proc", "started_at", "attempt",
                 "not_before", "deaths", "restarts", "stable_marked")

    def __init__(self, index: int):
        self.index = index
        self.state = SlotState.BACKOFF      # due for its initial spawn
        self.proc: Optional[Any] = None
        self.started_at = 0.0
        #: consecutive-death restart attempt (drives the backoff schedule;
        #: reset after stableUptimeSeconds of healthy uptime)
        self.attempt = 0
        #: monotonic time the next (re)spawn becomes due
        self.not_before = 0.0
        #: recent death times inside the crash-loop window
        self.deaths: deque = deque(maxlen=64)
        self.restarts = 0
        self.stable_marked = False

    @property
    def addr(self) -> Optional[str]:
        p = self.proc
        return getattr(p, "addr", None) if p is not None else None


class ReplicaSupervisor:
    """Spawns, watches, restarts and retires serving-replica slots."""

    def __init__(self, conf, spawn: Optional[Callable[[int], Any]] = None,
                 server_args: Optional[List[str]] = None):
        self.conf = conf
        self._spawn = spawn or self._default_spawn
        self._server_args = list(server_args or [])
        self._interval = conf.get(cfg.SERVING_FLEET_SUPERVISE_INTERVAL)
        self._backoff_ms = conf.get(cfg.SERVING_FLEET_RESTART_BACKOFF_MS)
        self._stable_s = conf.get(cfg.SERVING_FLEET_STABLE_UPTIME)
        self._crash_threshold = conf.get(cfg.SERVING_FLEET_CRASH_LOOP_THRESHOLD)
        self._crash_window = conf.get(cfg.SERVING_FLEET_CRASH_LOOP_WINDOW)
        self._seed = conf.get(cfg.SERVING_NET_FAULTS_SEED)
        self.registry_dir = conf.get(cfg.SERVING_NET_REGISTRY)
        self._liveness_window = conf.get(cfg.SERVING_HEALTH_LIVENESS_WINDOW)
        self._lock = threading.Lock()
        self._slots: Dict[int, ReplicaSlot] = {}
        self._next_index = 0
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- spawning ----------------------------------------------------------
    def _default_spawn(self, slot_index: int) -> _SubprocessReplica:
        args = [sys.executable, "-m", "spark_rapids_tpu.serving.server",
                "--port", "0"]
        for key, val in sorted(getattr(self.conf, "_values", {}).items()):
            if isinstance(val, bool):
                val = "true" if val else "false"
            args += ["--conf", f"{key}={val}"]
        args += self._server_args
        return _SubprocessReplica(args)

    # ---- lifecycle ---------------------------------------------------------
    def start(self, replicas: int) -> None:
        """Create ``replicas`` slots (spawned by the first tick) and start
        the supervision loop thread."""
        with self._lock:
            for _ in range(max(0, replicas)):
                self._new_slot_locked()
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="replica-supervisor")
            self._thread.start()
        self.tick()

    def _new_slot_locked(self) -> ReplicaSlot:
        slot = ReplicaSlot(self._next_index)
        self._next_index += 1
        self._slots[slot.index] = slot
        return slot

    def _loop(self) -> None:
        # Event.wait is the bounded sleep (R010); tick() itself holds the
        # lock only while deciding, never across a spawn/kill (R006)
        while not self._stop_event.wait(self._interval):
            self.tick()

    def stop(self, graceful: bool = False, timeout: float = 10.0) -> None:
        """Stop supervising and stop every replica. ``graceful`` drains
        each (terminate = the SIGTERM drain path) and waits out the
        timeout before killing what's left; otherwise kill outright."""
        self._stop_event.set()
        with self._lock:
            procs = [s.proc for s in self._slots.values()
                     if s.proc is not None]
            for s in self._slots.values():
                s.state = SlotState.STOPPED
        for p in procs:
            (p.terminate if graceful else p.kill)()
        if graceful:
            deadline = time.monotonic() + timeout
            for p in procs:
                while p.poll() is None and time.monotonic() < deadline:
                    time.sleep(0.05)
                if p.poll() is None:
                    p.kill()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    # ---- the supervision tick ----------------------------------------------
    def tick(self) -> None:
        """One supervision pass: reap exits, kill wedged replicas (missed
        heartbeats), schedule/execute restarts, fire the crash-loop
        breaker. Public so unit tests drive the state machine without the
        loop thread."""
        live_addrs = self._live_registry_addrs()    # blocking IO: no lock
        now = time.monotonic()
        to_kill: List[Any] = []
        to_spawn: List[ReplicaSlot] = []
        with self._lock:
            for slot in self._slots.values():
                if slot.state in (SlotState.DEGRADED, SlotState.STOPPED):
                    continue
                if slot.state is SlotState.DRAINING:
                    if slot.proc is None or slot.proc.poll() is not None:
                        slot.state = SlotState.STOPPED
                        slot.proc = None
                    continue
                if slot.state is SlotState.BACKOFF:
                    if now >= slot.not_before:
                        # claim the slot under the lock BEFORE the
                        # out-of-lock spawn: a concurrent tick must not
                        # collect it again and double-spawn
                        slot.state = SlotState.STARTING
                        to_spawn.append(slot)
                    continue
                proc = slot.proc
                if proc is None:
                    continue
                if proc.poll() is not None:         # death by exit
                    self._record_death_locked(slot, now)
                    continue
                if slot.state is SlotState.STARTING and slot.addr:
                    slot.state = SlotState.UP
                if (not slot.stable_marked
                        and now - slot.started_at >= self._stable_s):
                    slot.attempt = 0                # earned a fresh schedule
                    slot.stable_marked = True
                if self._wedged_locked(slot, live_addrs, now):
                    # death by silence: alive but not heartbeating — kill
                    # the wedged process and restart it like any death
                    to_kill.append(proc)
                    self._record_death_locked(slot, now)
        for proc in to_kill:
            proc.kill()
        for slot in to_spawn:
            self._respawn(slot)

    def _live_registry_addrs(self) -> Optional[set]:
        """Addresses with a fresh heartbeat; None when heartbeat-based
        death detection is off (no registry) or the scan failed RIGHT NOW
        (transient FS hiccup — a missed scan must not read as a massacre)."""
        if not self.registry_dir:
            return None
        try:
            return set(scan_registry(self.registry_dir,
                                     stale_after_s=self._liveness_window)
                       .values())
        except OSError:
            return None

    def _wedged_locked(self, slot: ReplicaSlot, live_addrs: Optional[set],
                       now: float) -> bool:
        if live_addrs is None or slot.state is not SlotState.UP:
            return False
        addr = slot.addr
        if addr is None:
            return False
        # grace: a replica younger than the liveness window may simply
        # not have published its first heartbeat yet
        if now - slot.started_at <= self._liveness_window:
            return False
        return addr not in live_addrs

    def _record_death_locked(self, slot: ReplicaSlot, now: float) -> None:
        slot.proc = None
        slot.deaths.append(now)
        while slot.deaths and slot.deaths[0] < now - self._crash_window:
            slot.deaths.popleft()
        if len(slot.deaths) >= self._crash_threshold:
            # crash-loop breaker: N rapid deaths — stop restarting,
            # surface the slot instead of burning CPU forever
            slot.state = SlotState.DEGRADED
            return
        slot.attempt += 1
        slot.stable_marked = False
        delay_ms = retry.backoff_ms(slot.attempt - 1, self._backoff_ms,
                                    self._seed,
                                    key=f"supervisor:slot{slot.index}")
        slot.state = SlotState.BACKOFF
        slot.not_before = now + delay_ms / 1e3

    def _respawn(self, slot: ReplicaSlot) -> None:
        """Spawn a replica into a slot already claimed for it (state
        STARTING, proc None — set under the lock by tick()/scale_up()
        before this out-of-lock call, so no two spawns target one slot)."""
        try:
            proc = self._spawn(slot.index)  # blocking: outside the lock
        except Exception:
            with self._lock:    # a failed spawn retries on the schedule
                if slot.state is SlotState.STARTING and slot.proc is None:
                    self._record_death_locked(slot, time.monotonic())
            return
        with self._lock:
            claimed = (slot.state is SlotState.STARTING
                       and slot.proc is None)
            if not claimed:
                stale = proc    # raced a stop()/retire: don't leak it
            else:
                stale = None
                #: a spawn that follows a death is a restart; the very
                #: first spawn (and a reset_slot re-arm) is not
                is_restart = slot.attempt > 0
                slot.proc = proc
                slot.started_at = time.monotonic()
                slot.stable_marked = False
                slot.state = (SlotState.UP if slot.addr
                              else SlotState.STARTING)
                if is_restart:
                    slot.restarts += 1
        if stale is not None:
            stale.kill()
            return
        if is_restart:
            um.SERVING_METRICS[um.SERVING_RESTARTS].add(1)

    # ---- fleet control (the autoscaler's levers) ---------------------------
    def scale_up(self) -> int:
        """Add one slot and spawn it now; returns the slot index."""
        with self._lock:
            slot = self._new_slot_locked()
            slot.state = SlotState.STARTING     # claimed for _respawn
        self._respawn(slot)
        return slot.index

    def scale_down(self, addr: Optional[str] = None) -> Optional[int]:
        """Retire one replica through the graceful-drain path: terminate()
        is the SIGTERM drain contract — running queries finish, streams
        flush, the registry entry is retracted at exit — and an
        intentionally DRAINING slot is never counted as a death. Prefers
        the replica at ``addr``; falls back to the newest active slot.
        Returns the retired slot index, or None when nothing matched."""
        with self._lock:
            candidates = [s for s in self._slots.values()
                          if s.state in (SlotState.UP, SlotState.STARTING)]
            chosen = None
            if addr is not None:
                chosen = next((s for s in candidates if s.addr == addr),
                              None)
            if chosen is None and addr is None and candidates:
                chosen = max(candidates, key=lambda s: s.index)
            if chosen is None:
                return None
            chosen.state = SlotState.DRAINING
            proc = chosen.proc
        if proc is not None:
            proc.terminate()
        return chosen.index

    def reset_slot(self, index: int) -> bool:
        """Re-arm a DEGRADED slot (the operator fixed the crash cause):
        clears the breaker history and schedules an immediate respawn."""
        with self._lock:
            slot = self._slots.get(index)
            if slot is None or slot.state is not SlotState.DEGRADED:
                return False
            slot.deaths.clear()
            slot.attempt = 0
            slot.state = SlotState.BACKOFF
            slot.not_before = 0.0
        return True

    # ---- introspection -----------------------------------------------------
    def addresses(self) -> List[str]:
        """Addresses of slots whose replica is (or is coming) up."""
        with self._lock:
            return [s.addr for s in self._slots.values()
                    if s.state in (SlotState.UP, SlotState.STARTING)
                    and s.addr]

    def active_count(self) -> int:
        """Slots the fleet can count on: UP/STARTING/BACKOFF (a slot in
        backoff is coming back; a DEGRADED or retired one is not)."""
        with self._lock:
            return sum(1 for s in self._slots.values()
                       if s.state in (SlotState.UP, SlotState.STARTING,
                                      SlotState.BACKOFF))

    def degraded_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots.values()
                       if s.state is SlotState.DEGRADED)

    def fleet_stats(self) -> Dict[str, Any]:
        """The supervisor's surface in serve.stats / CI assertions: every
        slot's state, address, restart count and recent-death count —
        DEGRADED (crash-looping) slots included, that is the point."""
        with self._lock:
            slots = [{"index": s.index, "state": s.state.value,
                      "addr": s.addr, "restarts": s.restarts,
                      "recent_deaths": len(s.deaths),
                      "attempt": s.attempt} for s in self._slots.values()]
        counts: Dict[str, int] = {}
        for s in slots:
            counts[s["state"]] = counts.get(s["state"], 0) + 1
        return {"slots": slots, "states": counts,
                "active": sum(1 for s in slots
                              if s["state"] in ("UP", "STARTING", "BACKOFF")),
                "degraded": counts.get("DEGRADED", 0)}
