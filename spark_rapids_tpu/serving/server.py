"""Network query service: the serving stack's front door.

PR 8 built a multi-tenant engine with no way in from outside the process —
``session.submit()`` is in-process only, so "millions of users"
(ROADMAP north star) was unreachable by construction. ``QueryServer``
puts the scheduler behind the wire protocol (serving/wire.py: Arrow IPC
over the PR 2 TCP framing/checksum/retry machinery):

- **streaming partial results**: each result batch rides to the client as
  its async D2H resolves (``QueryHandle.emit_batch`` ->
  ``ResultStream``), before the final batch exists; large batches slice
  into bounded wire frames (``serving.net.maxStreamBatchRows``);
- **bounded server state**: one parked (unacked) frame per query plus a
  depth-bounded stream — a slow client backpressures its own query's
  producer, never the server;
- **cancellation über alles**: client-initiated cancel and client
  disconnect (the transport's peer-lost signal) both release server-side
  resources through the PR 8 cooperative-cancel chain — semaphore holds,
  catalog buffers, parked frames, stream buffers;
- **N replicas, one cache**: servers sharing ``serving.cache.dir`` share
  the on-disk program-cache index (multi-process-safe by design), so a
  second replica warm-starts compiles behind the client's connection
  routing (client.py).

Handlers run on the transport's worker pool and every wait is bounded
(the R010 discipline): ``serve.next`` polls the stream for at most
``serving.net.nextPollMs`` before answering WAIT and freeing its thread.

CLI (the CI smoke / replica entry point)::

    python -m spark_rapids_tpu.serving.server --port 0 \
        --conf spark.rapids.tpu.sql.variableFloatAgg.enabled=true \
        --tpch-lineitem 0.01 --partitions 4

prints ``SERVING <host> <port>`` once the wire transport is bound.
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.serving import wire
from spark_rapids_tpu.serving.lifecycle import (OverloadedError,
                                                QuotaExceededError,
                                                ResultStream,
                                                SchedulerDrainingError)
from spark_rapids_tpu.shuffle.codec import checksum_of
from spark_rapids_tpu.shuffle.transport import AddressLengthTag
from spark_rapids_tpu.utils import metrics as um
from spark_rapids_tpu.utils import tracing as _tracing
from spark_rapids_tpu.utils.errors import encode_error, wire_boundary


class _ServedQuery:
    """Server-side state of one wire-submitted query: the scheduler
    handle, its result stream, the owning peer, and at most ONE parked
    (sent-but-unacked) wire frame kept for checksum-failure retransmit."""

    __slots__ = ("handle", "stream", "peer", "lock", "next_seq", "parked",
                 "slices", "resume_from")

    def __init__(self, handle, stream: ResultStream, peer: str,
                 resume_from: int = -1):
        self.handle = handle
        self.stream = stream
        self.peer = peer
        # re-entrant: _drop_query guards the parked/slices teardown and
        # is reached both bare (cancel, peer-lost, shutdown) and from
        # under the serve.next poll's hold (R012)
        self.lock = threading.RLock()
        self.next_seq = 0
        #: (seq, wire bytes, crc32) of the frame awaiting the client's ack
        self.parked: Optional[Tuple[int, bytes, int]] = None
        #: row-sliced remainders of an oversized exec batch, served next
        self.slices: List = []
        #: stream-resume failover: frames with seq <= resume_from were
        #: already delivered by the replica that died — the re-run skips
        #: them (dedup by seq, exactly-once delivery to the caller)
        self.resume_from = resume_from


class QueryServer:
    """One serving replica: wire handlers over one TpuSession/scheduler."""

    def __init__(self, session, conf=None, listen_port: Optional[int] = None):
        self.session = session
        base = conf or session.conf
        # serve.next handlers occupy a worker thread for up to nextPollMs;
        # give the serving transport a deeper pool than the shuffle default
        # so concurrent clients' polls do not head-of-line-block RPCs
        self.conf = base.with_overrides({
            cfg.SHUFFLE_TCP_WORKER_THREADS.key:
                max(base.get(cfg.SHUFFLE_TCP_WORKER_THREADS), 8)})
        self._poll_s = self.conf.get(cfg.SERVING_NET_POLL_MS) / 1e3
        self._stream_depth = self.conf.get(cfg.SERVING_NET_STREAM_DEPTH)
        self._max_rows = self.conf.get(cfg.SERVING_NET_MAX_STREAM_ROWS)
        #: per-client concurrent-query quota (0 = unlimited): enforced at
        #: the wire seam where the peer identity lives — the scheduler
        #: only knows tenants, and one tenant can span many clients
        self._quota_max = self.conf.get(cfg.SERVING_QUOTA_MAX_PER_CLIENT)
        self._retry_after_base = self.conf.get(cfg.SERVING_OVERLOAD_RETRY_AFTER)
        self._lock = threading.Lock()
        self._queries: Dict[int, _ServedQuery] = {}
        #: peers whose connection already died — a serve.submit dispatched
        #: just before the drop lands AFTER _on_peer_lost scanned
        #: _queries, so the handler must re-check and cancel immediately
        #: (client executor ids are uuid-unique, so a lost id never
        #: returns; bounded to the newest entries)
        self._lost_peers: "OrderedDict[str, None]" = OrderedDict()
        self._stop_event = threading.Event()
        #: graceful drain: new submits are rejected with a retryable
        #: redirect, running queries finish, streams flush, then
        #: serve_forever returns and the caller deregisters via shutdown
        self._draining = False
        self.transport = wire.make_serving_transport(
            f"query-server-{uuid.uuid4().hex[:8]}", self.conf, listen_port,
            registry_dir=self.conf.get(cfg.SERVING_NET_REGISTRY))
        server = self.transport.server
        server.register_request_handler(wire.REQ_SUBMIT, self._handle_submit)
        server.register_request_handler(wire.REQ_NEXT, self._handle_next)
        server.register_request_handler(wire.REQ_FETCH, self._handle_fetch)
        server.register_request_handler(wire.REQ_CANCEL, self._handle_cancel)
        server.register_request_handler(wire.REQ_REGISTER,
                                        self._handle_register)
        server.register_request_handler(wire.REQ_STATS, self._handle_stats)
        server.register_request_handler(wire.REQ_HEALTH, self._handle_health)
        server.register_request_handler(wire.REQ_DRAIN, self._handle_drain)
        # a vanished client is a cancellation: its queries release their
        # semaphore holds, catalog buffers and parked frames cooperatively
        self.transport.add_peer_lost_listener(self._on_peer_lost)
        # liveness heartbeat: refresh the registry-file mtime so replica
        # discovery (scan_registry + the liveness window) sees this
        # replica as alive; a killed transport stops refreshing and the
        # entry ages out — the SIGKILL story without SIGKILL
        if self.conf.get(cfg.SERVING_NET_REGISTRY):
            self._heartbeat_s = self.conf.get(cfg.SERVING_HEALTH_HEARTBEAT)
            threading.Thread(target=self._heartbeat_loop, daemon=True,
                             name="serving-heartbeat").start()
        # start the periodic gauge-sampler now, not at first submit: an
        # idle replica must report a FRESH serve_stats series (age_s near
        # the tick interval) or the autoscaler would treat it as unhealthy
        session.scheduler.start_stats_sampler()

    @property
    def address(self) -> Tuple[str, int]:
        t = self.transport
        inner = getattr(t, "_inner", None)   # fault wrapper pass-through
        return (inner or t).address

    # ---- handlers (transport worker threads; every wait bounded) -----------
    @staticmethod
    def _rejection(exc) -> bytes:
        """Structured front-door rejection: the taxonomy payload rides the
        SubmitResponse blob (query_id 0 — no handle exists) because the
        transport's exception path is a bare string that cannot carry
        retry_after_s across the wire."""
        return wire.SubmitResponse(0, error_json=json.dumps(
            encode_error(exc), default=str).encode()).to_bytes()

    # serializes taxonomy errors (overload shed / quota) into the
    # SubmitResponse blob — R015's wire seam, like _run_handle_traced
    @wire_boundary
    def _handle_submit(self, peer: str, payload: bytes) -> bytes:
        if self._draining:
            # retryable redirect: the type name rides the wire error and
            # the client reroutes the submission to another replica
            raise SchedulerDrainingError(
                "replica is draining; resubmit to another replica")
        req = wire.SubmitRequest.from_bytes(payload)
        if self._quota_max:
            with self._lock:
                open_for_peer = sum(1 for sq in self._queries.values()
                                    if sq.peer == peer)
            if open_for_peer >= self._quota_max:
                um.SERVING_METRICS[um.SERVING_QUOTA_REJECTIONS].add(1)
                return self._rejection(QuotaExceededError(
                    f"client {peer!r} at its concurrent-query quota "
                    f"({open_for_peer}/{self._quota_max}); retry after "
                    f"your own queries finish",
                    retry_after_s=self._retry_after_base))
        stream = ResultStream(depth=self._stream_depth)
        try:
            handle = self.session.scheduler.submit(
                req.sql, tenant=req.tenant,
                timeout=(req.timeout if req.timeout > 0 else None),
                label=req.label or None, stream=stream)
        except OverloadedError as e:
            # shed at the scheduler's per-tenant bound: ship the
            # structured rejection (code + retry_after_s) to the client
            return self._rejection(e)
        sq = _ServedQuery(handle, stream, peer, resume_from=req.resume_from)
        with self._lock:
            self._queries[handle.query_id] = sq
            # close the submit-vs-disconnect race: if this peer's
            # connection died while the request sat in the worker queue,
            # _on_peer_lost already scanned _queries and missed this
            # entry — cancel it here instead of leaving it to run for a
            # client that is gone
            raced_lost = peer in self._lost_peers
            if raced_lost:
                self._queries.pop(handle.query_id, None)
        if raced_lost:
            handle.cancel()
            stream.abandon()
            raise ConnectionError(f"peer {peer!r} disconnected")
        return wire.SubmitResponse(handle.query_id).to_bytes()

    def _lookup(self, query_id: int, peer: str) -> _ServedQuery:
        with self._lock:
            sq = self._queries.get(query_id)
        if sq is None or sq.peer != peer:
            raise KeyError(f"unknown query id {query_id} for peer {peer!r}")
        return sq

    def _park_locked(self, sq: _ServedQuery, table) -> Optional[bytes]:
        seq = sq.next_seq
        sq.next_seq += 1
        if seq <= sq.resume_from:
            # resumed query: the client already holds this frame from the
            # replica that died — skip it (dedup by seq, never re-sent)
            um.SERVING_METRICS[um.SERVING_RESUMED_BATCHES].add(1)
            return None
        data = wire.table_to_ipc(table)
        sq.parked = (seq, data, checksum_of(data))
        um.SERVING_METRICS[um.SERVING_STREAM_BATCHES].add(1)
        return wire.NextResponse(wire.NEXT_BATCH, seq=seq, nbytes=len(data),
                                 checksum=sq.parked[2]).to_bytes()

    def _serve_slices_locked(self, sq: _ServedQuery) -> Optional[bytes]:
        while sq.slices:
            resp = self._park_locked(sq, sq.slices.pop(0))
            if resp is not None:
                return resp
        return None

    def _slice(self, table) -> List:
        if self._max_rows <= 0 or table.num_rows <= self._max_rows:
            return [table]
        return [table.slice(off, self._max_rows)
                for off in range(0, table.num_rows, self._max_rows)]

    def _handle_next(self, peer: str, payload: bytes) -> bytes:
        req = wire.NextRequest.from_bytes(payload)
        sq = self._lookup(req.query_id, peer)
        deadline = time.monotonic() + self._poll_s
        with sq.lock:
            if req.ack_seq >= 0 and sq.parked is not None \
                    and sq.parked[0] == req.ack_seq:
                sq.parked = None
            if sq.parked is not None:       # unacked frame: re-offer it
                seq, data, crc = sq.parked
                return wire.NextResponse(
                    wire.NEXT_BATCH, seq=seq, nbytes=len(data),
                    checksum=crc).to_bytes()
            resp = self._serve_slices_locked(sq)
            if resp is not None:
                return resp
        # poll the stream OUTSIDE the query lock, bounded: a dry stream
        # answers WAIT and frees this worker thread for other clients.
        # The loop exists for resumed queries — a batch whose every slice
        # was already delivered (skipped by seq) keeps draining within
        # the same bounded poll budget instead of burning a WAIT per skip
        while True:
            left = max(0.0, deadline - time.monotonic())
            kind, val = sq.stream.next(timeout=left)
            with sq.lock:
                if kind == "batch":
                    sq.slices.extend(self._slice(val))
                    resp = self._serve_slices_locked(sq)
                    if resp is not None:
                        return resp
                elif kind == "done":
                    return self._finish_response(sq)
                elif kind == "error":
                    self._drop_query(sq)
                    # structured codec (utils/errors.py): registered types
                    # survive the wire with their classification and
                    # payload; anything else degrades to OPAQUE
                    return wire.NextResponse(
                        wire.NEXT_ERROR,
                        error=json.dumps(encode_error(val),
                                         default=str)).to_bytes()
                else:
                    return wire.NextResponse(wire.NEXT_WAIT).to_bytes()
            if time.monotonic() >= deadline:
                return wire.NextResponse(wire.NEXT_WAIT).to_bytes()

    def _finish_response(self, sq: _ServedQuery) -> bytes:
        result = sq.handle.result(timeout=5.0)
        snap = sq.handle.snapshot()
        self._drop_query(sq)
        return wire.NextResponse(
            wire.NEXT_DONE, batches=sq.next_seq,
            metrics_json=json.dumps(snap, default=str).encode(),
            schema_ipc=wire.schema_to_ipc(result.schema)).to_bytes()

    def _drop_query(self, sq: _ServedQuery) -> None:
        # under sq.lock: a cancel/peer-lost teardown must not clear the
        # slice list out from under a serve.next handler mid-pop (R012)
        with sq.lock:
            sq.parked = None
            sq.slices.clear()
        with self._lock:
            self._queries.pop(sq.handle.query_id, None)

    def _handle_fetch(self, peer: str, payload: bytes) -> bytes:
        req = wire.FetchRequest.from_bytes(payload)
        sq = self._lookup(req.query_id, peer)
        with sq.lock:
            parked = sq.parked
        if parked is None or parked[0] != req.seq:
            raise KeyError(f"no frame {req.seq} parked for query "
                           f"{req.query_id}")
        _seq, data, _crc = parked
        # the data plane: one tag-addressed frame through the shuffle
        # transport's server send path (where the chaos harness probes
        # corrupt/delay/dup — exactly like a shuffle block)
        with _tracing.span("serving.wire_frame", "serving",
                           {"bytes": len(data), "seq": _seq,
                            "query_id": req.query_id}):
            self.transport.server.send(
                peer, AddressLengthTag.for_bytes(data, req.tag),
                lambda tx: None)
        um.SERVING_METRICS[um.SERVING_WIRE_BYTES_OUT].add(len(data))
        return b""

    def _handle_cancel(self, peer: str, payload: bytes) -> bytes:
        """Client-initiated cancel: besides flagging the handle (the
        cooperative chain releases its permit and buffers), the client is
        DONE with this stream — abandon it so the producer never blocks
        on a reader that stopped pulling, and free the parked frame."""
        req = wire.CancelRequest.from_bytes(payload)
        sq = self._lookup(req.query_id, peer)
        sq.handle.cancel()
        sq.stream.abandon()
        self._drop_query(sq)
        return b""

    def _handle_register(self, peer: str, payload: bytes) -> bytes:
        req = wire.RegisterRequest.from_bytes(payload)   # crc-verified
        table = wire.ipc_to_table(req.ipc)
        df = self.session.create_dataframe(table)
        df.createOrReplaceTempView(req.name)
        return b""

    def _queries_open(self) -> int:
        with self._lock:
            return len(self._queries)

    def _handle_stats(self, peer: str, payload: bytes) -> bytes:
        sched = self.session.scheduler
        out = {"scheduler": sched.stats(),
               "serving": um.SERVING_METRICS.snapshot(),
               # lineage-recompute story: how often this replica repaired a
               # lost shuffle block by scoped re-execution instead of
               # failing the query over to another replica
               "shuffle": um.RECOMPUTE_METRICS.snapshot(),
               "queries_open": self._queries_open(),
               "state": "DRAINING" if self._draining else "UP",
               # the rolling time-series load-aware routing consumes:
               # device budget in use, queue depths, running/queued per
               # tenant, p50/p99 query wall over the window — computed
               # server-side (serving/stats.py), shipped as JSON
               "serve_stats": sched.serve_stats.snapshot(sched)}
        return json.dumps(out, default=str).encode()

    def _handle_health(self, peer: str, payload: bytes) -> bytes:
        """Liveness + load probe: what circuit-breaker probes and
        load-aware routing consume — replica state plus the PR 13
        serve_stats rolling time-series (free budget after footprint
        charges, queue depths, p50/p99 wall)."""
        sched = self.session.scheduler
        return json.dumps({
            "state": "DRAINING" if self._draining else "UP",
            #: per-process identity: a restarted replica behind the same
            #: address reports a NEW id, telling clients to replay their
            #: temp-view registrations instead of trusting a stale ledger
            "replica_id": self.transport.executor_id,
            "queries_open": self._queries_open(),
            "serve_stats": sched.serve_stats.snapshot(sched),
        }, default=str).encode()

    def _handle_drain(self, peer: str, payload: bytes) -> bytes:
        self.drain()
        return json.dumps({"state": "DRAINING",
                           "queries_open": self._queries_open()}).encode()

    # ---- lifecycle ---------------------------------------------------------
    def drain(self) -> None:
        """Graceful drain (serve.drain RPC / SIGTERM): flip to DRAINING —
        new submissions are rejected with the retryable redirect, the
        scheduler stops accepting work, running queries finish and their
        streams flush — then serve_forever notices the empty query table
        and returns so the caller deregisters (transport shutdown removes
        the registry entry) and exits."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self.session.scheduler.start_draining()
        um.SERVING_METRICS[um.SERVING_DRAINS].add(1)

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        """True once a DRAINING replica has nothing left to serve: every
        wire stream flushed (its query left ``_queries`` at DONE/ERROR)
        and every scheduler handle is terminal."""
        if not self._draining:
            return False
        with self._lock:
            if self._queries:
                return False
        return self.session.scheduler.drain(timeout=0)

    def _heartbeat_loop(self) -> None:
        while not self._stop_event.wait(self._heartbeat_s):
            self.transport.heartbeat()
    def _on_peer_lost(self, peer_id: str) -> None:
        """A client's connection died mid-stream: cancel its queries (the
        cooperative chain releases device-semaphore holds and catalog
        buffers), abandon their streams so producers never block on a
        reader that is gone, and free every parked frame."""
        with self._lock:
            self._lost_peers[peer_id] = None
            while len(self._lost_peers) > 1024:
                self._lost_peers.popitem(last=False)
            lost = [sq for sq in self._queries.values() if sq.peer == peer_id]
            for sq in lost:
                self._queries.pop(sq.handle.query_id, None)
        for sq in lost:
            sq.handle.cancel()
            sq.stream.abandon()
            with sq.lock:
                sq.parked = None
                sq.slices.clear()

    def serve_forever(self) -> None:
        """Block until shutdown() — or, once drain() flipped the replica
        to DRAINING, until every running query finished and every stream
        flushed. A BOUNDED poll (the R010 accept-loop discipline — an
        unbounded wait here would pin the process through signals and
        shutdown races), interrupt-friendly."""
        while not self._stop_event.wait(0.5):
            if self.drained():
                return

    def shutdown(self) -> None:
        self._stop_event.set()
        with self._lock:
            open_queries = list(self._queries.values())
            self._queries.clear()
        for sq in open_queries:
            sq.handle.cancel()
            sq.stream.abandon()
        self.transport.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="spark_rapids_tpu.serving.server")
    ap.add_argument("--port", type=int, default=None,
                    help="listen port (default serving.net.listenPort)")
    ap.add_argument("--conf", action="append", default=[],
                    metavar="KEY=VALUE")
    ap.add_argument("--tpch-lineitem", type=float, default=None,
                    metavar="SCALE",
                    help="register a generated TPC-H lineitem view")
    ap.add_argument("--partitions", type=int, default=1,
                    help="repartition registered views (multi-batch "
                         "result streams)")
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)
    conf = {}
    for kv in args.conf:
        key, _, val = kv.partition("=")
        conf[key] = val
    from spark_rapids_tpu.api.dataframe import TpuSession
    session = TpuSession(conf)
    _ = session.scheduler       # wire the program-cache index pre-compile
    if args.tpch_lineitem is not None:
        from spark_rapids_tpu.benchmarks.tpch import gen_lineitem
        df = session.create_dataframe(
            gen_lineitem(scale=args.tpch_lineitem, seed=args.seed))
        if args.partitions > 1:
            df = df.repartition(args.partitions)
        df.createOrReplaceTempView("lineitem")
    server = QueryServer(session, listen_port=args.port)
    host, port = server.address
    print(f"SERVING {host} {port}", flush=True)

    # SIGTERM = graceful drain (the orchestrator's stop signal): running
    # queries finish and streams flush before the process deregisters and
    # exits; a SECOND SIGTERM forces immediate shutdown
    import signal

    def _on_sigterm(signum, frame):
        if server.draining:
            server._stop_event.set()
        else:
            server.drain()
    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass                    # not the main thread (embedded use)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        session.scheduler.shutdown(wait=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
