"""R009: lock-order inversion across the engine's lock domains.

PR 8 multiplied the lock inventory: the device-admission semaphore's
condition, the buffer catalog + per-tier store locks, the scan-cache and
df-cache locks, the program-cache lock, and the shuffle client/transport
locks all now run under concurrent queries. A cycle in the order those
locks are ACQUIRED — thread 1 takes A then B, thread 2 takes B then A —
is a deadlock that no single file shows, and that strikes only under
contention (i.e. in production, not in tests).

The check builds the package's static lock graph:

- a lock ACQUISITION is ``with <expr>:`` where the expression is a plain
  name/attribute whose name contains ``lock``/``cond``/``mutex``/``cv``
  (the repo's naming convention — R006 relies on the same one);
- lock IDENTITY is (module, owning class, attribute name); ``self._lock``
  in a subclass method resolves to the topmost package base class so one
  hierarchy's lock is one node (the BufferStore tiers share identity —
  also why same-node edges are ignored: re-entrant by design, and an
  A->A "cycle" is not an ordering inversion);
- an EDGE A -> B exists when, lexically inside a ``with A`` body, B is
  acquired — directly, or anywhere within ``max_depth`` call-graph hops
  of a call made while holding A (callgraph.py resolution);
- CYCLES among >= 2 distinct locks are reported once each, with the
  acquisition sites that close them.

A justified inversion (there should be none; a lock handoff protocol
would be one) carries an inline suppression on the inner acquisition.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.callgraph import CallGraph, graph_for
from spark_rapids_tpu.analysis.cfg import iter_functions, walk_local
from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            dotted_name, register)

_LOCK_HINTS = ("lock", "cond", "mutex", "_cv")
#: call-graph hops a held lock's edges extend through
_MAX_DEPTH = 5

LockId = Tuple[str, str, str]          # (module, owner, attr/name)
Site = Tuple[str, int]                 # (module, lineno)


def _is_lock_expr(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if not name:
        return False
    leaf = name.split(".")[-1].lower()
    return any(h in leaf for h in _LOCK_HINTS)


def _lock_root_class(graph: CallGraph, cls_name: Optional[str]
                     ) -> Optional[str]:
    """Topmost package base class — one lock node per hierarchy."""
    if cls_name is None:
        return None
    seen = set()
    cur = cls_name
    while cur not in seen:
        seen.add(cur)
        ci = graph.classes.get(cur)
        if ci is None or not ci.bases:
            return cur
        nxt = next((b for b in ci.bases if b in graph.classes), None)
        if nxt is None:
            return cur
        cur = nxt
    return cur


def _lock_identity(graph: CallGraph, src: SourceFile, func_qualname: str,
                   expr: ast.AST) -> LockId:
    name = dotted_name(expr)
    parts = name.split(".")
    cls = func_qualname.split(".")[-2] if "." in func_qualname else None
    if parts[0] == "self" and len(parts) == 2:
        owner = _lock_root_class(graph, cls) or (cls or "")
        return (_owner_module(graph, owner) or src.display_path,
                owner, parts[1])
    # non-self receiver (e.lock, plock, module global): scope by module +
    # expression text — distinct objects stay distinct (conservative:
    # may MISS a cycle through an aliased lock, never invents one)
    return (src.display_path, func_qualname, name)


def _owner_module(graph: CallGraph, owner: str) -> Optional[str]:
    ci = graph.classes.get(owner)
    return ci.module if ci is not None else None


class _LockGraph:
    def __init__(self):
        #: edge (A, B) -> sites where it is established
        self.edges: Dict[Tuple[LockId, LockId], List[Site]] = {}
        #: lock acquisitions per function key: (lock, With node, src)
        self.acquisitions: Dict[str, List[Tuple[LockId, ast.With,
                                                SourceFile]]] = {}

    def add_edge(self, a: LockId, b: LockId, site: Site) -> None:
        if a == b:
            return                      # re-entrant / same-hierarchy: not
        self.edges.setdefault((a, b), []).append(site)

    def cycles(self) -> List[List[LockId]]:
        """Elementary cycles via SCC + per-SCC DFS (the graph is tiny)."""
        adj: Dict[LockId, Set[LockId]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        sccs = _tarjan(adj)
        out: List[List[LockId]] = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            comp_sorted = sorted(comp)
            start = comp_sorted[0]
            cycle = _find_cycle(adj, set(comp), start)
            if cycle:
                out.append(cycle)
        return out


def _tarjan(adj: Dict[LockId, Set[LockId]]) -> List[List[LockId]]:
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    onstack: Set[LockId] = set()
    stack: List[LockId] = []
    counter = [0]
    out: List[List[LockId]] = []

    def strong(v: LockId):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in onstack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                onstack.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    for v in list(adj):
        if v not in index:
            strong(v)
    return out


def _find_cycle(adj: Dict[LockId, Set[LockId]], comp: Set[LockId],
                start: LockId) -> Optional[List[LockId]]:
    path = [start]
    seen = {start}

    def dfs(v: LockId) -> Optional[List[LockId]]:
        for w in sorted(adj.get(v, ())):
            if w not in comp:
                continue
            if w == start and len(path) >= 2:
                return list(path)
            if w not in seen:
                seen.add(w)
                path.append(w)
                got = dfs(w)
                if got:
                    return got
                path.pop()
        return None

    return dfs(start)


@register
class LockOrderInversion(Rule):
    rule_id = "R009"
    title = "lock-order inversion (cycle in the static lock graph)"
    is_project_rule = True

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        graph = graph_for(files)
        lg = _LockGraph()
        by_key = {}
        for src in files:
            for qualname, node in iter_functions(src.tree):
                key = f"{src.display_path}::{qualname}"
                acqs: List[Tuple[LockId, ast.With, SourceFile]] = []
                # walk_local: a nested def's acquisitions belong to the
                # nested function (its own iter_functions entry), which may
                # run on a different thread at a different time — counting
                # them as held HERE invents lock-order edges
                for n in walk_local(node):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            if _is_lock_expr(item.context_expr):
                                acqs.append((_lock_identity(
                                    graph, src, qualname,
                                    item.context_expr), n, src))
                lg.acquisitions[key] = acqs
                by_key[key] = (src, qualname, node)

        # locks each function may acquire within _MAX_DEPTH hops
        summary: Dict[str, Set[LockId]] = {}

        def locks_below(key: str) -> Set[LockId]:
            if key in summary:
                return summary[key]
            out: Set[LockId] = set()
            for k in graph.reachable([key], max_depth=_MAX_DEPTH):
                for (lock, _n, _s) in lg.acquisitions.get(k, ()):
                    out.add(lock)
            summary[key] = out
            return out

        for key, (src, qualname, node) in by_key.items():
            for (outer_lock, with_node, _s) in lg.acquisitions.get(key, ()):
                # walk_local again: a closure defined under the lock does
                # not RUN under the lock
                for inner in walk_local(with_node):
                    site = (src.display_path,
                            getattr(inner, "lineno", with_node.lineno))
                    if isinstance(inner, (ast.With, ast.AsyncWith)):
                        for item in inner.items:
                            if _is_lock_expr(item.context_expr):
                                if src.is_suppressed(self.rule_id,
                                                     item.context_expr.lineno
                                                     if hasattr(
                                                         item.context_expr,
                                                         "lineno")
                                                     else inner.lineno):
                                    continue
                                lg.add_edge(outer_lock, _lock_identity(
                                    graph, src, qualname,
                                    item.context_expr), site)
                    elif isinstance(inner, ast.Call):
                        if src.is_suppressed(self.rule_id, inner.lineno):
                            continue
                        info_key = f"{src.display_path}::{qualname}"
                        caller = graph.functions.get(info_key)
                        if caller is None:
                            continue
                        for callee in graph.resolve_call(caller, inner):
                            for lock in locks_below(callee):
                                lg.add_edge(outer_lock, lock, site)

        findings: List[Finding] = []
        for cycle in lg.cycles():
            names = " -> ".join(f"{m}:{o}.{a}" if o else f"{m}:{a}"
                                for (m, o, a) in cycle)
            sites = []
            for i, a in enumerate(cycle):
                b = cycle[(i + 1) % len(cycle)]
                for (mod, line) in lg.edges.get((a, b), [])[:1]:
                    sites.append(f"{mod}:{line}")
            fake = ast.Pass()
            fake.lineno = 1
            anchor_mod = sites[0].rsplit(":", 1) if sites else None
            src0 = next((f for f in files
                         if anchor_mod and
                         f.display_path == anchor_mod[0]), files[0])
            if anchor_mod:
                fake.lineno = int(anchor_mod[1])
            findings.append(src0.finding(
                self.rule_id, fake,
                f"lock-order cycle: {names} -> (back to start); "
                f"acquisition sites {', '.join(sites)}: two threads taking "
                f"these locks in opposite orders deadlock under "
                f"contention; impose one global order (acquire the "
                f"first-named lock first everywhere) or restructure so "
                f"one side copies state and releases before calling down"))
        return findings
