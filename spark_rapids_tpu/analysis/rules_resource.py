"""R008: resource leaks — an acquire whose release is skipped on some path.

PR 8's multi-tenant serving contract is built on unwind hygiene: every
device-semaphore hold, every retained catalog buffer, every in-flight
build latch must be released on EVERY path out of the function that took
it — the pre-fix catalog remove-vs-spill leak cost an 8-thread hammer
test to find, exactly the class of bug a premerge gate should catch.

The check runs the forward dataflow (dataflow.py) over each function's
CFG (cfg.py), tracking four acquire kinds:

- **catalog retain** — ``x = <...>catalog.acquire(...)`` / ``x.retain()``
  retains a spillable buffer; released by ``x.close()`` (or
  ``close_all`` containing x). Handing the buffer off — returning or
  yielding it, storing it into an attribute/subscript, appending it to a
  container — transfers ownership and ends tracking.
- **semaphore hold** — ``<recv>.acquire_if_necessary(...)`` paired with
  ``<recv>.release_if_necessary(...)`` on the same receiver (scoped
  ``with sem.held():`` is auto-released and never tracked).
- **admission permit** — bare ``<recv>.acquire(...)`` on a receiver whose
  name contains ``throttle``/``sem``, paired with ``<recv>.release(...)``;
  a nested def in the same function releasing the receiver counts as a
  deferred-release handoff (the shuffle client's ``release_once`` closure).
- **build latch** — ``container[key] = ev`` where ``ev`` was created by
  ``threading.Event()``; released by ``ev.set()`` or by popping/deleting
  from the container (the scan-cache / program-cache latch idiom).
- **connection handle** — ``x = <...transport...>.connect(...)`` dials a
  peer (a socket + reader thread in the TCP transport); released by
  ``x.close()`` or handed off — stored into a cache (the
  manager/client connection-cache idiom), returned, or passed into a
  wrapping constructor (``c = ShuffleClient(transport, conn, ...)``).
  A connect that escapes on an early-exit path leaks the socket AND
  desyncs the peer's hello handshake — the serving wire layer's new
  resource kind.

Branch sensitivity: the edge transfer kills a buffer token on the branch
that proved it None (``if buf is None: return`` leaks nothing), so the
acquire-then-guard idiom stays clean without suppressions.

Explicit paths only: a leak on an implicit exception path (a call that
might raise) is not flagged — wrap real cleanup in try/finally and the
finally path is modeled. ``raise`` statements ARE paths.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from spark_rapids_tpu.analysis import dataflow
from spark_rapids_tpu.analysis.cfg import (FALSE, TRUE, Block, Cond,
                                           WithEnter, build_cfg,
                                           iter_functions)
from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, dotted_name, register)

#: token: (kind, key, extra, acquire lineno)
Token = Tuple[str, str, str, int]

_CONSUME_ATTRS = {"append", "add", "put", "insert", "extend", "setdefault"}


def _call_of(stmt) -> Optional[ast.Call]:
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        return stmt.value
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _FuncAnalysis:
    """One function's R008 pass."""

    def __init__(self, src: SourceFile, qualname: str, node):
        self.src = src
        self.qualname = qualname
        self.node = node
        #: vars assigned threading.Event() anywhere in the function
        self.event_vars = self._scan_event_vars()
        #: permit receivers released by a nested def (deferred release)
        self.deferred_releases = self._scan_deferred_releases()
        self.nested = {id(n) for _qn, n in iter_functions(node)}
        #: id(item) -> precomputed (kills, gens) action list; the transfer
        #: runs once per fixpoint visit, so the AST walk must happen once
        #: per STATEMENT, not once per visit
        self._actions: Dict[int, List[Tuple[str, tuple]]] = {}

    def _scan_event_vars(self) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(self.node):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                cname = call_name(n.value)
                if cname.split(".")[-1] == "Event":
                    out.update(t.id for t in n.targets
                               if isinstance(t, ast.Name))
        return out

    def _scan_deferred_releases(self) -> Set[str]:
        out: Set[str] = set()
        for _qn, nested in iter_functions(self.node):
            for n in ast.walk(nested):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("release", "release_if_necessary"):
                    out.add(dotted_name(n.func.value))
        return out

    def _in_nested(self, node: ast.AST) -> bool:
        cur = self.src.parent(node)
        while cur is not None and cur is not self.node:
            if id(cur) in self.nested:
                return True
            cur = self.src.parent(cur)
        return False

    # ---- transfer -----------------------------------------------------------
    def _compute_actions(self, item) -> List[Tuple[str, tuple]]:
        """Precomputed ordered action list for one block item: kills and
        handoffs before gens, so `x = y.acquire()` over a previous acquire
        into x reads as a rebind, not a double hold."""
        if not isinstance(item, (ast.Assign, ast.AugAssign, ast.Expr,
                                 ast.Return, ast.Delete, ast.Assert,
                                 ast.Raise)):
            return []
        kills: List[Tuple[str, tuple]] = []
        gens: List[Tuple[str, tuple]] = []
        calls = [n for n in ast.walk(item)
                 if isinstance(n, ast.Call) and not self._in_nested(n)]
        for call in calls:
            if not isinstance(call.func, ast.Attribute):
                fname = call_name(call).split(".")[-1]
                if fname == "close_all" and call.args:
                    names = set()
                    for a in call.args:
                        names |= _names_in(a)
                    kills.append(("kill_buffer_names", (frozenset(names),)))
                continue
            attr = call.func.attr
            recv = dotted_name(call.func.value)
            line = call.lineno
            if attr == "close":
                kills.append(("kill_buffer_names", (frozenset({recv}),)))
            elif attr == "release_if_necessary":
                kills.append(("kill_sem", (recv,)))
            elif attr == "release":
                kills.append(("kill_permit", (recv,)))
            elif attr == "set":
                kills.append(("kill_latch_ev", (recv,)))
            elif attr == "pop":
                kills.append(("kill_latch_cont", (recv,)))
            elif attr == "acquire_if_necessary":
                gens.append(("gen", ("semaphore", recv, "", line)))
            elif attr == "retain":
                gens.append(("gen", ("buffer", recv, "", line)))
            elif attr == "acquire" and "catalog" in recv.lower():
                if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name) \
                        and item.value is call:
                    gens.append(("gen", ("buffer", item.targets[0].id,
                                         "", line)))
            elif attr == "acquire" and any(
                    h in recv.lower() for h in ("throttle", "sem")):
                if recv not in self.deferred_releases:
                    gens.append(("gen", ("permit", recv, "", line)))
            elif attr == "connect" and "transport" in recv.lower():
                if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name) \
                        and item.value is call:
                    gens.append(("gen", ("connection", item.targets[0].id,
                                         "", line)))

        # `x = None` drops the binding: whatever x held was released or
        # handed off out-of-band (the explicit-discard idiom)
        if isinstance(item, ast.Assign) and \
                isinstance(item.value, ast.Constant) and \
                item.value.value is None:
            dropped = frozenset(t.id for t in item.targets
                                if isinstance(t, ast.Name))
            if dropped:
                kills.append(("kill_buffer_names", (dropped,)))

        if isinstance(item, ast.Delete):
            for tgt in item.targets:
                if isinstance(tgt, ast.Subscript):
                    kills.append(("kill_latch_cont",
                                  (dotted_name(tgt.value),)))

        # handoffs: return/yield value, store into attribute/subscript,
        # append-style consumption
        handoff_exprs: List[ast.AST] = []
        if isinstance(item, ast.Return) and item.value is not None:
            handoff_exprs.append(item.value)
        for n in ast.walk(item):
            if isinstance(n, (ast.Yield, ast.YieldFrom)) and \
                    n.value is not None:
                handoff_exprs.append(n.value)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _CONSUME_ATTRS:
                handoff_exprs.extend(n.args)
        if isinstance(item, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in item.targets):
                handoff_exprs.append(item.value)
        handed: Set[str] = set()
        for expr in handoff_exprs:
            handed |= _names_in(expr)
        if handed:
            kills.append(("kill_buffer_names", (frozenset(handed),)))

        # connection handoff-by-wrapping: a connection passed into a call
        # whose result is BOUND (``c = ShuffleClient(transport, conn, ..)``)
        # transfers ownership to the wrapper — the cached-client idiom.
        # Scoped to the connection kind: buffers used via method calls must
        # still close, only wrapping constructors adopt connections.
        if isinstance(item, ast.Assign) and isinstance(item.value, ast.Call):
            wrapped: Set[str] = set()
            for a in item.value.args:
                wrapped |= _names_in(a)
            if wrapped:
                kills.append(("kill_conn_names", (frozenset(wrapped),)))

        # latch publish: container[key] = ev
        if isinstance(item, ast.Assign) and len(item.targets) == 1 and \
                isinstance(item.targets[0], ast.Subscript) and \
                isinstance(item.value, ast.Name) and \
                item.value.id in self.event_vars:
            recv = dotted_name(item.targets[0].value)
            gens.append(("gen", ("latch", recv, item.value.id,
                                 item.lineno)))
        return kills + gens

    def transfer(self, state: FrozenSet[Token], item, block: Block
                 ) -> FrozenSet[Token]:
        if isinstance(item, WithEnter):
            # with-acquired resources are scoped (auto-released)
            return state
        actions = self._actions.get(id(item))
        if actions is None:
            actions = self._compute_actions(item)
            self._actions[id(item)] = actions
        if not actions:
            return state
        out = set(state)
        for (op, args) in actions:
            if op == "kill_buffer_names":
                # name-keyed kinds share the close/handoff discipline
                names = args[0]
                out = {t for t in out
                       if not (t[0] in ("buffer", "connection")
                               and t[1] in names)}
            elif op == "kill_conn_names":
                names = args[0]
                out = {t for t in out
                       if not (t[0] == "connection" and t[1] in names)}
            elif op == "kill_sem":
                out = {t for t in out
                       if not (t[0] == "semaphore" and t[1] == args[0])}
            elif op == "kill_permit":
                out = {t for t in out
                       if not (t[0] == "permit" and t[1] == args[0])}
            elif op == "kill_latch_ev":
                out = {t for t in out
                       if not (t[0] == "latch" and t[2] == args[0])}
            elif op == "kill_latch_cont":
                out = {t for t in out
                       if not (t[0] == "latch" and t[1] == args[0])}
            elif op == "gen":
                kind, key, extra, line = args
                out = {t for t in out
                       if not (t[0] == kind and t[1] == key)}
                out.add((kind, key, extra, line))
        return frozenset(out)

    # ---- branch-sensitive None kills ---------------------------------------
    @staticmethod
    def edge_transfer(state: FrozenSet[Token], block: Block,
                      label: Optional[str]) -> FrozenSet[Token]:
        if label not in (TRUE, FALSE) or not block.items:
            return state
        last = block.items[-1]
        if not isinstance(last, Cond):
            return state
        none_names = _none_test_names(last.test)
        if not none_names:
            return state
        names, none_on = none_names
        if (none_on == TRUE and label == TRUE) or \
                (none_on == FALSE and label == FALSE):
            return frozenset(t for t in state
                             if not (t[0] in ("buffer", "connection")
                                     and t[1] in names))
        return state


def _none_test_names(test: ast.expr
                     ) -> Optional[Tuple[Set[str], str]]:
    """(names, edge-on-which-they-are-None): ``x is None`` -> True edge,
    ``x is not None`` / bare ``x`` -> False edge, ``not x`` -> True edge."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
            isinstance(test.left, ast.Name) and \
            isinstance(test.comparators[0], ast.Constant) and \
            test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return {test.left.id}, TRUE
        if isinstance(test.ops[0], ast.IsNot):
            return {test.left.id}, FALSE
    if isinstance(test, ast.Name):
        return {test.id}, FALSE
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) and \
            isinstance(test.operand, ast.Name):
        return {test.operand.id}, TRUE
    return None


_KIND_HINT = {
    "buffer": "retained buffer never close()d",
    "semaphore": "semaphore hold never release_if_necessary()d",
    "permit": "admission permit never release()d",
    "latch": "build latch never set/popped — waiters block forever",
    "connection": "connection handle never close()d or handed off — "
                  "the socket and its reader thread leak",
}


@register
class ResourceLeak(Rule):
    rule_id = "R008"
    title = "acquire escapes the function without release on some path"

    #: attr names whose presence makes a function worth the CFG pass
    _TRIGGERS = frozenset({"acquire", "retain", "acquire_if_necessary",
                           "Event", "connect"})

    def check(self, src: SourceFile) -> List[Finding]:
        # one cheap pre-pass: the dataflow only ever generates tokens from
        # these call shapes, so a function without any of them is clean by
        # construction and skips CFG construction entirely
        interesting: Set[int] = set()
        for n in ast.walk(src.tree):
            name = ""
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute):
                    name = n.func.attr
                else:
                    name = call_name(n).split(".")[-1]
            if name not in self._TRIGGERS:
                continue
            cur = src.parent(n)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    interesting.add(id(cur))
                    break
                cur = src.parent(cur)
        if not interesting:
            return []
        findings: List[Finding] = []
        for qualname, node in iter_functions(src.tree):
            if id(node) in interesting:
                findings.extend(self._check_function(src, qualname, node))
        return findings

    def _check_function(self, src: SourceFile, qualname: str,
                        node) -> List[Finding]:
        fa = _FuncAnalysis(src, qualname, node)
        cfg = build_cfg(node)
        states = dataflow.run_forward(cfg, fa.transfer,
                                      edge_transfer=fa.edge_transfer)
        leaked: Dict[Token, Set[int]] = {}
        for bid, block in cfg.blocks.items():
            if not any(t == cfg.exit for (t, _l) in block.succs):
                continue
            if bid not in states:
                continue                       # unreachable
            out = dataflow.block_out_state(cfg, bid, states, fa.transfer)
            for (t, label) in block.succs:
                if t != cfg.exit:
                    continue
                escaped = fa.edge_transfer(out, block, label)
                for token in escaped:
                    leaked.setdefault(token, set()).add(
                        block.last_lineno() or node.lineno)
        findings: List[Finding] = []
        for token in sorted(leaked, key=lambda t: t[3]):
            kind, key, _extra, line = token
            exits = sorted(leaked[token])
            fake = ast.Pass()
            fake.lineno = line
            findings.append(src.finding(
                self.rule_id, fake,
                f"{qualname}: {_KIND_HINT[kind]} — acquired here "
                f"('{key}'), but a path exiting near line"
                f"{'s' if len(exits) > 1 else ''} "
                f"{', '.join(map(str, exits))} escapes still holding it; "
                f"release in a finally, scope it with a context manager, "
                f"or hand it off explicitly (return/store); a designed "
                f"handoff gets an inline suppression with its "
                f"justification"))
        return findings
