"""tpu-lint CLI.

Usage:
    python -m spark_rapids_tpu.analysis [paths...] [options]

With no paths, lints the spark_rapids_tpu package itself. Exit status is 0
when no non-baselined findings remain, 1 otherwise.

Options:
    --strict           ignore the baseline (nightly mode: grandfathered
                       debt stays visible)
    --baseline PATH    baseline file (default ci/tpu-lint-baseline.json)
    --write-baseline   write current findings as a baseline skeleton
                       (justifications left empty; the file will not load
                       until they are filled in)
    --rules IDS        comma-separated rule subset, e.g. R001,R004
    --list-rules       print the rule catalog and exit
    --check-configs    verify docs/configs.md matches the registry (the
                       premerge docs-sync gate; R004 drift runs in the
                       normal lint pass with baseline semantics)
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from spark_rapids_tpu.analysis import baseline as bl
from spark_rapids_tpu.analysis.core import (AnalysisResult, SourceFile,
                                            all_rules, analyze_files,
                                            load_source)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_files(paths: List[str], root: str,
                  errors: Optional[List[str]] = None) -> List[SourceFile]:
    files: List[SourceFile] = []
    seen = set()
    for p in paths:
        targets: List[str] = []
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                targets.extend(os.path.join(dirpath, f)
                               for f in sorted(filenames)
                               if f.endswith(".py"))
        elif p.endswith(".py"):
            targets.append(p)
        for t in targets:
            ap = os.path.abspath(t)
            if ap in seen:
                continue
            seen.add(ap)
            rel = os.path.relpath(ap, root)
            display = rel if not rel.startswith("..") else ap
            src = load_source(ap, display.replace(os.sep, "/"), errors)
            if src is not None:
                files.append(src)
    return files


def check_configs(root: str) -> int:
    """The premerge docs-sync gate (replaces the old heredoc diff). The R004
    drift scan runs in the full lint pass — NOT here — so its findings get
    the same suppression/baseline treatment as every other rule."""
    from spark_rapids_tpu import config
    docs = os.path.join(root, "docs", "configs.md")
    try:
        with open(docs, encoding="utf-8") as f:
            current = f.read()
    except OSError:
        current = None
    if current != config.generate_docs():
        print("docs/configs.md is stale: regenerate with "
              "python -m spark_rapids_tpu.config docs/configs.md")
        return 1
    print("configs ok: docs/configs.md matches the registry")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m spark_rapids_tpu.analysis",
                                 description="tpu-lint static analysis")
    ap.add_argument("paths", nargs="*", help="files or directories "
                    "(default: the spark_rapids_tpu package)")
    ap.add_argument("--strict", action="store_true",
                    help="ignore the baseline")
    ap.add_argument("--baseline", default=None, metavar="PATH")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--rules", default=None, metavar="IDS")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--check-configs", action="store_true")
    args = ap.parse_args(argv)

    root = _repo_root()
    if args.list_rules:
        for rule in all_rules():
            kind = "project" if rule.is_project_rule else "file"
            print(f"{rule.rule_id}  [{kind}]  {rule.title}")
        return 0
    if args.check_configs:
        return check_configs(root)

    paths = args.paths or [os.path.join(root, "spark_rapids_tpu")]
    rule_ids = (set(r.strip().upper() for r in args.rules.split(","))
                if args.rules else None)
    parse_errors: List[str] = []
    files = collect_files(paths, root, parse_errors)
    if not files and not parse_errors:
        print("no python files found under", paths)
        return 1
    result: AnalysisResult = analyze_files(files, rule_ids=rule_ids)
    result.errors.extend(parse_errors)

    baseline_path = args.baseline or os.path.join(root, bl.DEFAULT_BASELINE)
    if args.write_baseline:
        bl.write_baseline(result.findings, baseline_path)
        print(f"wrote {len(result.findings)} entries to {baseline_path}; "
              f"fill in every justification before committing")
        return 0

    findings = result.findings
    absorbed = 0
    if not args.strict:
        findings, absorbed = bl.apply_baseline(findings, baseline_path)
    for f in findings:
        print(f.render())
    for err in result.errors:
        print(f"PARSE ERROR: {err} (file NOT analyzed)")
    note = f", {absorbed} baselined" if absorbed else ""
    if findings or result.errors:
        print(f"tpu-lint: {len(findings)} finding(s), "
              f"{len(result.errors)} unparseable file(s) in "
              f"{result.files_scanned} files{note}")
        return 1
    print(f"tpu-lint: clean ({result.files_scanned} files{note})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
