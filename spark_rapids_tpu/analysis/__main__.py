"""tpu-lint CLI.

Usage:
    python -m spark_rapids_tpu.analysis [paths...] [options]

With no paths, lints the spark_rapids_tpu package itself. Exit status is 0
when no non-baselined findings remain, 1 otherwise.

Options:
    --strict            ignore the baseline (nightly mode: grandfathered
                        debt stays visible) AND fail on stale baseline
                        entries — an entry whose (rule, path, code) no
                        longer matches any source line must be removed
    --baseline PATH     baseline file (default ci/tpu-lint-baseline.json)
    --write-baseline    write current findings as a baseline skeleton
                        (justifications left empty; the file will not load
                        until they are filled in)
    --rules IDS         comma-separated rule subset, e.g. R008,R009,R010
    --list-rules        print the rule catalog and exit
    --list-suppressions inventory every inline ``# tpu-lint: disable=``
                        with file:line and its justification text
    --format MODE       output format: text (default), json — one
                        machine-readable object for CI annotation — or
                        sarif — a SARIF 2.1.0 log so CI publishes the
                        findings as code annotations (ci/premerge.sh
                        emits tpu-lint.sarif as an artifact)
    --profile           per-rule wall-time breakdown, printed to stderr
                        slowest-first (the premerge 30 s guard prints the
                        three slowest rules from it when it trips)
    --changed-only      fast-gate mode: findings restricted to files changed
                        vs the git merge-base (plus untracked files). File
                        rules run only on the changed subset; project rules
                        still see the FULL file set — interprocedural
                        context never shrinks — with their findings filtered
                        afterwards. Baseline- and suppression-staleness
                        gates are skipped (a subset run cannot judge them);
                        nightly's full --strict run keeps that job. Falls
                        back to a full run if no merge-base resolves.
    --base REF          merge-base reference for --changed-only (default:
                        origin/main, then main)
    --check-configs     verify docs/configs.md matches the registry (the
                        premerge docs-sync gate; R004 drift runs in the
                        normal lint pass with baseline semantics)
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from typing import Dict, List, Optional, Set

from spark_rapids_tpu.analysis import baseline as bl
from spark_rapids_tpu.analysis.core import (_SUPPRESS_RE, AnalysisResult,
                                            SourceFile, all_rules,
                                            analyze_files, load_source)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_files(paths: List[str], root: str,
                  errors: Optional[List[str]] = None) -> List[SourceFile]:
    files: List[SourceFile] = []
    seen = set()
    for p in paths:
        targets: List[str] = []
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                targets.extend(os.path.join(dirpath, f)
                               for f in sorted(filenames)
                               if f.endswith(".py"))
        elif p.endswith(".py"):
            targets.append(p)
        for t in targets:
            ap = os.path.abspath(t)
            if ap in seen:
                continue
            seen.add(ap)
            rel = os.path.relpath(ap, root)
            display = rel if not rel.startswith("..") else ap
            src = load_source(ap, display.replace(os.sep, "/"), errors)
            if src is not None:
                files.append(src)
    return files


def changed_paths(root: str, base: Optional[str]) -> Optional[Set[str]]:
    """Repo-relative paths of files changed vs the merge-base with ``base``
    (tracked diff + untracked), or None when no merge-base resolves — the
    caller falls back to a full run. Fail OPEN: a broken git state must
    widen the lint, never silently skip findings."""
    def run(*cmd: str):
        return subprocess.run(cmd, cwd=root, capture_output=True, text=True)

    mb = None
    for ref in ([base] if base else ["origin/main", "main"]):
        r = run("git", "merge-base", "HEAD", ref)
        if r.returncode == 0 and r.stdout.strip():
            mb = r.stdout.strip()
            break
    if mb is None:
        return None
    diff = run("git", "diff", "--name-only", "-z", mb)
    if diff.returncode != 0:
        return None
    changed = {p for p in diff.stdout.split("\0") if p}
    untracked = run("git", "ls-files", "--others", "--exclude-standard", "-z")
    if untracked.returncode == 0:
        changed |= {p for p in untracked.stdout.split("\0") if p}
    return changed


def check_configs(root: str) -> int:
    """The premerge docs-sync gate (replaces the old heredoc diff). The R004
    drift scan runs in the full lint pass — NOT here — so its findings get
    the same suppression/baseline treatment as every other rule."""
    from spark_rapids_tpu import config
    docs = os.path.join(root, "docs", "configs.md")
    try:
        with open(docs, encoding="utf-8") as f:
            current = f.read()
    except OSError:
        current = None
    if current != config.generate_docs():
        print("docs/configs.md is stale: regenerate with "
              "python -m spark_rapids_tpu.config docs/configs.md")
        return 1
    print("configs ok: docs/configs.md matches the registry")
    return 0


def _suppression_justification(src: SourceFile, lineno: int) -> str:
    """The human text around a ``# tpu-lint: disable=`` directive: the
    comment on the same line with the directive stripped, else the pure
    comment line directly above."""
    def comment_text(line: str) -> str:
        idx = line.find("#")
        if idx < 0:
            return ""
        text = line[idx:]
        text = _SUPPRESS_RE.sub("", text)
        text = re.sub(r"#\s*noqa[^#]*", "", text)
        return text.replace("#", " ").strip(" -—:\t")

    own = comment_text(src.lines[lineno - 1]) \
        if lineno - 1 < len(src.lines) else ""
    # justification blocks conventionally sit in the comment run just above
    # the suppressed statement (possibly a couple of code lines up when the
    # statement wraps): collect the nearest contiguous pure-comment block
    block: List[str] = []
    i = lineno - 2
    skipped = 0
    while i >= 0 and skipped <= 2 and not block:
        line = src.lines[i].strip()
        if line.startswith("#"):
            while i >= 0 and src.lines[i].strip().startswith("#"):
                text = comment_text(src.lines[i])
                if text:
                    block.insert(0, text)
                i -= 1
            break
        if not line:
            break
        skipped += 1
        i -= 1
    pieces = [p for p in (" ".join(block), own) if p]
    return " — ".join(pieces) if len(pieces) > 1 else \
        (pieces[0] if pieces else "")


def _suppression_status(files: List[SourceFile], result: AnalysisResult):
    """Per inline-suppression line: (src, lineno, declared ids, dead ids).

    An id is DEAD when no finding of that rule was absorbed at that line
    this run (``AnalysisResult.suppressions_hit``). A blanket ``ALL`` is
    dead only when the line absorbed nothing at all."""
    hit_by_line: Dict[tuple, set] = {}
    for path, ln, rid in result.suppressions_hit:
        hit_by_line.setdefault((path, ln), set()).add(rid)
    rows = []
    for src in files:
        for lineno in sorted(src.suppressions):
            declared = sorted(src.suppressions[lineno])
            hits = hit_by_line.get((src.display_path, lineno), set())
            if "ALL" in declared:
                dead = [] if hits else ["ALL"]
            else:
                dead = [r for r in declared if r not in hits]
            rows.append((src, lineno, declared, dead))
    return rows


def _covers_package(files: List[SourceFile], root: str) -> bool:
    """True when the analyzed set includes every .py of the package —
    the precondition for suppression staleness: a subset run would not
    re-derive interprocedural findings and would condemn live
    suppressions as stale."""
    pkg = os.path.join(root, "spark_rapids_tpu")
    have = {os.path.abspath(src.path) for src in files}
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for f in filenames:
            if f.endswith(".py") and \
                    os.path.abspath(os.path.join(dirpath, f)) not in have:
                return False
    return True


def stale_suppressions(files: List[SourceFile],
                       result: AnalysisResult) -> List[str]:
    msgs = []
    for src, lineno, _declared, dead in _suppression_status(files, result):
        if dead:
            msgs.append(f"STALE SUPPRESSION: {src.display_path}:{lineno}: "
                        f"disable={','.join(dead)} absorbed no finding — "
                        f"remove it")
    return msgs


def list_suppressions(files: List[SourceFile], result: AnalysisResult,
                      fmt: str) -> int:
    entries: List[Dict[str, object]] = []
    for src, lineno, declared, dead in _suppression_status(files, result):
        entries.append({
            "path": src.display_path,
            "line": lineno,
            "rules": declared,
            "stale_rules": dead,
            "status": "stale" if dead else "live",
            "justification": _suppression_justification(src, lineno),
            "code": src.line_text(lineno),
        })
    if fmt == "json":
        print(json.dumps({"suppressions": entries}, indent=2))
        return 0
    for e in entries:
        just = e["justification"] or "(no justification text)"
        mark = "live" if e["status"] == "live" else \
            f"STALE:{','.join(e['stale_rules'])}"
        print(f"{e['path']}:{e['line']}: {','.join(e['rules'])} "
              f"[{mark}] — {just}")
    n_stale = sum(1 for e in entries if e["status"] == "stale")
    print(f"{len(entries)} inline suppression(s) in {len(files)} files"
          f" ({n_stale} stale)" if entries else
          f"0 inline suppression(s) in {len(files)} files")
    return 0


def _sarif_doc(findings, errors, stale, files_scanned: int, absorbed: int,
               rule_seconds) -> Dict[str, object]:
    """SARIF 2.1.0: the interchange format CI systems ingest to render
    findings as inline code annotations on the PR diff."""
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    # plain repo-relative URIs, no uriBaseId: consumers
                    # resolve against the checkout (GitHub code scanning
                    # does; a bogus file:/// base would break the strict
                    # ones that honor originalUriBaseIds)
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "snippet": {"text": f.code}},
                },
            }],
        })
    driver = {
        "name": "tpu-lint",
        "informationUri": "docs/static-analysis.md",
        "rules": [{"id": r.rule_id,
                   "shortDescription": {"text": r.title},
                   # per-rule catalog anchor: CI annotations deep-link
                   # straight to the rule's docs section
                   "helpUri": r.help_uri()}
                  for r in all_rules()],
    }
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": driver},
            "results": results,
            "properties": {
                "filesScanned": files_scanned,
                "baselined": absorbed,
                "parseErrors": list(errors),
                "staleBaseline": list(stale),
                "ruleSeconds": dict(rule_seconds or {}),
            },
        }],
    }


def _emit(findings, errors, stale, files_scanned: int, absorbed: int,
          fmt: str, rule_seconds=None) -> None:
    if fmt == "sarif":
        print(json.dumps(_sarif_doc(findings, errors, stale, files_scanned,
                                    absorbed, rule_seconds), indent=2))
        return
    if fmt == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "errors": list(errors),
            "stale_baseline": list(stale),
            "files_scanned": files_scanned,
            "baselined": absorbed,
            "rule_seconds": dict(rule_seconds or {}),
        }, indent=2))
        return
    for f in findings:
        print(f.render())
    for err in errors:
        print(f"PARSE ERROR: {err} (file NOT analyzed)")
    for msg in stale:
        print(msg)
    note = f", {absorbed} baselined" if absorbed else ""
    if findings or errors or stale:
        bits = [f"{len(findings)} finding(s)",
                f"{len(errors)} unparseable file(s)"]
        if stale:
            bits.append(f"{len(stale)} stale baseline/suppression entr"
                        f"{'ies' if len(stale) > 1 else 'y'}")
        print(f"tpu-lint: {', '.join(bits)} in {files_scanned} "
              f"files{note}")
    else:
        print(f"tpu-lint: clean ({files_scanned} files{note})")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m spark_rapids_tpu.analysis",
                                 description="tpu-lint static analysis")
    ap.add_argument("paths", nargs="*", help="files or directories "
                    "(default: the spark_rapids_tpu package)")
    ap.add_argument("--strict", action="store_true",
                    help="ignore the baseline; fail on stale entries")
    ap.add_argument("--baseline", default=None, metavar="PATH")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--rules", default=None, metavar="IDS")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-suppressions", action="store_true")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"))
    ap.add_argument("--profile", action="store_true",
                    help="per-rule wall-time breakdown on stderr")
    ap.add_argument("--changed-only", action="store_true",
                    help="restrict findings to files changed vs the git "
                         "merge-base; project rules keep full context")
    ap.add_argument("--base", default=None, metavar="REF",
                    help="merge-base ref for --changed-only "
                         "(default origin/main, then main)")
    ap.add_argument("--check-configs", action="store_true")
    args = ap.parse_args(argv)

    root = _repo_root()
    if args.list_rules:
        for rule in all_rules():
            kind = "project" if rule.is_project_rule else "file"
            print(f"{rule.rule_id}  [{kind}]  {rule.title}")
        return 0
    if args.check_configs:
        return check_configs(root)

    paths = args.paths or [os.path.join(root, "spark_rapids_tpu")]
    rule_ids = (set(r.strip().upper() for r in args.rules.split(","))
                if args.rules else None)
    parse_errors: List[str] = []
    files = collect_files(paths, root, parse_errors)
    if args.list_suppressions:
        # run the full rule set so live/stale marking reflects reality
        return list_suppressions(files, analyze_files(files), args.format)
    if not files and not parse_errors:
        print("no python files found under", paths)
        return 1
    changed: Optional[Set[str]] = None
    if args.changed_only:
        changed = changed_paths(root, args.base)
        if changed is None:
            print("tpu-lint: --changed-only found no merge-base; "
                  "falling back to a full run", file=sys.stderr)

    if changed is not None:
        changed_srcs = [f for f in files if f.display_path in changed]
        if changed_srcs:
            # project rules over the FULL set (interprocedural context
            # never shrinks), file rules over the changed subset only;
            # project findings filter to changed files afterwards
            result = analyze_files(files, rule_ids=rule_ids,
                                   with_file_rules=False)
            result.findings = [f for f in result.findings
                               if f.path in changed]
            fres = analyze_files(changed_srcs, rule_ids=rule_ids,
                                 with_project_rules=False)
            result.findings.extend(fres.findings)
            result.suppressions_hit |= fres.suppressions_hit
            for rid, secs in fres.rule_seconds.items():
                result.rule_seconds[rid] = round(
                    result.rule_seconds.get(rid, 0.0) + secs, 4)
            result.files_scanned = len(files)
        else:
            result = AnalysisResult(files_scanned=len(files))
    else:
        result = analyze_files(files, rule_ids=rule_ids)
    result.errors.extend(parse_errors)

    baseline_path = args.baseline or os.path.join(root, bl.DEFAULT_BASELINE)
    if args.write_baseline:
        bl.write_baseline(result.findings, baseline_path)
        print(f"wrote {len(result.findings)} entries to {baseline_path}; "
              f"fill in every justification before committing")
        return 0

    findings = result.findings
    absorbed = 0
    stale: List[str] = []
    if not args.strict:
        findings, absorbed = bl.apply_baseline(findings, baseline_path)
    elif args.changed_only:
        # a subset run cannot judge baseline/suppression staleness — the
        # findings it never re-derived would all look dead. Nightly's full
        # --strict run owns that hygiene.
        pass
    else:
        # nightly hygiene: a baseline entry no source line matches anymore
        # is debt pretending to still exist — fail with a remove-me
        stale = bl.stale_entries(baseline_path, files, root)
        # same hygiene for inline suppressions — but only when the whole
        # package (and the whole rule set) was analyzed, else subset runs
        # would condemn suppressions whose findings they never re-derived
        if rule_ids is None and _covers_package(files, root):
            stale = stale + stale_suppressions(files, result)
    _emit(findings, result.errors, stale, result.files_scanned, absorbed,
          args.format, rule_seconds=result.rule_seconds)
    if args.profile:
        # stderr, slowest first: machine formats on stdout stay parseable
        # and the premerge guard can `head -3` the culprits
        for rid, secs in sorted(result.rule_seconds.items(),
                                key=lambda kv: -kv[1]):
            print(f"profile: {rid} {secs:.3f}s", file=sys.stderr)
    return 1 if (findings or result.errors or stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())
