"""R004/R005: cross-file drift rules.

R004 — config drift. Every tunable is declared once in config.py
(``NAME = _conf("key", ...)``); the engine reads it through the declared
constant (``conf.get(cfg.NAME)``) or, rarely, a registered string literal
(``get_raw("spark.rapids.tpu...")``). Two drift modes, both of which have
shipped silently before:

- a key is registered (and documented in docs/configs.md) but nothing ever
  reads it — users set it and nothing happens;
- a string literal under the conf prefix is read but never registered — a
  typo'd key silently returns the default forever.

A constant counts as read when ANY reference beyond its defining assignment
exists, including config.py's own convenience properties (the property is
the read path). Dynamic per-rule enable keys
(``spark.rapids.tpu.sql.expression.<Name>``, plan/overrides.py) are built
at runtime, never literals, so they don't trip the unregistered check.

R005 — Cpu/Tpu exec constructor parity, the api_validation reflection check
(ApiValidation.scala analog) surfaced as lint findings so premerge reports
every hygiene failure through one tool with one suppression/baseline story.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, register)

_CONF_PREFIX = "spark.rapids.tpu"


def _find_config_file(files: Sequence[SourceFile]) -> Optional[SourceFile]:
    for f in files:
        p = f.display_path.replace("\\", "/")
        if p.endswith("spark_rapids_tpu/config.py") or p == "config.py":
            return f
    return None


def registered_keys(config_src: SourceFile) -> Dict[str, Tuple[str, int]]:
    """constant name -> (full key, lineno) from ``NAME = _conf("key", ...)``
    assignments in config.py."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in config_src.tree.body:
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        if call_name(node.value) != "_conf" or not node.value.args:
            continue
        key_node = node.value.args[0]
        if not (isinstance(key_node, ast.Constant)
                and isinstance(key_node.value, str)):
            continue
        target = node.targets[0]
        if isinstance(target, ast.Name):
            key = key_node.value
            if not key.startswith(_CONF_PREFIX):
                key = f"{_CONF_PREFIX}.{key}"
            out[target.id] = (key, node.lineno)
    return out


def _identifier_uses(files: Sequence[SourceFile]) -> Dict[str, int]:
    """How often each identifier appears as a Name or attribute access across
    the file set (reads of ``cfg.NAME`` land here as the attribute name)."""
    counts: Dict[str, int] = {}
    for src in files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Name):
                counts[node.id] = counts.get(node.id, 0) + 1
            elif isinstance(node, ast.Attribute):
                counts[node.attr] = counts.get(node.attr, 0) + 1
    return counts


def _string_key_literals(files: Sequence[SourceFile]
                         ) -> List[Tuple[SourceFile, ast.Constant]]:
    out = []
    for src in files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value.startswith(_CONF_PREFIX + "."):
                out.append((src, node))
    return out


@register
class ConfigDrift(Rule):
    rule_id = "R004"
    title = "config drift (dead or unregistered keys)"
    is_project_rule = True

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        config_src = _find_config_file(files)
        if config_src is None:
            return []  # analyzing a subtree without the registry
        findings: List[Finding] = []
        keys = registered_keys(config_src)
        uses = _identifier_uses(files)
        for name, (key, lineno) in sorted(keys.items()):
            # one use is the defining assignment itself
            if uses.get(name, 0) <= 1:
                findings.append(Finding(
                    self.rule_id, config_src.display_path, lineno,
                    f"config key {key} ({name}) is registered and "
                    f"documented but never read by the engine; wire it up "
                    f"or remove it", config_src.line_text(lineno)))
        known = {key for key, _ in keys.values()}
        # dynamic per-rule enable keys share the sql.expression/sql.exec
        # namespaces (plan/overrides.py derives them from class names)
        dynamic_ns = (f"{_CONF_PREFIX}.sql.expression.",
                      f"{_CONF_PREFIX}.sql.exec.")
        for src, node in _string_key_literals(files):
            val = node.value
            if val in known or val.startswith(dynamic_ns):
                continue
            # prefix-only literals (env-var mapping, docs) are not key reads
            if val.count(".") <= _CONF_PREFIX.count("."):
                continue
            findings.append(src.finding(
                self.rule_id, node,
                f"conf key literal '{val}' is not registered in config.py; "
                f"a typo here silently returns the default forever"))
        return findings


@register
class ExecParity(Rule):
    rule_id = "R005"
    title = "Cpu/Tpu exec constructor parity"
    is_project_rule = True

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        paths = {f.display_path.replace("\\", "/") for f in files}
        if not any(p.endswith("api_validation.py") for p in paths):
            return []  # subtree run without the exec modules
        try:
            from spark_rapids_tpu import api_validation
            problems = api_validation.validate()
        except Exception as e:  # noqa: BLE001 - import errors ARE findings
            return [Finding(self.rule_id, "spark_rapids_tpu/api_validation.py",
                            1, f"api_validation failed to run: "
                               f"{type(e).__name__}: {e}")]
        return [Finding(self.rule_id, "spark_rapids_tpu/api_validation.py", 1,
                        f"Cpu/Tpu exec constructor mismatch: {p}")
                for p in problems]
