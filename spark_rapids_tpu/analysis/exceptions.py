"""Interprocedural exception-flow analysis for tpu-lint v4.

Computes per-function MAY-RAISE sets — which exception classes can escape a
function — by worklist fixpoint over the package call graph:

  * a ``raise ClassName(...)`` site contributes its class name;
  * a call site contributes its resolved callees' may-raise sets (the call
    graph under-approximates, so unresolvable calls contribute nothing —
    the same errs-toward-silence discipline as R009–R012);
  * a ``try`` subtracts what its ``except`` clauses catch, matched by class
    hierarchy (package ``ClassInfo`` bases joined with a builtin-exception
    table, so ``except OSError`` catches a propagated ``ConnectionError``);
  * handler bodies re-enter the walk with the caught subset bound, so bare
    ``raise`` and ``raise e`` propagate exactly what arrived, and
    ``raise Other(...) [from e]`` records a *conversion* (caught set →
    raised class) for R014's cancellation-laundering check;
  * ``else`` runs unprotected; ``finally`` raises union in (the CFG's
    finally-first routing, seen from the caller's side).

The transfer function is monotone (sets only grow) over a finite universe
(class names that appear at raise sites), so the fixpoint terminates even
through direct/mutual recursion; a visit cap bounds pathological inputs.

A final pass re-evaluates each function at the fixpoint to record
``HandlerFlow`` facts — for every except clause, which may-raised classes
arrive and what the handler body re-raises — plus conversions and a
class → raise-site index.  R013–R015 (rules_exceptions.py) consume these.

Exposed as ``raises_for(files)`` beside ``graph_for()``/``registry_for()``,
with the same single-entry cache so one analysis run builds the flow once.
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                                 graph_for)
from spark_rapids_tpu.analysis.cfg import walk_local
from spark_rapids_tpu.analysis.core import SourceFile, dotted_name

#: builtin exception ancestry (child -> parent); joined with package classes
#: so hierarchy matching works across the builtin/package seam
_BUILTIN_BASES: Dict[str, str] = {
    "Exception": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "BufferError": "Exception",
    "EOFError": "Exception",
    "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "LookupError": "Exception",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "MemoryError": "Exception",
    "NameError": "Exception",
    "UnboundLocalError": "NameError",
    "OSError": "Exception",
    "IOError": "OSError",
    "BlockingIOError": "OSError",
    "ChildProcessError": "OSError",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "FileExistsError": "OSError",
    "FileNotFoundError": "OSError",
    "InterruptedError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "PermissionError": "OSError",
    "TimeoutError": "OSError",
    "ReferenceError": "Exception",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "StopIteration": "Exception",
    "StopAsyncIteration": "Exception",
    "SyntaxError": "Exception",
    "SystemError": "Exception",
    "TypeError": "Exception",
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
}

#: fixpoint safety valve: re-evaluations per function before giving up
_MAX_VISITS_PER_FN = 64


class Hierarchy:
    """Exception-class ancestry: package ``ClassInfo`` bases (by name) over
    the builtin table.  Unknown names have no ancestry — they match only
    themselves and catch-all clauses."""

    def __init__(self, classes) -> None:
        self._bases: Dict[str, Tuple[str, ...]] = {
            child: (parent,) for child, parent in _BUILTIN_BASES.items()}
        for name, ci in classes.items():
            self._bases[name] = tuple(ci.bases)
        self._anc_cache: Dict[str, FrozenSet[str]] = {}

    def ancestors(self, name: str) -> FrozenSet[str]:
        """``name`` plus every transitive base (cycle-safe)."""
        got = self._anc_cache.get(name)
        if got is None:
            seen: Set[str] = set()
            stack = [name]
            while stack:
                n = stack.pop()
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(self._bases.get(n, ()))
            got = frozenset(seen)
            self._anc_cache[name] = got
        return got

    def is_subclass(self, name: str, base: str) -> bool:
        return base in self.ancestors(name)

    def catches(self, clause: str, raised: str) -> bool:
        """Does ``except clause`` catch a raised ``raised``?  Exception /
        BaseException are catch-alls (they also catch classes whose
        ancestry the graph cannot see)."""
        if clause in ("Exception", "BaseException"):
            return True
        return self.is_subclass(raised, clause)

    def is_exception_class(self, name: str) -> bool:
        anc = self.ancestors(name)
        return "Exception" in anc or "BaseException" in anc


class RaiseSite(NamedTuple):
    func: FunctionInfo
    node: ast.Raise
    name: str              # leaf class name raised


class HandlerFlow(NamedTuple):
    """One except clause at the fixpoint: what may arrive, what leaves."""
    func: FunctionInfo
    try_node: ast.Try
    handler: ast.ExceptHandler
    clause_names: Tuple[str, ...]   # ("BaseException",) for bare except
    caught: FrozenSet[str]          # may-raised classes this clause absorbs
    raised: FrozenSet[str]          # what the handler body may raise outward


class Conversion(NamedTuple):
    """An explicit ``raise NewClass(...)`` inside an except body — the
    handler converts its caught set into ``to_name``."""
    func: FunctionInfo
    handler: ast.ExceptHandler
    caught: FrozenSet[str]
    to_name: str
    node: ast.Raise


class _HandlerCtx(NamedTuple):
    var: Optional[str]              # ``except ... as var`` binding
    caught: FrozenSet[str]
    handler: ast.ExceptHandler


def _raised_class_name(expr: ast.expr) -> Optional[str]:
    """Leaf class name of an explicit raise expression (``raise X`` /
    ``raise X(...)`` / ``raise mod.X(...)``); None for dynamic raises."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if not name:
        return None
    leaf = name.split(".")[-1]
    return leaf if leaf[:1].isupper() else None


def _iter_calls(node: ast.AST):
    """Call nodes within one expression/statement fragment, not descending
    into lambda bodies (they do not run on this path)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Lambda):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


class _Evaluator:
    """One structural evaluation of a function body against the current
    raises map.  With ``sink`` set (final pass), records HandlerFlow /
    Conversion facts into the owning ExceptionFlow."""

    def __init__(self, info: FunctionInfo,
                 call_targets: Dict[int, Tuple[str, ...]],
                 raises_map: Dict[str, FrozenSet[str]],
                 hier: Hierarchy,
                 sink: Optional["ExceptionFlow"] = None) -> None:
        self.info = info
        self.call_targets = call_targets
        self.raises_map = raises_map
        self.hier = hier
        self.sink = sink

    def run(self) -> FrozenSet[str]:
        return frozenset(self.eval_stmts(self.info.node.body, None))

    # ---- expression level --------------------------------------------------
    def _call_raises(self, node: Optional[ast.AST]) -> Set[str]:
        out: Set[str] = set()
        if node is None:
            return out
        for call in _iter_calls(node):
            for key in self.call_targets.get(id(call), ()):
                out |= self.raises_map.get(key, frozenset())
        return out

    # ---- statement level ---------------------------------------------------
    def eval_stmts(self, stmts: Sequence[ast.stmt],
                   ctx: Optional[_HandlerCtx]) -> Set[str]:
        out: Set[str] = set()
        for s in stmts:
            out |= self.eval_stmt(s, ctx)
        return out

    def eval_stmt(self, s: ast.stmt,
                  ctx: Optional[_HandlerCtx]) -> Set[str]:
        if isinstance(s, ast.Raise):
            return self._eval_raise(s, ctx)
        if isinstance(s, ast.Try):
            return self._eval_try(s, ctx)
        if isinstance(s, ast.If):
            return (self._call_raises(s.test)
                    | self.eval_stmts(s.body, ctx)
                    | self.eval_stmts(s.orelse, ctx))
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return (self._call_raises(s.iter)
                    | self.eval_stmts(s.body, ctx)
                    | self.eval_stmts(s.orelse, ctx))
        if isinstance(s, ast.While):
            return (self._call_raises(s.test)
                    | self.eval_stmts(s.body, ctx)
                    | self.eval_stmts(s.orelse, ctx))
        if isinstance(s, (ast.With, ast.AsyncWith)):
            out: Set[str] = set()
            for item in s.items:
                out |= self._call_raises(item.context_expr)
            return out | self.eval_stmts(s.body, ctx)
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            # nested bodies run in their own activation; only decorators
            # and defaults evaluate on this path
            out = set()
            for d in s.decorator_list:
                out |= self._call_raises(d)
            return out
        # simple statement: any call anywhere in it may raise
        out = set()
        for child in ast.iter_child_nodes(s):
            out |= self._call_raises(child)
        return out

    def _eval_raise(self, s: ast.Raise,
                    ctx: Optional[_HandlerCtx]) -> Set[str]:
        out = self._call_raises(s.exc) | self._call_raises(s.cause)
        if s.exc is None:                      # bare raise: re-raise caught
            if ctx is not None:
                out |= ctx.caught
            return out
        name = _raised_class_name(s.exc)
        if name is not None:
            out.add(name)
            if ctx is not None and self.sink is not None:
                self.sink.conversions.append(
                    Conversion(self.info, ctx.handler, ctx.caught, name, s))
        elif (isinstance(s.exc, ast.Name) and ctx is not None
              and s.exc.id == ctx.var):        # raise e: re-raise caught
            out |= ctx.caught
        # other dynamic raises contribute nothing (under-approximate)
        return out

    def _clause(self, handler: ast.ExceptHandler
                ) -> Tuple[Tuple[str, ...], bool]:
        """(clause class names, resolved).  Bare ``except`` is a resolved
        BaseException catch-all; a clause with any non-name element is
        *unresolved* — it subtracts everything (keeps may-raise an
        under-approximation) but is not reported as a handler fact."""
        t = handler.type
        if t is None:
            return ("BaseException",), True
        elts = list(t.elts) if isinstance(t, ast.Tuple) else [t]
        names: List[str] = []
        for e in elts:
            dn = dotted_name(e)
            leaf = dn.split(".")[-1] if dn else ""
            if not leaf or not leaf[:1].isupper():
                return ("BaseException",), False
            names.append(leaf)
        return tuple(names), True

    def _eval_try(self, s: ast.Try,
                  ctx: Optional[_HandlerCtx]) -> Set[str]:
        remaining = set(self.eval_stmts(s.body, ctx))
        out: Set[str] = set()
        for h in s.handlers:
            clause, resolved = self._clause(h)
            caught = {c for c in remaining
                      if any(self.hier.catches(cl, c) for cl in clause)}
            remaining -= caught
            hctx = _HandlerCtx(h.name, frozenset(caught), h)
            h_out = self.eval_stmts(h.body, hctx)
            if self.sink is not None and resolved:
                self.sink.handler_flows.append(HandlerFlow(
                    self.info, s, h, clause,
                    frozenset(caught), frozenset(h_out)))
            out |= h_out
        return (remaining | out
                | self.eval_stmts(s.orelse, ctx)
                | self.eval_stmts(s.finalbody, ctx))


class ExceptionFlow:
    """Package-wide may-raise fixpoint plus the handler/conversion facts
    R013–R015 consume.  Build via ``raises_for(files)``."""

    def __init__(self, files: Sequence[SourceFile]) -> None:
        self.graph: CallGraph = graph_for(files)
        self.hierarchy = Hierarchy(self.graph.classes)
        self.handler_flows: List[HandlerFlow] = []
        self.conversions: List[Conversion] = []
        self.raise_sites: Dict[str, List[RaiseSite]] = {}
        self._raises: Dict[str, FrozenSet[str]] = {}
        self._call_targets: Dict[str, Dict[int, Tuple[str, ...]]] = {}
        self._build()

    # ---- queries -----------------------------------------------------------
    def raises(self, key: str) -> FrozenSet[str]:
        """May-raise set (leaf class names) escaping function ``key``."""
        return self._raises.get(key, frozenset())

    def decorated(self, marker: str) -> List[FunctionInfo]:
        """Functions carrying a decorator whose leaf name is ``marker``
        (e.g. ``triage_boundary`` / ``wire_boundary`` from utils.errors)."""
        out = []
        for info in self.graph.functions.values():
            for d in info.node.decorator_list:
                expr = d.func if isinstance(d, ast.Call) else d
                dn = dotted_name(expr)
                if dn and dn.split(".")[-1] == marker:
                    out.append(info)
                    break
        return out

    # ---- construction ------------------------------------------------------
    def _build(self) -> None:
        graph = self.graph
        for key, info in graph.functions.items():
            targets: Dict[int, Tuple[str, ...]] = {}
            for node in walk_local(info.node):
                if isinstance(node, ast.Call):
                    resolved = tuple(t for t in graph.resolve_call(info, node)
                                     if t != key)
                    if resolved:
                        targets[id(node)] = resolved
                elif isinstance(node, ast.Raise) and node.exc is not None:
                    name = _raised_class_name(node.exc)
                    if name is not None:
                        self.raise_sites.setdefault(name, []).append(
                            RaiseSite(info, node, name))
            self._call_targets[key] = targets
            self._raises[key] = frozenset()

        callers: Dict[str, Set[str]] = {}
        for caller, callees in graph.edges.items():
            for callee in callees:
                callers.setdefault(callee, set()).add(caller)

        worklist = deque(graph.functions)
        queued = set(worklist)
        visits: Dict[str, int] = {}
        while worklist:
            key = worklist.popleft()
            queued.discard(key)
            if visits.get(key, 0) >= _MAX_VISITS_PER_FN:
                continue
            visits[key] = visits.get(key, 0) + 1
            info = graph.functions[key]
            new = _Evaluator(info, self._call_targets[key], self._raises,
                             self.hierarchy).run()
            if new != self._raises[key]:
                self._raises[key] = new
                for caller in callers.get(key, ()):
                    if caller not in queued:
                        queued.add(caller)
                        worklist.append(caller)

        # final pass at the fixpoint: collect handler/conversion facts
        for key, info in graph.functions.items():
            _Evaluator(info, self._call_targets[key], self._raises,
                       self.hierarchy, sink=self).run()


_FLOW_CACHE: Dict[int, ExceptionFlow] = {}


def raises_for(files: Sequence[SourceFile]) -> ExceptionFlow:
    """Build (or reuse) the exception-flow analysis for one run's file set —
    R013/R014/R015 share a single fixpoint, same caching discipline as
    ``graph_for``/``registry_for``."""
    key = hash(tuple(id(f) for f in files))
    got = _FLOW_CACHE.get(key)
    if got is None:
        _FLOW_CACHE.clear()          # one live file set at a time
        got = ExceptionFlow(files)
        _FLOW_CACHE[key] = got
    return got
