"""tpu-lint baseline: grandfathered findings with written justifications.

The baseline is a checked-in JSON file (default ci/tpu-lint-baseline.json)
listing findings that predate a rule and are allowed to stand. Every entry
MUST carry a non-empty ``justification`` — an entry without one fails the
load, so debt can't be grandfathered silently. ``--strict`` (the nightly
mode) ignores the baseline entirely, keeping the debt visible.

Matching is by (rule, path, stripped source line), not line number: code
moves, lines rarely change. ``count`` bounds how many identical findings
one entry absorbs (default 1).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.analysis.core import Finding

DEFAULT_BASELINE = os.path.join("ci", "tpu-lint-baseline.json")


class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """(rule, path, code) -> allowed count. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("findings", [])
    table: Dict[Tuple[str, str, str], int] = {}
    for i, e in enumerate(entries):
        missing = [k for k in ("rule", "path", "code") if not e.get(k)]
        if missing:
            raise BaselineError(
                f"{path}: entry {i} missing {missing}")
        if not str(e.get("justification", "")).strip():
            raise BaselineError(
                f"{path}: entry {i} ({e['rule']} {e['path']}) has no "
                f"justification; baselined debt requires a written reason")
        key = (e["rule"], e["path"], e["code"])
        table[key] = table.get(key, 0) + int(e.get("count", 1))
    return table


def apply_baseline(findings: List[Finding], path: str
                   ) -> Tuple[List[Finding], int]:
    """(new findings, number absorbed by the baseline)."""
    table = dict(load_baseline(path))
    new: List[Finding] = []
    absorbed = 0
    for f in findings:
        key = f.baseline_key()
        if table.get(key, 0) > 0:
            table[key] -= 1
            absorbed += 1
        else:
            new.append(f)
    return new, absorbed


def stale_entries(path: str, files, root: Optional[str] = None) -> List[str]:
    """Baseline entries whose (rule, path, code) no longer matches ANY
    source line — dead weight that would silently absorb a future
    unrelated finding with the same shape. ``--strict`` (nightly) fails on
    these with a remove-me message, so grandfathered debt disappears from
    the ledger the same PR it disappears from the code.

    An entry for a file OUTSIDE the analyzed set is only stale when the
    file is gone from disk too — ``--strict path/to/one_file.py`` subset
    runs must not condemn live entries for files they never looked at."""
    table = load_baseline(path)
    by_path = {f.display_path: f for f in files}
    out: List[str] = []
    for (rule, fpath, code) in sorted(table):
        src = by_path.get(fpath)
        if src is None:
            on_disk = os.path.join(root, fpath) if root else fpath
            if not os.path.exists(on_disk):
                out.append(
                    f"stale baseline entry: ({rule}, {fpath}) — the file "
                    f"no longer exists; remove me")
            continue
        if not any(line.strip() == code for line in src.lines):
            out.append(
                f"stale baseline entry: ({rule}, {fpath}, {code!r}) no "
                f"longer matches any source line; remove me")
    return out


def write_baseline(findings: List[Finding], path: str) -> None:
    """Serialize current findings as a baseline skeleton. Justifications are
    emitted as empty strings on purpose: the file will not LOAD until a
    human writes one per entry."""
    entries = []
    for f in findings:
        entries.append({"rule": f.rule, "path": f.path, "code": f.code,
                        "count": 1, "justification": "",
                        "message": f.message})
    with open(path, "w", encoding="utf-8") as out:
        json.dump({"version": 1, "findings": entries}, out, indent=2)
        out.write("\n")
