"""R010: cancellation-unsafe blocking waits on execute paths.

PR 8's cancellation contract is COOPERATIVE: ``cancel()`` only sets a
flag, and the running query must observe it at checkpoints — exec
boundaries, semaphore admission, the pipeline producer, cache latches.
One unbounded wait anywhere on the execute path breaks the whole
contract: a cancelled query blocked in ``queue.get()`` with no timeout
sits there until the process dies, still holding its semaphore permit
and catalog buffers.

The check: a blocking primitive reachable (callgraph.py, bounded hops)
from a serving/exec execute root —

- roots: every ``execute`` method in ``execs/``, plus the serving
  scheduler's worker path (``_worker_loop`` / ``_run_handle``), the
  serving wire surface (``serve_forever`` — the server's accept/run
  loop must poll bounded so shutdown and signals land — plus the
  client's ``submit`` / ``batches`` / ``result`` stream drivers) and
  the DataFrame collect entry (``_collect``);
- blocking primitives: ``<queue>.get()`` where the receiver is a
  ``queue.Queue`` (created in the function, assigned to an attr in the
  same module, or named ``*queue*``/``q``), and ``<event-or-cond>.wait()``
  — in BOTH cases only when called with NO timeout: a wait with a
  timeout is the sanctioned poll idiom (``while not ev.wait(0.05):
  cancel_check()``), which every repo latch uses.

A server-side loop that is legitimately outside the per-query contract
(an RPC dispatch thread, a daemon) is not reachable from the roots by
construction; if one ever is, it takes an inline suppression with the
justification, not a baseline entry.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from spark_rapids_tpu.analysis.callgraph import graph_for
from spark_rapids_tpu.analysis.cfg import iter_functions
from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, dotted_name, register)

#: call-graph hops from an execute root the contract extends through
_MAX_DEPTH = 12

#: receiver-name fragments marking an Event/Condition/latch wait
_WAIT_HINTS = ("ev", "event", "cond", "latch", "done", "ready", "_cv",
               "available", "room")
#: receiver-name fragments marking a queue
_QUEUE_HINTS = ("queue", "_q")


def _is_queue_typed(src: SourceFile, func_node, recv: str) -> bool:
    """Receiver is a queue: assigned ``queue.Queue(...)`` in this function
    or this module, annotated as one, or named like one."""
    leaf = recv.split(".")[-1].lower()
    if recv.lower() == "q" or leaf == "q":
        return True
    if any(h in recv.lower() for h in _QUEUE_HINTS):
        return True
    for n in ast.walk(src.tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            vname = call_name(n.value)
            if vname.split(".")[-1] != "Queue":
                continue
            for t in n.targets:
                if dotted_name(t) == recv or (
                        isinstance(t, ast.Attribute) and
                        t.attr == recv.split(".")[-1]):
                    return True
        if isinstance(n, ast.AnnAssign) and n.annotation is not None:
            ann = ""
            if isinstance(n.annotation, ast.Constant):
                ann = str(n.annotation.value)
            else:
                ann = dotted_name(n.annotation)
            if "Queue" in ann and dotted_name(n.target) == recv:
                return True
    return False


def _is_bounded(call: ast.Call, attr: str) -> bool:
    """The call cannot block forever: a real timeout is supplied, or a
    queue ``get`` is non-blocking. Spelling the unbounded default out
    (``q.get(True)`` / ``q.get(block=True)``) does NOT bound it."""
    kws = {kw.arg: kw.value for kw in call.keywords if kw.arg}
    if "timeout" in kws:
        v = kws["timeout"]
        return not (isinstance(v, ast.Constant) and v.value is None)
    if attr == "get":
        if "block" in kws:
            v = kws["block"]
            # block=False is non-blocking; block=True (or dynamic) without
            # a timeout is the unbounded default restated
            return isinstance(v, ast.Constant) and v.value is False
        if call.args:
            if len(call.args) >= 2:
                return True            # get(block, timeout)
            first = call.args[0]
            if isinstance(first, ast.Constant) and first.value is True:
                return False           # get(True): explicitly unbounded
            # get(False) is non-blocking; a dynamic block arg stays silent
            # (the engine errs toward no false findings)
            return True
        return False
    # Event/Condition/latch wait(): the first positional IS the timeout
    return bool(call.args)


@register
class CancellationUnsafeWait(Rule):
    rule_id = "R010"
    title = "unbounded blocking wait reachable from an execute path"
    is_project_rule = True

    def _roots(self, graph) -> List[str]:
        roots: List[str] = []
        for key, info in graph.functions.items():
            mod = info.module.replace("\\", "/")
            name = info.qualname.split(".")[-1]
            if name == "execute" and ("/execs/" in mod or
                                      mod.startswith("execs/")):
                roots.append(key)
            elif ("/serving/" in mod or mod.startswith("serving/")) and \
                    name in ("_worker_loop", "_run_handle", "submit",
                             "drain", "serve_forever", "batches",
                             "result"):
                roots.append(key)
            elif name == "_collect" and mod.endswith("api/dataframe.py"):
                roots.append(key)
        return roots

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        graph = graph_for(files)
        roots = self._roots(graph)
        if not roots:
            return []
        reachable = graph.reachable(roots, max_depth=_MAX_DEPTH)
        findings: List[Finding] = []
        seen: Set[int] = set()
        for key in sorted(reachable):
            info = graph.functions[key]
            nested = {id(n) for _qn, n in iter_functions(info.node)}
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                if self._inside_other_function(info, node, nested):
                    continue
                attr = node.func.attr
                recv = dotted_name(node.func.value)
                if not recv:
                    continue
                blocking = False
                what = ""
                if attr == "get" and not _is_bounded(node, attr) and \
                        _is_queue_typed(info.src, info.node, recv):
                    blocking = True
                    what = f"{recv}.get()"
                elif attr == "wait" and not _is_bounded(node, attr) and \
                        any(h in recv.lower() for h in _WAIT_HINTS):
                    blocking = True
                    what = f"{recv}.wait()"
                if not blocking:
                    continue
                if id(node) in seen:
                    continue
                seen.add(id(node))
                findings.append(info.src.finding(
                    self.rule_id, node,
                    f"{info.qualname}: {what} blocks with no timeout on a "
                    f"path reachable from a serving/exec execute root "
                    f"(e.g. {graph.functions[roots[0]].qualname}): a "
                    f"cancelled query never observes its flag here and "
                    f"holds its semaphore/buffers forever; poll with a "
                    f"timeout and call the bound query's "
                    f"cancel_check/check_cancelled between polls (the "
                    f"scan-cache latch idiom), or justify with an inline "
                    f"suppression"))
        return findings

    @staticmethod
    def _inside_other_function(info, node, nested) -> bool:
        cur = info.src.parent(node)
        while cur is not None and cur is not info.node:
            if id(cur) in nested:
                return True
            cur = info.src.parent(cur)
        return False
