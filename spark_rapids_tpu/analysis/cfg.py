"""Per-function control-flow graphs for tpu-lint v2.

PR 8 made the engine's correctness rest on cross-statement properties —
"every semaphore hold is released on every unwind path" is a claim about
PATHS, not lines — which the v1 flat AST matchers cannot express. This
module builds a small, honest CFG per function: basic blocks of simple
statements, labeled branch edges (``true``/``false`` off a condition),
loop back-edges, try/except/finally routing, and ``with`` enter/exit
markers. The forward dataflow engine in ``dataflow.py`` runs over it.

Modeling decisions (kept deliberately boring):

- Only EXPLICIT control flow is modeled: ``return``/``raise``/``break``/
  ``continue`` and structured statements. Implicit exceptions from
  arbitrary calls are approximated by edges from every block in a ``try``
  body to its handlers; outside a ``try`` they are not modeled (flagging
  every call as a potential unwind would drown real findings).
- ``finally`` bodies are built once; every exit of the protected body
  routes through them. An abrupt exit (return/break/continue) through a
  finally is routed finally-entry first, with the finally's end edged to
  the abrupt target — paths merge there, a standard may-analysis
  over-approximation.
- ``with`` is transparent to the graph (its body cannot be skipped); the
  block stream carries ``WithEnter``/``WithExit`` markers so rules can
  treat context-managed acquires as auto-released.
- Compound headers are wrapped (``Cond``, ``LoopIter``, ``Handler``) so a
  rule walking a block's items never wanders into a nested body it will
  also see as separate blocks.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

#: edge labels off a condition block
TRUE, FALSE = "true", "false"


class Cond:
    """Block-terminating branch condition (If/While test). Successor edges
    carry ``true``/``false`` labels."""

    __slots__ = ("test", "node")

    def __init__(self, test: ast.expr, node: ast.stmt):
        self.test = test
        self.node = node

    @property
    def lineno(self) -> int:
        return getattr(self.test, "lineno", getattr(self.node, "lineno", 1))


class LoopIter:
    """For-loop header: ``target`` bound from ``iter`` each round; the
    ``true`` edge enters the body, ``false`` exits the loop."""

    __slots__ = ("target", "iter", "node")

    def __init__(self, node: ast.For):
        self.target = node.target
        self.iter = node.iter
        self.node = node

    @property
    def lineno(self) -> int:
        return self.node.lineno


class Handler:
    """An ``except`` clause entry marker (carries the ExceptHandler node)."""

    __slots__ = ("node",)

    def __init__(self, node: ast.ExceptHandler):
        self.node = node

    @property
    def lineno(self) -> int:
        return self.node.lineno


class WithEnter:
    """``with`` statement entry: carries the withitems."""

    __slots__ = ("items", "node")

    def __init__(self, node):
        self.items = node.items
        self.node = node

    @property
    def lineno(self) -> int:
        return self.node.lineno


class WithExit:
    """``with`` statement normal exit (context managers released here on
    the fall-through path; abrupt exits release too — rules must treat
    with-acquired resources as scoped)."""

    __slots__ = ("items", "node")

    def __init__(self, node):
        self.items = node.items
        self.node = node

    @property
    def lineno(self) -> int:
        return self.node.lineno


class Block:
    __slots__ = ("id", "items", "succs")

    def __init__(self, bid: int):
        self.id = bid
        #: simple statements and Cond/LoopIter/Handler/WithEnter/WithExit
        self.items: List[object] = []
        #: (target block id, edge label or None)
        self.succs: List[Tuple[int, Optional[str]]] = []

    def last_lineno(self) -> int:
        for item in reversed(self.items):
            ln = getattr(item, "lineno", None)
            if ln is not None:
                return ln
        return 0


class CFG:
    """One function's control-flow graph. ``entry`` starts the body;
    ``exit`` is the single synthetic sink every return/raise/fall-off
    reaches."""

    def __init__(self):
        self.blocks: Dict[int, Block] = {}
        self.entry: int = -1
        self.exit: int = -1

    def block(self, bid: int) -> Block:
        return self.blocks[bid]

    def predecessors(self, bid: int) -> List[Tuple[int, Optional[str]]]:
        return [(b.id, label) for b in self.blocks.values()
                for (t, label) in b.succs if t == bid]

    def back_edges(self) -> List[Tuple[int, int]]:
        """(src, dst) edges that close a loop (dst discovered before src on
        a DFS from entry) — the loop back-edge test hook."""
        seen: Dict[int, int] = {}
        order = 0
        out: List[Tuple[int, int]] = []
        onpath: List[int] = []

        def dfs(bid: int):
            nonlocal order
            seen[bid] = order
            order += 1
            onpath.append(bid)
            for (t, _lbl) in self.blocks[bid].succs:
                if t not in seen:
                    dfs(t)
                elif t in onpath:
                    out.append((bid, t))
            onpath.pop()

        dfs(self.entry)
        return out


class _FinallyFrame:
    """One pending ``finally`` between a statement and the scopes outside
    it. Abrupt exits enter at ``entry``; once the subgraph is built,
    ``end`` gets an edge to every recorded abrupt target."""

    __slots__ = ("entry", "end", "targets")

    def __init__(self, entry: int):
        self.entry = entry
        self.end: Optional[int] = None
        self.targets: List[int] = []


class _Env:
    __slots__ = ("break_target", "continue_target", "handlers", "finallies")

    def __init__(self, break_target=None, continue_target=None,
                 handlers=(), finallies=()):
        self.break_target = break_target
        self.continue_target = continue_target
        #: handler block ids of the innermost enclosing try
        self.handlers = handlers
        #: innermost-last stack of _FinallyFrame
        self.finallies = finallies

    def child(self, **kw) -> "_Env":
        out = _Env(self.break_target, self.continue_target,
                   self.handlers, self.finallies)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


_SIMPLE = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Pass,
           ast.Assert, ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal,
           ast.Delete, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


class _Builder:
    def __init__(self):
        self.cfg = CFG()
        self._next = 0
        self.cfg.exit = self.new_block().id

    def new_block(self) -> Block:
        b = Block(self._next)
        self._next += 1
        self.cfg.blocks[b.id] = b
        return b

    def edge(self, src: Block, dst_id: int, label: Optional[str] = None):
        if (dst_id, label) not in src.succs:
            src.succs.append((dst_id, label))

    # ---- abrupt-exit routing ----------------------------------------------
    def _route(self, cur: Block, env: _Env, target: int):
        """Edge ``cur`` toward ``target`` through any pending finallies
        (innermost first); the finally end is wired to ``target`` when the
        enclosing Try finishes building."""
        if env.finallies:
            frame = env.finallies[-1]
            self.edge(cur, frame.entry)
            if target not in frame.targets:
                frame.targets.append(target)
        else:
            self.edge(cur, target)

    # ---- statement sequences ----------------------------------------------
    def seq(self, stmts, cur: Optional[Block], env: _Env) -> Optional[Block]:
        """Build ``stmts`` starting in ``cur``; returns the fall-through
        block, or None when every path terminated."""
        for stmt in stmts:
            if cur is None:         # unreachable tail (after return/raise)
                cur = self.new_block()
            cur = self.stmt(stmt, cur, env)
        return cur

    def stmt(self, node, cur: Block, env: _Env) -> Optional[Block]:
        if isinstance(node, _SIMPLE):
            cur.items.append(node)
            return cur
        if isinstance(node, ast.Return):
            cur.items.append(node)
            self._route(cur, env, self.cfg.exit)
            return None
        if isinstance(node, ast.Raise):
            cur.items.append(node)
            if env.handlers:
                for h in env.handlers:
                    self.edge(cur, h)
            else:
                self._route(cur, env, self.cfg.exit)
            return None
        if isinstance(node, ast.Break):
            if env.break_target is not None:
                self._route(cur, env, env.break_target)
            return None
        if isinstance(node, ast.Continue):
            if env.continue_target is not None:
                self._route(cur, env, env.continue_target)
            return None
        if isinstance(node, ast.If):
            return self._if(node, cur, env)
        if isinstance(node, (ast.While,)):
            return self._while(node, cur, env)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            return self._for(node, cur, env)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            return self._with(node, cur, env)
        if isinstance(node, ast.Try):
            return self._try(node, cur, env)
        # unknown compound (e.g. Match): keep it opaque but present
        cur.items.append(node)
        return cur

    # ---- structured statements --------------------------------------------
    def _if(self, node: ast.If, cur: Block, env: _Env) -> Optional[Block]:
        cur.items.append(Cond(node.test, node))
        then_b = self.new_block()
        self.edge(cur, then_b.id, TRUE)
        then_end = self.seq(node.body, then_b, env)
        if node.orelse:
            else_b = self.new_block()
            self.edge(cur, else_b.id, FALSE)
            else_end = self.seq(node.orelse, else_b, env)
        else:
            else_end = None
        if then_end is None and node.orelse and else_end is None:
            return None
        join = self.new_block()
        if not node.orelse:
            self.edge(cur, join.id, FALSE)
        if then_end is not None:
            self.edge(then_end, join.id)
        if else_end is not None:
            self.edge(else_end, join.id)
        return join

    def _while(self, node: ast.While, cur: Block, env: _Env) -> Block:
        head = self.new_block()
        self.edge(cur, head.id)
        head.items.append(Cond(node.test, node))
        body = self.new_block()
        after = self.new_block()
        self.edge(head, body.id, TRUE)
        body_end = self.seq(node.body, body,
                            env.child(break_target=after.id,
                                      continue_target=head.id))
        if body_end is not None:
            self.edge(body_end, head.id)       # the loop back-edge
        self._loop_orelse(node, head, after, env)
        return after

    def _for(self, node, cur: Block, env: _Env) -> Block:
        head = self.new_block()
        self.edge(cur, head.id)
        head.items.append(LoopIter(node))
        body = self.new_block()
        after = self.new_block()
        self.edge(head, body.id, TRUE)
        body_end = self.seq(node.body, body,
                            env.child(break_target=after.id,
                                      continue_target=head.id))
        if body_end is not None:
            self.edge(body_end, head.id)       # the loop back-edge
        self._loop_orelse(node, head, after, env)
        return after

    def _loop_orelse(self, node, head: Block, after: Block, env: _Env):
        """Wire the loop's normal (exhausted) exit: through the ``else``
        clause when present — ``break`` jumps straight to ``after`` and
        must NOT execute it."""
        if node.orelse:
            orelse_b = self.new_block()
            self.edge(head, orelse_b.id, FALSE)
            orelse_end = self.seq(node.orelse, orelse_b, env)
            if orelse_end is not None:
                self.edge(orelse_end, after.id)
        else:
            self.edge(head, after.id, FALSE)

    def _with(self, node, cur: Block, env: _Env) -> Optional[Block]:
        cur.items.append(WithEnter(node))
        end = self.seq(node.body, cur, env)
        if end is None:
            return None
        end.items.append(WithExit(node))
        return end

    def _try(self, node: ast.Try, cur: Block, env: _Env) -> Optional[Block]:
        body_entry = self.new_block()
        self.edge(cur, body_entry.id)
        handler_blocks: List[Block] = []
        for h in node.handlers:
            hb = self.new_block()
            hb.items.append(Handler(h))
            handler_blocks.append(hb)
        frame = None
        finallies = env.finallies
        if node.finalbody:
            frame = _FinallyFrame(self.new_block().id)
            finallies = env.finallies + (frame,)

        # this try's handlers CHAIN onto the enclosing ones — an uncaught
        # raise in a nested (or finally-only) try may still land in an
        # outer except, so replacing the set would sever real release paths
        body_env = env.child(handlers=tuple(b.id for b in handler_blocks)
                             + tuple(env.handlers),
                             finallies=finallies)
        body_end = self.seq(node.body, body_entry, body_env)
        # any statement in the try body may raise into any handler
        for bid in range(body_entry.id, self._next):
            blk = self.cfg.blocks.get(bid)
            if blk is None or blk in handler_blocks:
                continue
            for hb in handler_blocks:
                if bid != hb.id:
                    self.edge(blk, hb.id)
        if node.orelse and body_end is not None:
            body_end = self.seq(node.orelse, body_end, body_env)

        handler_env = env.child(finallies=finallies)
        handler_ends = [self.seq(h.body, hb, handler_env)
                        for h, hb in zip(node.handlers, handler_blocks)]

        ends = [e for e in [body_end, *handler_ends] if e is not None]
        if node.finalbody:
            f_entry = self.cfg.blocks[frame.entry]
            for e in ends:
                self.edge(e, f_entry.id)
            implicit_only = not ends and not frame.targets
            if implicit_only:
                # finally reachable only through an implicit unwind the
                # graph does not model; keep it wired from the body entry
                self.edge(body_entry, f_entry.id)
            f_end = self.seq(node.finalbody, f_entry, env)
            frame.end = f_end.id if f_end is not None else None
            if f_end is not None:
                # abrupt targets route through any still-pending OUTER
                # finallies (env here excludes this frame): a return
                # escaping two nested try/finally levels must pass through
                # BOTH finally bodies before reaching exit
                for t in frame.targets:
                    self._route(f_end, env, t)
                if implicit_only:
                    # the unwind RESUMES after the finally — an enclosing
                    # except may catch it, else the function is exited;
                    # it never falls through to the code after the try
                    if env.handlers:
                        for h in env.handlers:
                            self.edge(f_end, h)
                    else:
                        self._route(f_end, env, self.cfg.exit)
            if not ends:
                return None
            after = self.new_block()
            if f_end is not None:
                self.edge(f_end, after.id)
            return after
        if not ends:
            return None
        after = self.new_block()
        for e in ends:
            self.edge(e, after.id)
        return after


def build_cfg(func) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef. Nested defs/lambdas are
    opaque single statements (build their CFGs separately)."""
    b = _Builder()
    entry = b.new_block()
    b.cfg.entry = entry.id
    end = b.seq(func.body, entry, _Env())
    if end is not None:
        b.edge(end, b.cfg.exit)        # implicit return at fall-off
    return b.cfg


def walk_local(func: ast.AST):
    """``ast.walk`` limited to one function's own scope: does not descend
    into nested def/class/lambda bodies (their statements run in their own
    activation, not on this function's paths)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def iter_functions(tree: ast.AST):
    """Every (qualname, FunctionDef) in a module, including methods and
    nested defs — qualnames use the ``Class.method`` / ``outer.inner``
    dotted form."""
    out = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                out.append((qn, child))
                walk(child, f"{qn}.")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out
