"""R016–R018: program-cache key soundness over the capture-provenance
engine (``analysis/captures.py``).

The serving tier's correctness claims — cross-query program reuse, the
on-disk cross-process plan-key index, fused-stage bit-identity,
warm-start replicas — all rest on one invariant: a cached XLA program
observes nothing that is not part of its cache key.  These rules
machine-check that invariant the way R012 checks locks and R013–R015
check the failure ladder.

R016  cache-key incompleteness — a builder closure captures a value
      with no sanctioned provenance (not key-derived, not a traced
      argument, not provably constant).  Two call sites with different
      values share one specialization; the second silently serves the
      first's stale program.  Wrong *results*, not wrong performance:
      the highest-severity rule in the catalog.

R017  mutable capture by reference — the trace snapshots a list / dict /
      ndarray / attribute at compile time; in-place write sites
      elsewhere in the package mutate the object behind the snapshot,
      and a repr-recomputed key may not reflect it (ndarray reprs
      truncate).

R018  trace-time side effects — metric bumps, tracer spans,
      ``absorb()``, lock acquisition, host I/O inside a traced body run
      once per *compile*, not per call: the effect silently vanishes on
      every cache hit (lost observability) or, worse, deadlocks the
      compile path.
"""
from typing import List, Sequence

from spark_rapids_tpu.analysis.captures import capture_analysis
from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            register)


@register
class CacheKeyIncompleteRule(Rule):
    rule_id = "R016"
    title = ("cached-program builder captures a value not derivable from "
             "its cache key (stale-specialization wrong-results hazard)")
    is_project_rule = True
    help_anchor = "r016"

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        _, sites = capture_analysis(files)
        out: List[Finding] = []
        for site in sites:
            for cap in site.captures:
                if cap.origin is not None:
                    continue
                via = f" ({cap.via})" if cap.via else ""
                out.append(cap.src.finding(
                    self.rule_id, cap.node,
                    f"program cached via {site.route}() at line "
                    f"{site.line} captures '{cap.path}'{via}, which is "
                    "not derivable from the cache key — a stale "
                    "specialization serves wrong results when it "
                    "changes; widen the key, hoist it to a traced "
                    "argument, or pin it as a keyed default"))
        return out


@register
class MutableCaptureRule(Rule):
    rule_id = "R017"
    title = ("traced program captures a mutable object by reference "
             "while the package mutates it in place")
    is_project_rule = True
    help_anchor = "r017"

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        analyzer, sites = capture_analysis(files)
        out: List[Finding] = []
        for site in sites:
            for cap, why in analyzer.mutable_hazards(site):
                out.append(cap.src.finding(
                    self.rule_id, cap.node,
                    f"trace built via {site.route}() at line {site.line} "
                    f"captures mutable '{cap.path}' by reference — {why}; "
                    "the compiled program snapshots it at trace time and "
                    "never sees the mutation — key an immutable copy "
                    "(tuple/frozen) or pass it as a traced argument"))
        return out


@register
class TraceTimeEffectRule(Rule):
    rule_id = "R018"
    title = ("side effect inside a traced body runs once per compile, "
             "not per call")
    is_project_rule = True
    help_anchor = "r018"

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        _, sites = capture_analysis(files)
        out: List[Finding] = []
        seen = set()
        for site in sites:
            for eff in site.effects:
                key = (eff.src.display_path, eff.node.lineno, eff.kind)
                if key in seen:         # one site per effect even when
                    continue            # several routes share the body
                seen.add(key)
                out.append(eff.src.finding(
                    self.rule_id, eff.node,
                    f"{eff.desc} inside the traced body of the "
                    f"{site.route}() program at line {site.line} — jit "
                    "replays the traced result and the effect runs once "
                    "per compile, not per call; hoist it out of the "
                    "trace"))
        return out
