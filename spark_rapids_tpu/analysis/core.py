"""tpu-lint core: findings, rule registry, suppressions, and the driver.

Static-analysis counterpart of the reference's premerge hygiene tooling
(api_validation + the docs/configs.md diff): round-5 showed the engine's
remaining losses come from jit-hygiene and data-movement mistakes that only
surface hours into a benchmark run (the q4 recompile wall, dispatch-bound
queries, the stalled exchange). tpu-lint makes those properties
machine-checkable at premerge time.

Two rule kinds share one registry:

- file rules: ``check(SourceFile) -> findings`` — pure AST checks run per
  module (R001 recompile hazards, R002 hidden host syncs, R003 x64-dtype
  hazards, R006 lock-across-blocking-IO).
- project rules: ``check_project(files) -> findings`` — cross-file
  properties (R004 config drift, R005 Cpu/Tpu exec parity). They run once
  per invocation, only when the analyzed set includes the package itself.

Suppression: ``# tpu-lint: disable=R001`` (or ``disable=R001,R002`` /
``disable=all``) on the flagged line or on a comment line directly above it.
Grandfathered findings live in the baseline file (see baseline.py); every
baseline entry must carry a written justification.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: package-relative path fragments treated as device hot paths (R002 scope)
HOT_PATH_DIRS = ("execs", "ops", "shuffle")

_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9,\s]+)")


@dataclass(frozen=True)
class Finding:
    """One lint result. ``code`` is the stripped source line — the stable
    identity used for baseline matching (line numbers drift, code lines
    rarely do)."""

    rule: str
    path: str
    line: int
    message: str
    code: str = ""

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        tail = f"\n    {self.code}" if self.code else ""
        return f"{loc}: {self.rule}: {self.message}{tail}"

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.code)

    def to_dict(self) -> Dict[str, object]:
        """Machine-readable form for ``--format json`` (CI annotations)."""
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "code": self.code}


class Rule:
    """Base lint rule. Subclasses set ``rule_id``/``title`` and implement
    ``check`` (file rule) or ``check_project`` (project rule)."""

    rule_id: str = ""
    title: str = ""
    #: project rules need the whole package file set, not one module
    is_project_rule: bool = False
    #: anchor into docs/static-analysis.md (SARIF helpUri); defaults to the
    #: lowercased rule id — every catalog entry carries a matching anchor
    help_anchor: str = ""

    def help_uri(self) -> str:
        return f"docs/static-analysis.md#{self.help_anchor or self.rule_id.lower()}"

    def check(self, src: "SourceFile") -> List[Finding]:
        return []

    def check_project(self, files: Sequence["SourceFile"]) -> List[Finding]:
        return []


_RULES: Dict[str, Rule] = {}


def register(rule_cls) -> type:
    """Class decorator adding a rule to the global registry."""
    rule = rule_cls()
    if not rule.rule_id:
        raise ValueError(f"{rule_cls.__name__} has no rule_id")
    if rule.rule_id in _RULES:
        raise ValueError(f"duplicate rule id {rule.rule_id}")
    _RULES[rule.rule_id] = rule
    return rule_cls


def all_rules() -> List[Rule]:
    _load_builtin_rules()
    return [_RULES[k] for k in sorted(_RULES)]


def _load_builtin_rules() -> None:
    # import for side effect: each module registers its rules
    from spark_rapids_tpu.analysis import (rules_cancel,      # noqa: F401
                                           rules_captures,    # noqa: F401
                                           rules_dtype,       # noqa: F401
                                           rules_exceptions,  # noqa: F401
                                           rules_lockorder,   # noqa: F401
                                           rules_locks,       # noqa: F401
                                           rules_metrics,     # noqa: F401
                                           rules_project,     # noqa: F401
                                           rules_races,       # noqa: F401
                                           rules_recompile,   # noqa: F401
                                           rules_resource,    # noqa: F401
                                           rules_serving,     # noqa: F401
                                           rules_sync)        # noqa: F401


class SourceFile:
    """One parsed module: AST with parent links, raw lines, and the
    per-line suppression table."""

    def __init__(self, path: str, text: str, display_path: Optional[str] = None):
        self.path = path
        #: path as reported in findings (repo-relative when possible)
        self.display_path = display_path or path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.suppressions = self._scan_suppressions(text)

    # ---- navigation --------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def inside_loop(self, node: ast.AST) -> bool:
        """True when ``node`` sits in a for/while body or a comprehension —
        the contexts where a per-iteration hazard repeats per batch. Stops at
        the enclosing function boundary: a loop *around* a def does not make
        the def's body per-iteration."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
                                ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                return True
        return False

    def is_hot_path(self) -> bool:
        p = self.display_path.replace("\\", "/")
        return any(f"/{d}/" in p or p.startswith(f"{d}/")
                   for d in HOT_PATH_DIRS)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # ---- suppressions ------------------------------------------------------
    @staticmethod
    def _scan_suppressions(text: str) -> Dict[int, Set[str]]:
        """line -> suppressed rule ids, from ``# tpu-lint: disable=...``
        comments. Tokenize (not regex over raw lines) so string literals
        containing the marker don't suppress anything."""
        table: Dict[int, Set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                ids = {s.strip().upper() for s in m.group(1).split(",")
                       if s.strip()}
                table.setdefault(tok.start[0], set()).update(ids)
        except tokenize.TokenError:
            pass
        return table

    def is_suppressed(self, rule_id: str, lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            ids = self.suppressions.get(ln)
            if ids and (rule_id.upper() in ids or "ALL" in ids):
                return True
        return False

    # ---- finding helper ----------------------------------------------------
    def finding(self, rule_id: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        return Finding(rule_id, self.display_path, lineno, message,
                       self.line_text(lineno))


# ---------------------------------------------------------------- AST helpers
def dotted_name(node: ast.AST) -> str:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); '' when not a plain
    dotted path."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def is_numeric_literal(node: ast.AST) -> bool:
    """A number, or a (nested) list/tuple of numbers — the shapes whose
    default dtype drifts between x32 and x64 modes."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex)) and \
            not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub,
                                                              ast.UAdd)):
        return is_numeric_literal(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return bool(node.elts) and all(is_numeric_literal(e)
                                       for e in node.elts)
    return False


# ------------------------------------------------------------------- driver
@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    files_scanned: int = 0
    errors: List[str] = field(default_factory=list)
    #: per-rule wall seconds (the --profile surface: when the premerge
    #: 30 s guard trips, the three slowest rules name the culprit)
    rule_seconds: Dict[str, float] = field(default_factory=dict)
    #: (path, suppression line, RULE_ID) triples for every inline
    #: suppression that actually absorbed a finding this run — the
    #: staleness check condemns suppression lines absent from this set
    suppressions_hit: Set[Tuple[str, int, str]] = field(default_factory=set)


def load_source(path: str, display_path: Optional[str] = None,
                errors: Optional[List[str]] = None) -> Optional[SourceFile]:
    """Parse one file; on failure return None and, when ``errors`` is given,
    record the reason — a silently skipped file would otherwise make the
    lint gate report clean on code it never saw."""
    try:
        with open(path, encoding="utf-8") as f:
            return SourceFile(path, f.read(), display_path)
    except (OSError, SyntaxError, ValueError) as e:
        if errors is not None:
            errors.append(f"{display_path or path}: {type(e).__name__}: {e}")
        return None


def analyze_files(files: Sequence[SourceFile],
                  rule_ids: Optional[Set[str]] = None,
                  with_project_rules: bool = True,
                  with_file_rules: bool = True) -> AnalysisResult:
    """Run every (selected) rule over ``files``; suppressions applied here so
    rules stay oblivious to them.

    ``--changed-only`` splits one logical run into two calls: file rules
    over the changed subset (``with_project_rules=False``) and project
    rules over the FULL set (``with_file_rules=False``) so interprocedural
    context never shrinks; the CLI merges and filters the findings."""
    import time as _time
    result = AnalysisResult(files_scanned=len(files))
    rules = [r for r in all_rules()
             if rule_ids is None or r.rule_id in rule_ids]
    for rule in rules:
        raw: List[Finding] = []
        t0 = _time.perf_counter()
        if rule.is_project_rule:
            if with_project_rules:
                raw = rule.check_project(files)
        elif with_file_rules:
            for src in files:
                raw.extend(rule.check(src))
        result.rule_seconds[rule.rule_id] = round(
            _time.perf_counter() - t0, 4)
        by_path = {f.display_path: f for f in files}
        for finding in raw:
            src = by_path.get(finding.path)
            if src is not None and src.is_suppressed(finding.rule,
                                                     finding.line):
                rid = finding.rule.upper()
                for ln in (finding.line, finding.line - 1):
                    ids = src.suppressions.get(ln)
                    if ids and (rid in ids or "ALL" in ids):
                        result.suppressions_hit.add((finding.path, ln, rid))
                continue
            result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
