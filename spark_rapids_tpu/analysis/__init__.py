"""tpu-lint: AST static analysis for recompile hazards, hidden host-device
syncs, dtype drift, config/registry drift, exec parity, and lock hygiene.

CLI: ``python -m spark_rapids_tpu.analysis [paths]`` (see __main__.py).
Library: ``analyze_files(files)`` over ``SourceFile`` objects; rules live in
rules_*.py and self-register via the ``@register`` decorator.
"""
from spark_rapids_tpu.analysis.core import (AnalysisResult, Finding, Rule,
                                            SourceFile, all_rules,
                                            analyze_files, load_source,
                                            register)

__all__ = ["AnalysisResult", "Finding", "Rule", "SourceFile", "all_rules",
           "analyze_files", "load_source", "register"]
