"""Forward may-dataflow over the tpu-lint CFG.

One engine, three rules: the analysis walks a function's CFG to a fixpoint,
carrying a frozenset of facts (held resources, for R008) through each
block's statements and across labeled edges. Union at merges — a fact holds
at a point when it holds on ANY path there, which is exactly the shape of
"some path escapes without releasing".

The rule supplies two callbacks:

- ``transfer(state, item, block) -> state`` applied to each block item in
  order (simple statements and the Cond/LoopIter/Handler/WithEnter/WithExit
  markers from cfg.py);
- ``edge_transfer(state, src_block, label) -> state`` (optional) applied
  when following an edge — the hook branch-sensitive kills use (``if buf
  is None: return`` holds no buffer on the true edge).

Termination: states only grow per fact-universe and the universe is finite
(facts are generated from statements, a finite set), so the worklist
converges; a bail-out cap guards pathological functions anyway.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, FrozenSet, Optional

from spark_rapids_tpu.analysis.cfg import CFG, Block

State = FrozenSet

#: safety valve: no real function needs this many worklist visits
_MAX_VISITS = 20000


def run_forward(cfg: CFG,
                transfer: Callable[[State, object, Block], State],
                edge_transfer: Optional[
                    Callable[[State, Block, Optional[str]], State]] = None,
                init: State = frozenset()) -> Dict[int, State]:
    """Fixpoint block-IN states. ``result[cfg.exit]`` is the union of every
    path's facts at function exit."""
    in_states: Dict[int, State] = {cfg.entry: init}
    work = deque([cfg.entry])
    visits = 0
    while work:
        visits += 1
        if visits > _MAX_VISITS:
            break
        bid = work.popleft()
        block = cfg.blocks[bid]
        state = in_states.get(bid, frozenset())
        for item in block.items:
            state = transfer(state, item, block)
        for (succ, label) in block.succs:
            out = state
            if edge_transfer is not None:
                out = edge_transfer(out, block, label)
            prev = in_states.get(succ)
            merged = out if prev is None else (prev | out)
            if prev is None or merged != prev:
                in_states[succ] = merged
                work.append(succ)
    return in_states


def block_out_state(cfg: CFG, bid: int, in_states: Dict[int, State],
                    transfer: Callable[[State, object, Block], State]
                    ) -> State:
    """Re-run one block's transfer to get its OUT state (the engine stores
    IN states only)."""
    block = cfg.blocks[bid]
    state = in_states.get(bid, frozenset())
    for item in block.items:
        state = transfer(state, item, block)
    return state
