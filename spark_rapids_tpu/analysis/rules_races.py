"""R012: thread-escape + lockset data-race detection over the serving fleet.

PRs 12 and 14 turned the engine into a multithreaded serving fleet —
scheduler workers, TCP accept/worker pools, heartbeat and probe loops,
pipeline producers — all coordinating through shared mutable state
(breaker state, replica tables, parked frames, stats windows). Every
concurrency bug shipped so far was found by hand or by a lucky stress
test. R012 makes the discipline machine-checkable, in the
Eraser/RacerD lineage (lockset analysis, no annotations required),
built from the v2 engine's existing parts:

1. **thread-root discovery** — concurrent entry points are enumerated
   statically: every function handed to ``threading.Thread(target=...)``
   (names, ``self.method`` bound methods, lambdas, nested defs), every
   handler registered on the transport's worker pool
   (``register_request_handler`` / ``add_peer_lost_listener``), and the
   serving package's public API surface (``submit``/``result``/client
   calls — documented thread-safe, so it is one MULTI root). A root is
   *multi-instance* when many threads can run it at once: a spawn inside
   a loop (worker pools), a spawn in ``__init__`` (one thread per
   instance, many instances), or a pool-registered handler.
2. **escape analysis** — an attribute ``(module, topmost-base-class,
   attr)`` is SHARED when functions reachable from two distinct roots
   (or twice from one multi root) touch it, within ``_MAX_DEPTH``
   call-graph hops (callgraph.py resolution, attr-name typing included).
3. **lockset dataflow** — a MUST-analysis over the PR 9 CFG's
   ``WithEnter``/``WithExit`` markers computes the set of locks (R009's
   ``(module, topmost-base-class, attr)`` identity) held at every load
   and store of a shared attribute; locks held at every call site
   propagate into callees (entry locksets, intersection over callers).
   A write/write or read/write pair whose locksets intersect to the
   empty set is a data race.

Whitelisted idioms (the engine's sanctioned lock-free patterns):

- **inherently thread-safe attrs** — ``queue.Queue``/``Event``/
  ``Condition``/``Lock``/``Semaphore``/``itertools.count`` and friends:
  their method calls are internally synchronized.
- **publish-snapshot** — every write to the attr is a single plain
  ``obj.attr = value`` store (never ``+=``, never ``attr[k] = v``,
  never ``attr.append(...)``, never a store that reads the attr it
  writes): an atomic reference publish of an immutable snapshot, the
  documented ``last_metrics`` pattern. Read-modify-write defeats it.
- **init-before-spawn** — accesses in ``__init__`` that precede the
  first thread spawn / handler registration happen before the object
  is reachable by any other thread.
- **justified suppression** — ``# tpu-lint: disable=R012`` on an access
  line exempts that access; on the ``class`` line it exempts every
  attribute of the class (for types thread-confined by documented
  contract). Both carry a written justification.

Reporting gate (RacerD's): an attribute is only reported when the code
shows threading intent — at least one access to it holds SOME lock, or
its class owns a lock. A fully lock-free class is either confined or a
design problem a lockset cannot arbitrate; the leaked-thread sub-check
below still covers its spawn hygiene.

Sub-check, same registry: a NON-daemon ``threading.Thread`` started on a
serving/shuffle path with no reachable ``join()``/stop-event on the
shutdown path outlives drain and pins interpreter exit (the PR 14
accept-thread bug was this shape).
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.callgraph import CallGraph, graph_for
from spark_rapids_tpu.analysis.cfg import (Cond, Handler, LoopIter, WithEnter,
                                           WithExit, build_cfg, iter_functions,
                                           walk_local)
from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, dotted_name, register)
from spark_rapids_tpu.analysis.rules_lockorder import (_is_lock_expr,
                                                       _lock_root_class)

#: call-graph hops a thread root's reach extends through
_MAX_DEPTH = 10

#: constructor leaf names whose instances synchronize internally — an
#: attribute assigned one of these is whitelisted wholesale (their method
#: calls are the sanctioned cross-thread channel)
_SAFE_CTORS = frozenset({
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Event",
    "Condition", "Lock", "RLock", "Semaphore", "BoundedSemaphore",
    "Barrier", "local", "count",
})

#: ctors that mark their OWNER class as lock-owning (the reporting gate)
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})

#: attr-name fragments that mark synchronization plumbing itself — the
#: lock/event/queue objects, not the state they guard
_SKIP_HINTS = ("lock", "cond", "mutex", "_cv", "sem", "event", "_evt",
               "queue", "latch")

#: method names that MUTATE their receiver in place (a store access).
#: Deliberately the builtin-container vocabulary only: ``put`` belongs to
#: queues/streams, which synchronize internally.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
})

#: calls that hand their function argument to a worker-pool/callback
#: thread: (call leaf name, argument index of the handler)
_HANDLER_REGISTRARS = {"register_request_handler": 1,
                       "add_peer_lost_listener": 0}

#: serving public API surface = the MAIN root (documented thread-safe:
#: many user threads may drive one client/scheduler concurrently)
_MAIN_ROOT = "<main>"

LockId = Tuple[str, str, str]          # (module, owner class, attr)
AttrId = Tuple[str, str, str]          # (module, owner class, attr)

#: access kinds; everything except "load" is a write
_LOAD, _STORE, _STORE_AUG, _STORE_SUB, _STORE_MUT, _STORE_RMW = (
    "load", "store", "store-aug", "store-sub", "store-mut", "store-rmw")


class _Access:
    __slots__ = ("attr", "func", "line", "kind", "locks", "src", "roots")

    def __init__(self, attr: AttrId, func: str, line: int, kind: str,
                 locks: FrozenSet[LockId], src: SourceFile,
                 roots: FrozenSet[str]):
        self.attr = attr
        self.func = func
        self.line = line
        self.kind = kind
        self.locks = locks
        self.src = src
        self.roots = roots

    @property
    def is_write(self) -> bool:
        return self.kind != _LOAD


class _ThreadRegistry:
    """Thread roots + reachability + type tables for one file set; built
    once per analysis run and cached alongside the call graph (the
    premerge-latency contract)."""

    def __init__(self, graph: CallGraph, files: Sequence[SourceFile]):
        self.graph = graph
        #: root id -> multi-instance?
        self.roots: Dict[str, bool] = {}
        #: root id -> function keys it enters at
        self.root_funcs: Dict[str, List[str]] = {}
        #: roots that ENTER lock-free (thread targets, pool handlers, the
        #: main surface). A SPAWNER root still runs in its callers'
        #: context, so its entry lockset flows from call sites instead.
        self.entry_free: Set[str] = set()
        #: spawn sites for the leaked-thread sub-check:
        #: (src, call node, enclosing qualname, daemon?, binding name)
        self.spawns: List[Tuple[SourceFile, ast.Call, str, bool,
                                Optional[str]]] = []
        #: per-__init__ first spawn/registration lineno (init-before-spawn)
        self.first_spawn_line: Dict[str, int] = {}
        #: (owner, attr) pairs assigned a thread-safe ctor + global names
        self.safe_attrs: Set[Tuple[str, str]] = set()
        self.safe_names: Set[str] = set()
        #: owner classes that own a lock (the reporting gate)
        self.lock_owners: Set[str] = set()
        #: attrs assigned Lock/RLock/Condition ctors: ``with obj.attr:``
        #: acquires them even when the NAME carries no lock hint (the
        #: BounceBufferManager ``_available`` condition shape)
        self.lock_attrs: Set[Tuple[str, str]] = set()
        self.lock_names: Set[str] = set()
        self._scan(files)
        #: function key -> root ids reaching it (the escape map)
        self.reached: Dict[str, Set[str]] = {}
        for rid, funcs in self.root_funcs.items():
            for key in graph.reachable(funcs, max_depth=_MAX_DEPTH):
                self.reached.setdefault(key, set()).add(rid)

    # ---- scanning -----------------------------------------------------------
    def _scan(self, files: Sequence[SourceFile]) -> None:
        main_funcs: List[str] = []
        for key, info in self.graph.functions.items():
            mod = info.module.replace("\\", "/")
            leaf = info.qualname.split(".")[-1]
            if ("/serving/" in mod or mod.startswith("serving/")) and \
                    info.class_name and not leaf.startswith("_"):
                main_funcs.append(key)
        if main_funcs:
            self.roots[_MAIN_ROOT] = True      # many caller threads
            self.root_funcs[_MAIN_ROOT] = main_funcs
            self.entry_free.update(main_funcs)

        seen_calls: Set[int] = set()
        for src in files:
            for qualname, node in iter_functions(src.tree):
                key = f"{src.display_path}::{qualname}"
                info = self.graph.functions.get(key)
                if info is None:
                    continue
                # walk_local, not ast.walk: a nested def is its own
                # iter_functions entry — scanning it from the outer
                # function too would record every spawn twice
                for n in walk_local(node):
                    if not isinstance(n, ast.Call) or id(n) in seen_calls:
                        continue
                    seen_calls.add(id(n))
                    leaf = call_name(n).split(".")[-1]
                    if leaf == "Thread":
                        self._scan_thread(src, info, qualname, node, n)
                    elif leaf in _HANDLER_REGISTRARS:
                        idx = _HANDLER_REGISTRARS[leaf]
                        expr = self._handler_arg(n, idx)
                        if expr is not None:
                            for t in _resolve_func_expr(self.graph, info,
                                                        expr):
                                self._add_root(t, multi=True)
                        self._note_spawn_line(key, n.lineno)
                        # the registrar races its own handlers from here on
                        self._add_root(key, multi=False, pin_entry=False)
            self._scan_types(src)

    @staticmethod
    def _handler_arg(call: ast.Call, idx: int) -> Optional[ast.AST]:
        if len(call.args) > idx:
            return call.args[idx]
        for kw in call.keywords:
            if kw.arg in ("handler", "listener", "callback", "fn"):
                return kw.value
        return None

    def _scan_thread(self, src: SourceFile, info, qualname: str,
                     func_node, call: ast.Call) -> None:
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and len(call.args) >= 2:
            target = call.args[1]          # Thread(group, target, ...)
        daemon = any(kw.arg == "daemon" and
                     isinstance(kw.value, ast.Constant) and
                     kw.value.value is True for kw in call.keywords)
        binding = _thread_binding(src, call)
        self.spawns.append((src, call, qualname, daemon, binding))
        self._note_spawn_line(f"{src.display_path}::{qualname}",
                              call.lineno)
        # the SPAWNER keeps running concurrently with what it spawned —
        # its post-spawn code (constructor tails included) is a root too,
        # but one whose entry lockset still flows from its callers
        self._add_root(f"{src.display_path}::{qualname}", multi=False,
                       pin_entry=False)
        if target is None:
            return
        multi = src.inside_loop(call) or \
            qualname.split(".")[-1] == "__init__"
        for t in _resolve_func_expr(self.graph, info, target):
            self._add_root(t, multi=multi)

    def _add_root(self, key: str, multi: bool,
                  pin_entry: bool = True) -> None:
        self.roots[key] = self.roots.get(key, False) or multi
        self.root_funcs.setdefault(key, [key])
        if pin_entry:
            self.entry_free.add(key)

    def _note_spawn_line(self, func_key: str, lineno: int) -> None:
        cur = self.first_spawn_line.get(func_key)
        if cur is None or lineno < cur:
            self.first_spawn_line[func_key] = lineno

    def _scan_types(self, src: SourceFile) -> None:
        """Thread-safe attr typing + lock ownership, package-wide: the
        whitelist errs toward silence, so a global name fallback is
        acceptable (an attr NAMED like a synchronized one elsewhere is
        overwhelmingly the same idiom)."""
        for n in ast.walk(src.tree):
            value = None
            targets: List[ast.AST] = []
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                value, targets = n.value, list(n.targets)
            elif isinstance(n, ast.AnnAssign) and \
                    isinstance(n.value, ast.Call) and n.target is not None:
                value, targets = n.value, [n.target]
            if value is None:
                continue
            leaf = call_name(value).split(".")[-1]
            if leaf not in _SAFE_CTORS:
                continue
            for t in targets:
                if isinstance(t, ast.Attribute):
                    owner = self._owner_of(src, t)
                    if owner:
                        self.safe_attrs.add((owner, t.attr))
                        if leaf in _LOCK_CTORS:
                            self.lock_owners.add(owner)
                            self.lock_attrs.add((owner, t.attr))
                    self.safe_names.add(t.attr)
                    if leaf in _LOCK_CTORS:
                        self.lock_names.add(t.attr)
                elif isinstance(t, ast.Name):
                    self.safe_names.add(t.id)
                    if leaf in _LOCK_CTORS:
                        self.lock_names.add(t.id)

    def _owner_of(self, src: SourceFile, attr_node: ast.Attribute
                  ) -> Optional[str]:
        if not (isinstance(attr_node.value, ast.Name) and
                attr_node.value.id == "self"):
            return None
        for anc in src.ancestors(attr_node):
            if isinstance(anc, ast.ClassDef):
                return _lock_root_class(self.graph, anc.name) or anc.name
        return None

    # ---- queries ------------------------------------------------------------
    def concurrent(self, a: FrozenSet[str], b: FrozenSet[str]) -> bool:
        """Can an execution of a function with roots ``a`` overlap one
        with roots ``b``? Distinct roots always can; one shared root can
        only when it is multi-instance."""
        for ra in a:
            for rb in b:
                if ra != rb:
                    return True
                if self.roots.get(ra):
                    return True
        return False


def _thread_binding(src: SourceFile, call: ast.Call) -> Optional[str]:
    """Name/attr the Thread object is bound to (``self.reader = Thread``),
    for the join-reachability check; None when start()ed anonymously."""
    parent = src.parent(call)
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            name = dotted_name(t)
            if name:
                return name.split(".")[-1]
    # threading.Thread(...).start() — the Attribute receiver is the call
    if isinstance(parent, ast.Attribute) and parent.attr == "start":
        return None
    return None


def _resolve_func_expr(graph: CallGraph, caller, expr: ast.AST) -> List[str]:
    """Resolve a function-valued expression (Thread target / registered
    handler) to function keys: names, self.method, typed-attr methods,
    and the calls inside a lambda body (the lambda runs them on the new
    thread)."""
    if isinstance(expr, ast.Lambda):
        out: List[str] = []
        for n in ast.walk(expr.body):
            if isinstance(n, ast.Call):
                out.extend(graph.resolve_call(caller, n))
        return out
    name = dotted_name(expr)
    if not name:
        return []
    # reuse the call resolver on a synthetic zero-arg call of the target
    fake = ast.Call(func=expr, args=[], keywords=[])
    return graph.resolve_call(caller, fake)


# ---------------------------------------------------------------- locksets
def _expr_nodes(root: ast.AST):
    """Walk an item's expressions without crossing into nested scopes
    (a lambda/def body runs at another time, on another thread even)."""
    stack = [root]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _item_roots(item) -> List[ast.AST]:
    if isinstance(item, Cond):
        return [item.test]
    if isinstance(item, LoopIter):
        return [item.target, item.iter]
    if isinstance(item, WithEnter):
        out: List[ast.AST] = []
        for it in item.items:
            out.append(it.context_expr)
            if it.optional_vars is not None:
                out.append(it.optional_vars)
        return out
    if isinstance(item, (WithExit, Handler)):
        return []
    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [item]


class _FuncPass:
    """One function's R012 pass: local type table, must-lockset dataflow
    over the CFG with-markers, per-item locksets, accesses and call
    sites."""

    def __init__(self, registry: _ThreadRegistry, src: SourceFile,
                 qualname: str, node):
        self.reg = registry
        self.graph = registry.graph
        self.src = src
        self.qualname = qualname
        self.node = node
        self.key = f"{src.display_path}::{qualname}"
        parts = qualname.split(".")
        self.cls = parts[-2] if len(parts) >= 2 else None
        self.local_types = self._local_types()
        self.item_locks: Dict[int, FrozenSet[LockId]] = {}
        #: (callee key, lockset at the call site)
        self.call_sites: List[Tuple[str, FrozenSet[LockId]]] = []
        #: attr accesses with their LOCAL locksets (entry added later)
        self.accesses: List[Tuple[AttrId, int, str,
                                  FrozenSet[LockId]]] = []
        self._run()

    # ---- receiver typing ---------------------------------------------------
    def _local_types(self) -> Dict[str, str]:
        """name -> class for receivers in this function: ``self``, the
        annotated parameters, and locals assigned a package-class
        construction; the global attr-typing table backs the rest."""
        out: Dict[str, str] = {}
        if self.cls:
            out["self"] = self.cls
        args = self.node.args
        for arg in args.args + args.kwonlyargs:
            if arg.annotation is None:
                continue
            ann = dotted_name(arg.annotation)
            if not ann and isinstance(arg.annotation, ast.Constant):
                ann = str(arg.annotation.value)
            leaf = ann.strip("\"'").split(".")[-1] if ann else ""
            if leaf in self.graph.classes:
                out[arg.arg] = leaf
        for n in ast.walk(self.node):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                leaf = call_name(n.value).split(".")[-1]
                if leaf in self.graph.classes:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = leaf
        return out

    def _recv_class(self, name: str) -> Optional[str]:
        got = self.local_types.get(name)
        if got:
            return got
        hinted = self.graph._attr_types.get(name, set())
        if len(hinted) == 1:
            return next(iter(hinted))
        return None

    def _resolve_attr(self, node: ast.Attribute) -> Optional[AttrId]:
        """(module, topmost-base-class, attr) for a two-part receiver
        (``self.x`` / ``sq.x``); deeper chains stay unresolved — the
        engine under-approximates, it never invents."""
        if not isinstance(node.value, ast.Name):
            return None
        cls = self._recv_class(node.value.id)
        if cls is None or cls not in self.graph.classes:
            return None
        owner = _lock_root_class(self.graph, cls) or cls
        ci = self.graph.classes.get(owner)
        mod = ci.module if ci is not None else self.src.display_path
        return (mod, owner, node.attr)

    def _lock_id(self, expr: ast.AST) -> LockId:
        name = dotted_name(expr)
        parts = name.split(".")
        if len(parts) == 2:
            cls = self._recv_class(parts[0])
            if cls is not None:
                owner = _lock_root_class(self.graph, cls) or cls
                ci = self.graph.classes.get(owner)
                mod = ci.module if ci is not None else self.src.display_path
                return (mod, owner, parts[1])
        # unknown receiver / module global: a WILDCARD identity that
        # matches any lock with the same leaf name — over-merging locks
        # only ever SILENCES a finding, never invents one
        return ("", "", parts[-1])

    def _is_lock_with(self, expr: ast.AST) -> bool:
        """Lock acquisition: the R009 naming convention, or an attr the
        registry saw assigned a Lock/RLock/Condition constructor."""
        if _is_lock_expr(expr):
            return True
        name = dotted_name(expr)
        if not name:
            return False
        leaf = name.split(".")[-1]
        if isinstance(expr, ast.Attribute):
            attr = self._resolve_attr(expr)
            if attr is not None:
                return (attr[1], attr[2]) in self.reg.lock_attrs or \
                    leaf in self.reg.lock_names
        return leaf in self.reg.lock_names

    # ---- the must-dataflow -------------------------------------------------
    def _apply(self, item, state: FrozenSet[LockId]) -> FrozenSet[LockId]:
        if isinstance(item, WithEnter):
            add = [self._lock_id(it.context_expr) for it in item.items
                   if self._is_lock_with(it.context_expr)]
            if add:
                return state | frozenset(add)
        elif isinstance(item, WithExit):
            drop = [self._lock_id(it.context_expr) for it in item.items
                    if self._is_lock_with(it.context_expr)]
            if drop:
                return state - frozenset(drop)
        return state

    def _run(self) -> None:
        cfg = build_cfg(self.node)
        in_states: Dict[int, Optional[FrozenSet[LockId]]] = {
            cfg.entry: frozenset()}
        work = deque([cfg.entry])
        visits = 0
        while work:
            visits += 1
            if visits > 20000:
                break
            bid = work.popleft()
            state = in_states.get(bid)
            if state is None:
                continue
            block = cfg.blocks[bid]
            for item in block.items:
                self.item_locks[id(item)] = state
                state = self._apply(item, state)
            for (succ, _lbl) in block.succs:
                prev = in_states.get(succ)
                merged = state if prev is None else (prev & state)
                if prev is None or merged != prev:
                    in_states[succ] = merged
                    work.append(succ)
        # harvest accesses + call sites with the (converged) item locksets
        caller_info = self.graph.functions.get(self.key)
        for block in cfg.blocks.values():
            for item in block.items:
                locks = self.item_locks.get(id(item), frozenset())
                for root in _item_roots(item):
                    self._harvest(root, locks, caller_info)

    def _harvest(self, stmt: ast.AST, locks: FrozenSet[LockId],
                 caller_info) -> None:
        counted: Set[int] = set()

        def note(attr_node: ast.Attribute, kind: str) -> None:
            attr = self._resolve_attr(attr_node)
            if attr is None:
                return
            counted.add(id(attr_node))
            self.accesses.append((attr, attr_node.lineno, kind, locks))

        # store shapes first, so the loads pass can skip counted nodes
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            flat: List[ast.AST] = []
            for t in targets:
                flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
            for t in flat:
                if isinstance(t, ast.Attribute):
                    kind = _STORE
                    if stmt.value is not None:
                        tid = self._resolve_attr(t)
                        if tid is not None and any(
                                isinstance(n, ast.Attribute) and
                                self._resolve_attr(n) == tid
                                for n in _expr_nodes(stmt.value)):
                            kind = _STORE_RMW
                    note(t, kind)
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute):
                    note(t.value, _STORE_SUB)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Attribute):
                note(stmt.target, _STORE_AUG)
            elif isinstance(stmt.target, ast.Subscript) and \
                    isinstance(stmt.target.value, ast.Attribute):
                note(stmt.target.value, _STORE_SUB)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute):
                    note(t.value, _STORE_SUB)
                elif isinstance(t, ast.Attribute):
                    note(t, _STORE)

        for n in _expr_nodes(stmt):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATORS and \
                        isinstance(n.func.value, ast.Attribute):
                    note(n.func.value, _STORE_MUT)
                if caller_info is not None:
                    for callee in self.graph.resolve_call(caller_info, n):
                        self.call_sites.append((callee, locks))
        for n in _expr_nodes(stmt):
            if isinstance(n, ast.Attribute) and id(n) not in counted and \
                    isinstance(n.ctx, ast.Load):
                note(n, _LOAD)


def _locks_match(a: LockId, b: LockId) -> bool:
    if a == b:
        return True
    if a[0] == "" and a[2] == b[2]:
        return True
    if b[0] == "" and b[2] == a[2]:
        return True
    return False


def _locksets_disjoint(a: FrozenSet[LockId], b: FrozenSet[LockId]) -> bool:
    return not any(_locks_match(x, y) for x in a for y in b)


_REG_CACHE: Dict[int, _ThreadRegistry] = {}


def registry_for(files: Sequence[SourceFile]) -> _ThreadRegistry:
    """Build (or reuse) the thread-root/escape registry for one file set;
    cached alongside the call graph so R012 rides the same build the
    other interprocedural rules share."""
    key = hash(tuple(id(f) for f in files))
    got = _REG_CACHE.get(key)
    if got is None:
        _REG_CACHE.clear()
        got = _ThreadRegistry(graph_for(files), files)
        _REG_CACHE[key] = got
    return got


@register
class ThreadLocksetRaces(Rule):
    rule_id = "R012"
    title = "shared-state data race (thread escape + disjoint locksets)"
    is_project_rule = True

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        reg = registry_for(files)
        if not reg.roots:
            return []
        by_path = {f.display_path: f for f in files}
        passes: Dict[str, _FuncPass] = {}
        for key in sorted(reg.reached):
            info = reg.graph.functions.get(key)
            if info is None:
                continue
            src = by_path.get(info.module)
            if src is None:
                continue
            passes[key] = _FuncPass(reg, src, info.qualname, info.node)

        entry = self._entry_locksets(reg, passes)
        accesses = self._collect(reg, passes, entry)
        findings = self._report(reg, accesses, by_path)
        findings.extend(self._check_leaked_threads(reg))
        return findings

    # ---- interprocedural entry locksets ------------------------------------
    @staticmethod
    def _entry_locksets(reg: _ThreadRegistry,
                        passes: Dict[str, _FuncPass]
                        ) -> Dict[str, FrozenSet[LockId]]:
        """Locks held on EVERY analyzed path into each function:
        intersection over call sites of (caller entry ∪ site lockset);
        roots enter lock-free. Monotone decreasing, so the fixpoint is
        cheap."""
        TOP = None
        entry: Dict[str, Optional[FrozenSet[LockId]]] = {
            k: TOP for k in passes}
        for fk in reg.entry_free:
            if fk in entry:
                entry[fk] = frozenset()
        for _ in range(16):
            changed = False
            for caller, fp in passes.items():
                base = entry.get(caller)
                if base is None:
                    continue
                for (callee, site_locks) in fp.call_sites:
                    if callee not in entry:
                        continue
                    contrib = base | site_locks
                    cur = entry[callee]
                    new = contrib if cur is None else (cur & contrib)
                    if new != cur:
                        entry[callee] = new
                        changed = True
            if not changed:
                break
        return {k: (v if v is not None else frozenset())
                for k, v in entry.items()}

    # ---- access collection + whitelists ------------------------------------
    def _collect(self, reg: _ThreadRegistry, passes: Dict[str, _FuncPass],
                 entry: Dict[str, FrozenSet[LockId]]
                 ) -> Dict[AttrId, List[_Access]]:
        out: Dict[AttrId, List[_Access]] = {}
        for key, fp in passes.items():
            roots = frozenset(reg.reached.get(key, ()))
            if not roots:
                continue
            base = entry.get(key, frozenset())
            leaf = fp.qualname.split(".")[-1]
            spawn_line = reg.first_spawn_line.get(key)
            for (attr, line, kind, locks) in fp.accesses:
                mod, owner, name = attr
                if name.startswith("__"):
                    continue
                low = name.lower()
                if any(h in low for h in _SKIP_HINTS):
                    continue
                if (owner, name) in reg.safe_attrs or \
                        name in reg.safe_names:
                    continue
                # init-before-spawn: the object is unreachable by any
                # other thread until its constructor spawns/publishes
                if leaf == "__init__" and fp.cls is not None and \
                        (_lock_root_class(reg.graph, fp.cls) or fp.cls) \
                        == owner and \
                        (spawn_line is None or line < spawn_line):
                    continue
                if fp.src.is_suppressed(self.rule_id, line):
                    continue
                out.setdefault(attr, []).append(_Access(
                    attr, key, line, kind, base | locks, fp.src, roots))
        return out

    # ---- reporting ----------------------------------------------------------
    def _report(self, reg: _ThreadRegistry,
                by_attr: Dict[AttrId, List[_Access]],
                by_path: Dict[str, SourceFile]) -> List[Finding]:
        findings: List[Finding] = []
        class_suppressed: Dict[str, bool] = {}
        for attr in sorted(by_attr):
            accs = by_attr[attr]
            mod, owner, name = attr
            writes = [a for a in accs if a.is_write]
            if not writes:
                continue
            # publish-snapshot: every write a plain whole-attr store that
            # never reads what it overwrites — atomic reference publish
            if all(a.kind == _STORE for a in writes):
                continue
            # reporting gate: some access holds SOME lock, or the class
            # owns one — the code says "this state is meant to be shared"
            if owner not in class_suppressed:
                class_suppressed[owner] = self._is_class_suppressed(
                    reg, by_path, owner)
            if class_suppressed[owner]:
                continue
            gated = any(a.locks for a in accs) or owner in reg.lock_owners
            if not gated:
                continue
            pair = self._find_race(reg, writes, accs)
            if pair is None:
                continue
            w, other = pair
            findings.append(self._render(reg, attr, w, other))
        return findings

    @staticmethod
    def _is_class_suppressed(reg: _ThreadRegistry,
                             by_path: Dict[str, SourceFile],
                             owner: str) -> bool:
        """A ``# tpu-lint: disable=R012`` on (or right above) the class
        statement exempts every attribute of the class — the documented
        thread-confined-by-contract annotation."""
        ci = reg.graph.classes.get(owner)
        if ci is None:
            return False
        target = by_path.get(ci.module)
        if target is None:
            return False
        for n in ast.walk(target.tree):
            if isinstance(n, ast.ClassDef) and n.name == owner:
                return target.is_suppressed("R012", n.lineno)
        return False

    def _find_race(self, reg: _ThreadRegistry, writes: List[_Access],
                   accs: List[_Access]
                   ) -> Optional[Tuple[_Access, _Access]]:
        """The worst conflicting pair: prefer a lock-free write against a
        locked access (the classic forgotten-lock shape), then any
        disjoint-lockset pair."""
        best: Optional[Tuple[_Access, _Access]] = None
        best_score = -1
        for w in writes:
            for a in accs:
                if not reg.concurrent(w.roots, a.roots):
                    continue
                if not _locksets_disjoint(w.locks, a.locks):
                    continue
                score = (2 if a.is_write else 1) + \
                    (2 if not w.locks and a.locks else 0) + \
                    (1 if not w.locks else 0)
                if score > best_score:
                    best, best_score = (w, a), score
        return best

    def _render(self, reg: _ThreadRegistry, attr: AttrId, w: _Access,
                other: _Access) -> Finding:
        mod, owner, name = attr

        def site(a: _Access) -> str:
            fn = a.func.split("::")[-1]
            locks = ", ".join(sorted(
                f"{o}.{la}" if o else la for (_m, o, la) in a.locks)) \
                or "no locks"
            roots = ", ".join(sorted(
                r.split("::")[-1] if "::" in r else r
                for r in a.roots)[:3])
            return (f"{a.src.display_path}:{a.line} in {fn} "
                    f"[{a.kind}, holding {locks}; threads: {roots}]")

        anchor = ast.Pass()
        anchor.lineno = w.line
        kind = "write/write" if other.is_write else "write/read"
        return w.src.finding(
            self.rule_id, anchor,
            f"data race on {owner}.{name}: {kind} with no common lock — "
            f"{site(w)} vs {site(other)}; both sites are reachable from "
            f"concurrent thread roots and their locksets intersect to "
            f"the empty set. Guard both with the attribute's lock, "
            f"publish an immutable snapshot with a single plain store, "
            f"or justify the benign race with an inline "
            f"'# tpu-lint: disable=R012' comment")

    # ---- leaked-thread sub-check -------------------------------------------
    def _check_leaked_threads(self, reg: _ThreadRegistry) -> List[Finding]:
        findings: List[Finding] = []
        for (src, call, qualname, daemon, binding) in reg.spawns:
            p = src.display_path.replace("\\", "/")
            if not any(f"/{d}/" in p or p.startswith(f"{d}/")
                       for d in ("serving", "shuffle")):
                continue
            if daemon:
                continue
            if src.is_suppressed(self.rule_id, call.lineno):
                continue
            if binding is not None and self._joined(src, binding):
                continue
            findings.append(src.finding(
                self.rule_id, call,
                f"{qualname}: non-daemon thread started on a "
                f"serving/shuffle path with no reachable join()/stop "
                f"on the shutdown path — it outlives drain() and pins "
                f"interpreter exit (the accept-thread leak shape); pass "
                f"daemon=True, or keep the Thread and join it from "
                f"shutdown/close/drain"))
        return findings

    @staticmethod
    def _joined(src: SourceFile, binding: str) -> bool:
        """Some call in the module joins the binding the thread was
        stored under — shutdown-path hygiene at file scope. Matching is
        by the binding's leaf name ONLY: a wildcard on generic loop
        variables (``for t in workers: t.join()``) would silence the
        check for every unrelated thread in the file."""
        for n in ast.walk(src.tree):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "join":
                recv = dotted_name(n.func.value)
                if recv.split(".")[-1] == binding:
                    return True
        return False
