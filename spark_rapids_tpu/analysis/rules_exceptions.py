"""R013–R015: failure-ladder conformance, machine-checked.

The escalation ladder (transfer retry → stage recompute → replica failover)
only works if its signals actually ARRIVE at the rung that triages them.
These rules consume the may-raise fixpoint (analysis/exceptions.py) and the
declared taxonomy (utils/errors.py) to check three contracts:

R013 swallowed-escalation-signal — an ``except`` clause that catches a
    may-raised ladder signal (ShuffleFetchFailedError, ChecksumError,
    WireQueryError, SpillCorruptionError, QueryCancelledError) and neither
    re-raises it, converts it to another classified type, nor reaches a
    registered ``@triage_boundary`` breaks the ladder silently.  A broad
    ``except Exception`` on a path where a signal may-raise needs an
    isinstance triage (the bare-``raise`` branch makes the re-raise visible
    to the engine) or a justified inline suppression.

R014 classification conformance — exception classes arriving at a declared
    ``@triage_boundary`` must be taxonomy-registered (the boundary routes by
    classification; an unregistered type has none), and converting a
    CANCELLATION-classified exception into a RETRYABLE/ESCALATION_SIGNAL
    type is always a finding: a cancelled query must never be retried into
    life.

R015 wire-boundary serializability — package exception types that may-raise
    into a declared ``@wire_boundary`` (executor-daemon control socket,
    serving wire) must carry a registered wire codec; anything else degrades
    to OpaqueWireError on the far side, losing its classification and its
    structured payload.  Flagged at the raise site, where the fix (register
    the type) belongs.

All three inherit the engine's under-approximation: unresolvable calls
contribute no may-raise facts, so every finding rests on an actual resolved
raise path — the errs-toward-silence discipline of R009–R012.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            register)
from spark_rapids_tpu.analysis.exceptions import (ExceptionFlow, HandlerFlow,
                                                  raises_for)
from spark_rapids_tpu.utils import errors as taxonomy

#: call-graph hops from a handler body to a triage boundary that still count
#: as "reaching" it (the handler delegates the decision, it does not hide it)
_TRIAGE_HOPS = 3


def _boundary_keys(flow: ExceptionFlow, marker: str) -> Set[str]:
    return {info.key for info in flow.decorated(marker)}


def _local_calls(stmts: Sequence[ast.stmt]):
    """Call nodes in the given statements, excluding nested def/lambda/class
    bodies (they do not run on the handler's path)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _reaches_triage(flow: ExceptionFlow, hf: HandlerFlow,
                    triage_keys: Set[str]) -> bool:
    """The handler body calls into a registered triage boundary (directly
    or within a few hops — delegating the routing decision is fine)."""
    targets: List[str] = []
    for call in _local_calls(hf.handler.body):
        targets.extend(flow.graph.resolve_call(hf.func, call))
    if not targets:
        return False
    return bool(flow.graph.reachable(targets, max_depth=_TRIAGE_HOPS)
                & triage_keys)


@register
class SwallowedEscalationSignal(Rule):
    rule_id = "R013"
    title = "except clause swallows a may-raised escalation-ladder signal"
    is_project_rule = True
    help_anchor = "r013"

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        flow = raises_for(files)
        signals = set(taxonomy.ladder_signals())
        classified = {s.name for s in taxonomy.TAXONOMY}
        boundary = (_boundary_keys(flow, "triage_boundary")
                    | _boundary_keys(flow, "wire_boundary"))
        triage = _boundary_keys(flow, "triage_boundary")
        findings: List[Finding] = []
        for hf in flow.handler_flows:
            sig = sorted(hf.caught & signals)
            if not sig:
                continue
            if hf.func.key in boundary:
                continue                     # the handler IS the triage
            if hf.raised & (set(sig) | classified):
                continue                     # re-raises or converts
            if _reaches_triage(flow, hf, triage):
                continue                     # delegates the routing
            clause = ", ".join(hf.clause_names)
            findings.append(hf.func.src.finding(
                self.rule_id, hf.handler,
                f"{hf.func.qualname}: except {clause} absorbs "
                f"{', '.join(sig)} — a ladder signal that a higher rung "
                f"must triage (retry/recompute/failover). Re-raise it, "
                f"convert it to a taxonomy-registered type, route it to a "
                f"@triage_boundary function, or add an isinstance triage "
                f"with a bare `raise` for the signal branch; if this "
                f"swallow is genuinely safe, justify it with an inline "
                f"suppression"))
        return findings


@register
class ClassificationConformance(Rule):
    rule_id = "R014"
    title = "unclassified or mis-converted exception at a triage boundary"
    is_project_rule = True
    help_anchor = "r014"

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        flow = raises_for(files)
        triage = _boundary_keys(flow, "triage_boundary")
        cancel = {s.name for s in taxonomy.TAXONOMY
                  if s.classification == taxonomy.CANCELLATION}
        retry_like = {s.name for s in taxonomy.TAXONOMY
                      if s.classification in (taxonomy.RETRYABLE,
                                              taxonomy.ESCALATION_SIGNAL)}
        findings: List[Finding] = []

        # (a) cancellation laundering: always a finding, boundary or not
        for conv in flow.conversions:
            cancelled = sorted(conv.caught & cancel)
            if cancelled and conv.to_name in retry_like:
                findings.append(conv.func.src.finding(
                    self.rule_id, conv.node,
                    f"{conv.func.qualname}: handler converts "
                    f"{', '.join(cancelled)} (CANCELLATION) into "
                    f"{conv.to_name} (retryable) — a cancelled query must "
                    f"never be retried into life. Re-raise the "
                    f"cancellation, or narrow the except clause so it "
                    f"never catches one"))

        # (b) package exception classes arriving at a triage boundary must
        #     be taxonomy-registered (the boundary routes by classification)
        flagged: Set[str] = set()
        for hf in flow.handler_flows:
            if hf.func.key not in triage:
                continue
            for cname in sorted(hf.caught):
                if cname in flagged or cname not in flow.graph.classes:
                    continue
                if not flow.hierarchy.is_exception_class(cname):
                    continue
                if taxonomy.spec_by_name(cname) is not None:
                    continue
                flagged.add(cname)
                site = flow.raise_sites.get(cname, [None])[0]
                anchor_src = site.func.src if site else hf.func.src
                anchor = site.node if site else hf.handler
                where = (f"raised in {site.func.qualname}" if site
                         else "raised upstream")
                findings.append(anchor_src.finding(
                    self.rule_id, anchor,
                    f"{cname} ({where}) arrives at triage boundary "
                    f"{hf.func.qualname} but is not registered in the "
                    f"utils/errors.py taxonomy — the boundary cannot "
                    f"classify it as retryable/permanent/cancellation. "
                    f"Register the class with a classification (and wire "
                    f"code if it crosses a process boundary)"))
        return findings


@register
class WireBoundarySerializability(Rule):
    rule_id = "R015"
    title = "exception without a wire codec may-raises across a process boundary"
    is_project_rule = True
    help_anchor = "r015"

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        flow = raises_for(files)
        wire = _boundary_keys(flow, "wire_boundary")
        findings: List[Finding] = []
        flagged: Set[Tuple[str, str]] = set()
        for hf in flow.handler_flows:
            if hf.func.key not in wire:
                continue
            for cname in sorted(hf.caught):
                if cname not in flow.graph.classes:
                    continue                 # builtins degrade by design
                if not flow.hierarchy.is_exception_class(cname):
                    continue
                spec = taxonomy.spec_by_name(cname)
                if spec is not None and spec.wire_code:
                    continue
                dedup = (cname, hf.func.key)
                if dedup in flagged:
                    continue
                flagged.add(dedup)
                site = flow.raise_sites.get(cname, [None])[0]
                anchor_src = site.func.src if site else hf.func.src
                anchor = site.node if site else hf.handler
                findings.append(anchor_src.finding(
                    self.rule_id, anchor,
                    f"{cname} may-raises across wire boundary "
                    f"{hf.func.qualname} with no registered wire codec — "
                    f"it degrades to OpaqueWireError (non-retryable, no "
                    f"structured payload) on the far side. Register it in "
                    f"utils/errors.py with a wire code and codec fields, "
                    f"or convert it to a registered type before the "
                    f"boundary"))
        return findings
