"""R003: x64-dtype hazards.

Spark LONG/DOUBLE semantics require true 64-bit arithmetic, which this
engine guarantees by setting ``jax_enable_x64`` exactly once in
``spark_rapids_tpu/device.py`` *before* any jax program traces. Two ways
that guarantee silently erodes:

- a module imports ``jax`` / ``jax.numpy`` at module level without first
  importing ``spark_rapids_tpu.device``: imported standalone (a repl, a
  script, a test importing the module directly), its programs trace in x32
  and LONG columns truncate without an error. Every jax-importing module
  carries the one-line guard import (the existing tpu_execs.py idiom).
- an array is built from a bare numeric literal with no dtype
  (``np.array([1, 2])``, ``jnp.zeros(n)``): the default dtype differs
  between x32 and x64 modes, so the same code produces different column
  types depending on import order. Device code pins every constructor's
  dtype explicitly.

Scalar sentinel constructors (``np.int64(-1)`` etc.) are deliberately NOT
flagged: under the engine's pinned x64 mode they are exact, and ops/ uses
them pervasively as typed sentinels.
"""
from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, has_kwarg,
                                            is_numeric_literal, register)

#: jnp constructors whose default dtype depends on the x64 flag; value is the
#: positional index where dtype may appear (None = keyword only)
_JNP_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                  "arange": None}

#: modules exempt from the device-import guard: device.py itself applies the
#: setting, analysis/ never traces programs
_GUARD_EXEMPT = ("device.py", "analysis/")


def _module_imports(tree: ast.Module):
    """(imports_jax, imports_device, first_jax_node) from MODULE-LEVEL
    imports only — lazy imports inside functions run after engine setup."""
    imports_jax = False
    imports_device = False
    first = None
    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    imports_jax = True
                    first = first or node
        elif isinstance(node, ast.ImportFrom):
            m = node.module or ""
            if m == "jax" or m.startswith("jax."):
                imports_jax = True
                first = first or node
            if m == "spark_rapids_tpu.device":
                imports_device = True
            if m == "spark_rapids_tpu" and \
                    any(a.name == "device" for a in node.names):
                imports_device = True
    return imports_jax, imports_device, first


@register
class X64DtypeHazards(Rule):
    rule_id = "R003"
    title = "x64-dtype hazards (unpinned dtypes, missing x64 guard)"

    def check(self, src: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        path = src.display_path.replace("\\", "/")
        if not any(e in path for e in _GUARD_EXEMPT):
            imports_jax, imports_device, first = _module_imports(src.tree)
            if imports_jax and not imports_device:
                findings.append(src.finding(
                    self.rule_id, first,
                    "module imports jax without importing "
                    "spark_rapids_tpu.device first: imported standalone it "
                    "traces in x32 and LONG/DOUBLE columns silently "
                    "truncate; add `from spark_rapids_tpu import device as "
                    "_device  # noqa: F401 - jax setup` above the jax "
                    "import"))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if not cname:
                continue
            head, _, tail = cname.rpartition(".")
            if head in ("np", "numpy", "jnp") and tail in ("array", "asarray"):
                if node.args and is_numeric_literal(node.args[0]) and \
                        len(node.args) < 2 and not has_kwarg(node, "dtype"):
                    findings.append(src.finding(
                        self.rule_id, node,
                        f"{cname}(<numeric literal>) without dtype: the "
                        f"default drifts between x32 and x64 modes; pin "
                        f"dtype explicitly"))
            elif head == "jnp" and tail in _JNP_DTYPE_POS:
                pos = _JNP_DTYPE_POS[tail]
                has_pos = pos is not None and len(node.args) > pos
                if not has_pos and not has_kwarg(node, "dtype"):
                    findings.append(src.finding(
                        self.rule_id, node,
                        f"jnp.{tail}(...) without dtype: default dtype "
                        f"depends on the x64 flag; pin it explicitly"))
        return findings
