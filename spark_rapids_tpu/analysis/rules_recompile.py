"""R001: recompile hazards.

Round-5 VERDICT: q4 spent 3.5 h inside XLA compiles because programs were
re-traced per (scale, query). Flare's core argument — compilation cost must
be amortized, never paid per call — is enforced here in its statically
checkable forms:

- ``jax.jit`` / ``pjit`` / ``jax.shard_map`` constructed inside a for/while
  loop or comprehension: a fresh closure per iteration defeats jit's
  function-identity cache, so every iteration re-traces and may recompile.
- a jit construction invoked immediately (``jax.jit(f)(x)``): the wrapped
  function is dropped after one call, so its compile is paid every time the
  enclosing code runs.
- ``static_argnums`` / ``static_argnames`` passed an unhashable container
  literal built from non-literal elements — flagged conservatively only when
  the value is a dict/set literal (always wrong: jax needs a hashable spec).

The engine's sanctioned pattern is a keyed program cache around the jit
construction (``_cached_jit`` in execs/tpu_execs.py, ``_PROGRAMS`` in
shuffle/partition_kernel.py); anything jit-like created per call should
route through one.
"""
from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, register)

#: callables that construct a compiled program when invoked
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.shard_map",
              "shard_map"}


def is_jit_call(node: ast.Call) -> bool:
    name = call_name(node)
    if name in _JIT_NAMES:
        return True
    # functools.partial(jax.jit, ...) builds the same hazard lazily
    if name in ("functools.partial", "partial") and node.args:
        inner = node.args[0]
        if isinstance(inner, (ast.Attribute, ast.Name)):
            from spark_rapids_tpu.analysis.core import dotted_name
            return dotted_name(inner) in _JIT_NAMES
    return False


def _in_cache_guard(src: SourceFile, node: ast.Call) -> bool:
    """True when the jit construction sits inside the sanctioned keyed-cache
    idiom: an ``if`` branch that also stores into a subscripted container
    (``_PROGRAMS[key] = fn`` after ``fn = _PROGRAMS.get(key)``) — one
    compile per key, however often the enclosing loop runs."""
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While,
                            ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(anc, ast.If):
            for stmt in ast.walk(anc):
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Subscript) for t in stmt.targets):
                    return True
    return False


@register
class RecompileHazards(Rule):
    rule_id = "R001"
    title = "recompile hazards (per-call jit construction)"

    def check(self, src: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not is_jit_call(node):
                continue
            name = call_name(node) or "jit"
            if src.inside_loop(node) and not _in_cache_guard(src, node):
                findings.append(src.finding(
                    self.rule_id, node,
                    f"{name}(...) constructed inside a loop: each iteration "
                    f"builds a fresh closure, defeating jit's program cache "
                    f"and re-tracing per iteration; hoist it out or route it "
                    f"through a keyed program cache (_cached_jit pattern)"))
            parent = src.parent(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                findings.append(src.finding(
                    self.rule_id, node,
                    f"{name}(fn)(...) invoked immediately: the compiled "
                    f"program is dropped after one call, so tracing and "
                    f"compilation are paid on every execution; bind the "
                    f"jitted function once and reuse it"))
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") and \
                        isinstance(kw.value, (ast.Dict, ast.Set)):
                    findings.append(src.finding(
                        self.rule_id, kw.value,
                        f"{name}: {kw.arg} given an unhashable "
                        f"dict/set literal; use an int/str tuple"))
        return findings
