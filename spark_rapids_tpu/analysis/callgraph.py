"""Package-wide call graph for tpu-lint v2.

The interprocedural rules (R009 lock-order, R010 cancellation-unsafe
waits) need to answer "what can this function reach?" across module
boundaries — a lock acquired three calls below a ``with`` block still
orders after it, and a blocking wait is only a serving hazard when an
execute path can actually arrive there.

Name resolution is deliberately static and conservative, in tiers:

1. ``self.m()`` / ``cls.m()`` — the enclosing class, then its package base
   classes (single- and multiple-inheritance chains resolved by name).
2. bare ``f()`` — nested sibling defs, module-level functions, names
   pulled in by ``from pkg.mod import f``, and module classes (an
   instantiation edges to ``Class.__init__``).
3. ``alias.f()`` — module aliases from ``import pkg.mod as alias`` /
   ``from pkg import mod``.
4. attr-name typing — the package consistently names attributes after
   their type (``self.catalog = BufferCatalog()``); every such assignment
   (and ``x: Class`` annotation) feeds a global attr-name -> classes
   table, so ``dm.catalog.remove()`` resolves through the ``catalog``
   component.
5. unique-method fallback — a method name defined by exactly ONE package
   class resolves to it, unless the name collides with builtin-collection
   vocabulary (``get``/``pop``/``append``/...), where the receiver is far
   more likely a dict or list than the one package class.

Unresolvable calls get no edge: the graph under-approximates, which for
both rules errs toward silence, never toward false findings. Summaries
are bounded: ``reachable()`` BFSes to ``max_depth`` call hops, so a
pathological chain cannot blow up premerge latency, and recursion (direct
or mutual) terminates because visited nodes are never re-expanded.
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.cfg import iter_functions, walk_local
from spark_rapids_tpu.analysis.core import SourceFile, dotted_name

#: method names that are overwhelmingly builtin-collection calls; the
#: unique-method fallback refuses these (tier-4 typing may still resolve)
_COMMON_NAMES = frozenset({
    "get", "set", "pop", "add", "append", "extend", "insert", "remove",
    "update", "clear", "copy", "items", "keys", "values", "join", "split",
    "strip", "close", "open", "read", "write", "send", "recv", "put",
    "start", "run", "wait", "acquire", "release", "setdefault", "discard",
    "popitem", "sort", "index", "count", "format", "encode", "decode",
})

#: default call-hop bound for reachability summaries
DEFAULT_DEPTH = 16


def module_name(display_path: str) -> str:
    p = display_path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class FunctionInfo:
    __slots__ = ("key", "module", "qualname", "node", "src", "class_name")

    def __init__(self, module: str, qualname: str, node, src: SourceFile):
        self.module = module                 # display path
        self.qualname = qualname             # Class.method / func / outer.inner
        self.key = f"{module}::{qualname}"
        self.node = node
        self.src = src
        parts = qualname.split(".")
        self.class_name = parts[-2] if len(parts) >= 2 else None


class ClassInfo:
    __slots__ = ("module", "name", "bases", "methods")

    def __init__(self, module: str, name: str):
        self.module = module
        self.name = name
        self.bases: List[str] = []           # base-class NAMES (unresolved)
        self.methods: Dict[str, str] = {}    # method name -> function key


class CallGraph:
    def __init__(self, files: Sequence[SourceFile]):
        self.functions: Dict[str, FunctionInfo] = {}
        self.edges: Dict[str, Set[str]] = {}
        #: calls made inside lambda bodies nested in each function. They are
        #: DEFERRED: the lambda runs in its own activation, possibly long
        #: after (and far from) the enclosing function's paths, so they must
        #: not join ``edges`` — R009's lock-order reachability would otherwise
        #: claim a closure defined under a lock runs under it. The capture
        #: analysis (R016-R018) needs them: a jit builder written as
        #: ``lambda: make(...)`` observes everything ``make`` observes.
        self.deferred_edges: Dict[str, Set[str]] = {}
        #: class name -> ClassInfo (package class names are unique enough;
        #: a collision keeps the first and is logged nowhere — conservative)
        self.classes: Dict[str, ClassInfo] = {}
        #: module display path -> {bare name -> function key}
        self._module_funcs: Dict[str, Dict[str, str]] = {}
        #: module display path -> {alias -> module display path}
        self._module_aliases: Dict[str, Dict[str, str]] = {}
        #: module display path -> {imported name -> (module path, name)}
        self._from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        #: attr/param name -> class names assigned to it anywhere
        self._attr_types: Dict[str, Set[str]] = {}
        #: method name -> function keys across all classes
        self._methods_by_name: Dict[str, List[str]] = {}
        self._index(files)
        self._link(files)

    # ---- indexing ----------------------------------------------------------
    def _index(self, files: Sequence[SourceFile]) -> None:
        by_modname = {module_name(f.display_path): f.display_path
                      for f in files}
        #: deferred attr-typing candidates: (attr-or-param name, class name)
        typing_candidates: List[Tuple[str, str]] = []
        for src in files:
            mod = src.display_path
            funcs: Dict[str, str] = {}
            for qualname, node in iter_functions(src.tree):
                info = FunctionInfo(mod, qualname, node, src)
                self.functions[info.key] = info
                parts = qualname.split(".")
                # only TOP-LEVEL functions enter the bare-name table: a
                # method's leaf name must not capture bare calls to
                # same-named parameters/locals (tier-5 handles unique
                # method names, WITH the common-name guard)
                if len(parts) == 1:
                    funcs[qualname] = info.key
            self._module_funcs[mod] = funcs

            aliases: Dict[str, str] = {}
            froms: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        target = by_modname.get(a.name)
                        if target:
                            aliases[a.asname or a.name.split(".")[-1]] = target
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        sub = by_modname.get(f"{node.module}.{a.name}")
                        if sub:                      # from pkg import mod
                            aliases[a.asname or a.name] = sub
                            continue
                        target = by_modname.get(node.module)
                        if target:                   # from pkg.mod import f
                            froms[a.asname or a.name] = (target, a.name)
                elif isinstance(node, ast.ClassDef):
                    ci = self.classes.setdefault(node.name,
                                                 ClassInfo(mod, node.name))
                    for b in node.bases:
                        bn = dotted_name(b)
                        if bn:
                            ci.bases.append(bn.split(".")[-1])
                    for stmt in node.body:
                        if isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            key = f"{mod}::{node.name}.{stmt.name}"
                            if key in self.functions:
                                ci.methods[stmt.name] = key
                elif isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    cname = dotted_name(node.value.func).split(".")[-1]
                    if cname and cname[:1].isupper():
                        for t in node.targets:
                            if isinstance(t, ast.Attribute):
                                typing_candidates.append((t.attr, cname))
                            elif isinstance(t, ast.Name):
                                typing_candidates.append((t.id, cname))
                elif isinstance(node, ast.AnnAssign) and \
                        node.annotation is not None:
                    cname = dotted_name(node.annotation).split(".")[-1]
                    tgt = node.target
                    if isinstance(tgt, ast.Attribute):
                        typing_candidates.append((tgt.attr, cname))
                    elif isinstance(tgt, ast.Name):
                        typing_candidates.append((tgt.id, cname))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for arg in node.args.args + node.args.kwonlyargs:
                        if arg.annotation is None:
                            continue
                        ann = dotted_name(arg.annotation)
                        if not ann and isinstance(arg.annotation,
                                                  ast.Constant):
                            ann = str(arg.annotation.value)
                        if ann:
                            typing_candidates.append(
                                (arg.arg, ann.strip("\"'").split(".")[-1]))
            self._module_aliases[mod] = aliases
            self._from_imports[mod] = froms

        for key, info in self.functions.items():
            if info.class_name:
                name = info.qualname.split(".")[-1]
                self._methods_by_name.setdefault(name, []).append(key)

        # attr-name typing: self.X = ClassName(...) / x: ClassName — the
        # candidates resolve only after every package class is indexed
        for (name, cname) in typing_candidates:
            if cname in self.classes:
                self._attr_types.setdefault(name, set()).add(cname)

    # ---- class-chain lookup ------------------------------------------------
    def _method_in_chain(self, cls_name: str, meth: str,
                         _seen: Optional[Set[str]] = None) -> Optional[str]:
        seen = _seen or set()
        if cls_name in seen:
            return None
        seen.add(cls_name)
        ci = self.classes.get(cls_name)
        if ci is None:
            return None
        if meth in ci.methods:
            return ci.methods[meth]
        for base in ci.bases:
            found = self._method_in_chain(base, meth, seen)
            if found:
                return found
        return None

    # ---- call-site resolution ----------------------------------------------
    def resolve_call(self, caller: FunctionInfo, call: ast.Call
                     ) -> List[str]:
        name = dotted_name(call.func)
        if not name:
            return []
        parts = name.split(".")
        mod = caller.module

        if parts[0] in ("self", "cls") and len(parts) == 2 and \
                caller.class_name:
            found = self._method_in_chain(caller.class_name, parts[1])
            if found:
                return [found]
            return self._fallback(parts[1])

        if len(parts) == 1:
            bare = parts[0]
            # nested sibling: outer.inner defined in this function scope or
            # any enclosing one (a closure two defs deep still sees the
            # helpers of every scope above it)
            scope = caller.qualname
            while scope:
                sibling = f"{mod}::{scope}.{bare}"
                if sibling in self.functions:
                    return [sibling]
                scope = scope.rsplit(".", 1)[0] if "." in scope else ""
            funcs = self._module_funcs.get(mod, {})
            if bare in funcs:
                return [funcs[bare]]
            frm = self._from_imports.get(mod, {}).get(bare)
            if frm:
                target_mod, target_name = frm
                key = f"{target_mod}::{target_name}"
                if key in self.functions:
                    return [key]
                init = self._method_in_chain(target_name, "__init__")
                if init:
                    return [init]
            if bare in self.classes:
                init = self._method_in_chain(bare, "__init__")
                return [init] if init else []
            return []

        # alias.f(...) — module alias from imports
        alias_target = self._module_aliases.get(mod, {}).get(parts[0])
        if alias_target is not None and len(parts) == 2:
            funcs = self._module_funcs.get(alias_target, {})
            if parts[1] in funcs:
                return [funcs[parts[1]]]

        # x.attr_chain.m(...) — attr-name typing on the last receiver part
        meth = parts[-1]
        recv_hint = parts[-2] if len(parts) >= 2 else ""
        hinted = self._attr_types.get(recv_hint, set())
        keys = []
        for cname in hinted:
            found = self._method_in_chain(cname, meth)
            if found:
                keys.append(found)
        if keys:
            return keys
        # self.attr.m through the enclosing class's own annotated attrs is
        # covered by the global table above; last resort:
        return self._fallback(meth)

    def _fallback(self, meth: str) -> List[str]:
        if meth in _COMMON_NAMES:
            return []
        keys = self._methods_by_name.get(meth, [])
        return list(keys) if len(keys) == 1 else []

    # ---- edge construction --------------------------------------------------
    def _link(self, files: Sequence[SourceFile]) -> None:
        for key, info in self.functions.items():
            targets: Set[str] = set()
            deferred: Set[str] = set()
            # calls inside nested defs belong to the nested function; calls
            # inside lambda bodies are collected separately (deferred) — a
            # lambda body has no statements, so ast.walk over it only ever
            # meets expressions and nested lambdas/comprehensions
            for node in walk_local(info.node):
                if isinstance(node, ast.Call):
                    for t in self.resolve_call(info, node):
                        if t != key:
                            targets.add(t)
                elif isinstance(node, ast.Lambda):
                    for sub in ast.walk(node.body):
                        if isinstance(sub, ast.Call):
                            for t in self.resolve_call(info, sub):
                                if t != key:
                                    deferred.add(t)
            self.edges[key] = targets
            self.deferred_edges[key] = deferred

    # ---- queries ------------------------------------------------------------
    def callees(self, key: str) -> Set[str]:
        return self.edges.get(key, set())

    def callees_all(self, key: str) -> Set[str]:
        """Immediate callees INCLUDING calls deferred inside lambda bodies.

        ``callees``/``reachable`` stay lambda-blind on purpose (R009: a
        closure defined under a lock is not running under it); capture
        provenance wants the opposite — whatever a builder lambda calls, the
        compiled program observed."""
        return self.edges.get(key, set()) | self.deferred_edges.get(key, set())

    def reachable(self, roots: Sequence[str],
                  max_depth: int = DEFAULT_DEPTH) -> Set[str]:
        """Functions reachable from ``roots`` within ``max_depth`` call
        hops (roots included). Cycles terminate: a visited key is never
        re-expanded."""
        seen: Set[str] = set(r for r in roots if r in self.functions)
        frontier = deque((r, 0) for r in seen)
        while frontier:
            key, d = frontier.popleft()
            if d >= max_depth:
                continue
            for t in self.edges.get(key, ()):
                if t not in seen:
                    seen.add(t)
                    frontier.append((t, d + 1))
        return seen

    def find(self, module_suffix: str, qualname: str) -> Optional[str]:
        """Function key by module path suffix + qualname (test/rule hook)."""
        for key, info in self.functions.items():
            if info.qualname == qualname and \
                    info.module.endswith(module_suffix):
                return key
        return None


_GRAPH_CACHE: Dict[int, CallGraph] = {}


def graph_for(files: Sequence[SourceFile]) -> CallGraph:
    """Build (or reuse) the call graph for one analysis run's file set —
    R009 and R010 share a single build so the interprocedural pass stays
    inside the premerge runtime budget."""
    key = hash(tuple(id(f) for f in files))
    got = _GRAPH_CACHE.get(key)
    if got is None:
        _GRAPH_CACHE.clear()          # one live file set at a time
        got = CallGraph(files)
        _GRAPH_CACHE[key] = got
    return got
