"""Capture-provenance analysis over program-cache builder sites (v5 engine).

Every XLA program the serving tier caches is produced by a *builder*
routed through one of the R007 cache idioms (``_cached_jit`` /
``_shard_jit`` / ``PhysicalExec.cached_program`` /
``ProgramCache.get_or_build``; ``eval_exprs_device`` routes through its
internal ``get_or_build`` and is covered there).  The cache contract is:
a compiled program may observe **nothing** that is not part of its cache
key.  An unkeyed observable means two call sites with different values
share one specialization — the second silently gets the first's program
and serves stale wrong results.  That contract is what this engine
machine-checks.

For each builder site the engine computes the builder closure tree's
observable-value set — free closure reads, ``self.*`` attribute reads,
module globals, default-argument pins — resolved through the PR 9 call
graph.  Unlike ``cfg.walk_local`` this pass sees *through* lambdas and
comprehensions (their scoping handled properly: comprehension targets
are comprehension-local), so ``lambda:``-form builders and listcomps
contribute their captures.  Unresolved references contribute nothing:
the engine under-approximates, it errs toward silence, never invents.

Each capture then gets a provenance against the sanctioned origins:

=============  =========================================================
origin         meaning
=============  =========================================================
``key``        the dotted path appears in (or is a direct component of)
               the cache-key expression — recomputed per lookup, so a
               change reaches the cache as a new key
``derived``    every reaching local assignment computes it exclusively
               from key/const paths (fixpoint) — e.g.
               ``nflat = flat_len(schema)`` with ``schema`` keyed
``const``      provably constant binding: a builtin, an import, a
               module-level def/class, or a module global assigned
               exactly once and never declared ``global`` in a function
``code``       a function defined in an enclosing scope — code, not
               data; its *own* frees are analyzed in its place
``delegated``  a callable parameter of the enclosing function that the
               closure invokes — the wrapper's callers pass the real
               builder and are analyzed at their own sites
``None``       unsanctioned -> R016
=============  =========================================================

Traced runtime arguments (the traced function's own parameters) never
appear as captures — they are bound names, excluded by construction.

The engine also identifies the *traced body* (the callable the builder
returns, unwrapping ``jax.jit``/factory indirection) and scans it for
trace-time side effects (R018), and cross-references captures against
package-wide in-place write sites (R017).
"""
from __future__ import annotations

import ast
import builtins
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                                 graph_for)
from spark_rapids_tpu.analysis.core import SourceFile, call_name, dotted_name

#: cache route -> positional indices whose arguments form the cache key.
#: ``_shard_jit`` folds mesh, caller key AND both sharding specs into the
#: inner ``_cached_jit`` key, so all four positions are key positions.
_ROUTE_KEY_ARGS: Dict[str, Tuple[int, ...]] = {
    "_cached_jit": (0,),
    "cached_program": (0,),
    "get_or_build": (0,),
    "_shard_jit": (0, 1, 3, 4),
}
#: cache route -> positional index of the builder argument
_ROUTE_BUILDER_ARG: Dict[str, int] = {
    "_cached_jit": 1,
    "cached_program": 1,
    "get_or_build": 1,
    "_shard_jit": 2,
}
_KEY_KWARGS = frozenset({"key", "in_specs", "out_specs"})
_BUILDER_KWARG = "builder"

_BUILTIN_NAMES = frozenset(dir(builtins))
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_COMPS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: interprocedural recursion bound — deep enough for builder -> factory
#: -> traced-fn chains, shallow enough to stay inside the premerge budget
_MAX_DEPTH = 4

#: in-place mutator vocabulary (the R012 set): a call of one of these on
#: ``x.attr`` / a module global is a write to the *object*, invisible to
#: a repr-recomputed key and to a compile-time trace snapshot
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "fill",
})

#: attr-name fragments marking synchronization plumbing (R009 convention)
_LOCK_HINTS = ("lock", "cond", "mutex", "_cv", "sem")


# ---------------------------------------------------------------------------
# scope-aware free-variable extraction (lambdas + comprehensions included)
# ---------------------------------------------------------------------------

def _arg_names(args: ast.arguments) -> List[str]:
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def _target_names(target: ast.AST) -> Iterable[str]:
    """Names BOUND by an assignment target (``obj.x = v`` binds nothing)."""
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            yield n.id


def _local_walk(root: ast.AST):
    """Nodes of ``root``'s own scope: nested function/lambda bodies and
    comprehensions are yielded but not entered (their default/decorator
    expressions, which evaluate in this scope, ARE entered)."""
    if isinstance(root, ast.Lambda):
        stack: List[ast.AST] = [root.body]
    elif isinstance(root, _FUNCS):
        stack = list(root.body)
    else:
        stack = [root]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _SCOPES):
            if isinstance(node, _FUNCS):
                stack.extend(node.decorator_list)
            a = node.args
            stack.extend(d for d in list(a.defaults) + list(a.kw_defaults)
                         if d is not None)
            continue
        if isinstance(node, _COMPS):
            continue
        stack.extend(ast.iter_child_nodes(node))


def bound_names(fn: ast.AST) -> Set[str]:
    """Every name the scope of ``fn`` binds: params, assignment targets,
    loop/with/except/walrus targets, imports, nested def/class names —
    minus names pierced by ``global``/``nonlocal`` declarations."""
    bound: Set[str] = set(_arg_names(fn.args)) if isinstance(fn, _SCOPES) \
        else set()
    pierced: Set[str] = set()
    for node in _local_walk(fn):
        if isinstance(node, (*_FUNCS, ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                bound.update(_target_names(t))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
            bound.update(_target_names(node.target))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bound.update(_target_names(node.target))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    bound.update(_target_names(item.optional_vars))
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ExceptHandler):
            if node.name:
                bound.add(node.name)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            pierced.update(node.names)
    return bound - pierced


def _scan(roots: Sequence[ast.AST], bound: Set[str],
          reads: Dict[str, ast.AST], called: Set[str],
          calls: List[ast.Call]) -> None:
    """Collect free dotted Load paths / invoked paths / call nodes over
    ``roots``, descending through nested scopes with proper shadowing."""

    def add(path: str, node: ast.AST) -> None:
        if path.split(".", 1)[0] not in bound and path not in reads:
            reads[path] = node

    def visit(node: ast.AST) -> None:
        if isinstance(node, _SCOPES):
            a = node.args
            for d in list(a.defaults) + list(a.kw_defaults):
                if d is not None:
                    visit(d)
            if isinstance(node, _FUNCS):
                for d in node.decorator_list:
                    visit(d)
            inner_roots = [node.body] if isinstance(node, ast.Lambda) \
                else list(node.body)
            _scan(inner_roots, bound | bound_names(node), reads, called,
                  calls)
            return
        if isinstance(node, _COMPS):
            comp_bound = set()
            for gen in node.generators:
                comp_bound.update(_target_names(gen.target))
            inner: List[ast.AST] = (
                [node.key, node.value] if isinstance(node, ast.DictComp)
                else [node.elt])
            for gen in node.generators:
                inner.append(gen.iter)
                inner.extend(gen.ifs)
            _scan(inner, bound | comp_bound, reads, called, calls)
            return
        if isinstance(node, ast.Call):
            calls.append(node)
            fpath = dotted_name(node.func)
            if fpath:
                if fpath.split(".", 1)[0] not in bound:
                    called.add(fpath)
                add(fpath, node.func)
                for sub in node.args:
                    visit(sub)
                for kw in node.keywords:
                    visit(kw.value)
                return
        if isinstance(node, ast.Attribute):
            if isinstance(node.ctx, ast.Load):
                path = dotted_name(node)
                if path:
                    add(path, node)
                    return
            else:
                base = dotted_name(node.value)
                if base:                 # obj.x = v observes obj
                    add(base, node)
                    return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                add(node.id, node)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for r in roots:
        visit(r)


def free_reads(fn: ast.AST) -> Tuple[Dict[str, ast.AST], Set[str],
                                     List[ast.Call]]:
    """(free dotted path -> first reading node, invoked free paths, every
    call node in the closure tree) for a function or lambda.  A nested
    scope's frees bubble out unless an enclosing scope binds them."""
    reads: Dict[str, ast.AST] = {}
    called: Set[str] = set()
    calls: List[ast.Call] = []
    roots = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
    _scan(roots, bound_names(fn), reads, called, calls)
    return reads, called, calls


def free_paths(fn: ast.AST) -> Set[str]:
    """Free dotted paths of a function/lambda (test + engine hook)."""
    return set(free_reads(fn)[0])


def expr_paths(expr: ast.AST) -> Set[str]:
    """Every dotted Load path an expression observes (no scope filter)."""
    reads: Dict[str, ast.AST] = {}
    _scan([expr], set(), reads, set(), [])
    return set(reads)


# ---------------------------------------------------------------------------
# module environment: constant bindings + in-place mutation sites
# ---------------------------------------------------------------------------

class ModuleEnv:
    __slots__ = ("src", "imports", "defs", "classes", "consts",
                 "mut_globals")

    def __init__(self, src: SourceFile):
        self.src = src
        self.imports: Set[str] = set()
        self.defs: Dict[str, ast.AST] = {}
        self.classes: Set[str] = set()
        self.consts: Set[str] = set()
        self.mut_globals: Set[str] = set()
        assigned: Dict[str, int] = {}
        globaled: Set[str] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    self.imports.add((alias.asname or alias.name)
                                     .split(".")[0])
            elif isinstance(node, ast.Global):
                globaled.update(node.names)
        stmts = list(src.tree.body)
        for s in list(stmts):            # one level of top-level if/try
            if isinstance(s, ast.If):
                stmts.extend(s.body)
                stmts.extend(s.orelse)
            elif isinstance(s, ast.Try):
                stmts.extend(s.body)
                for h in s.handlers:
                    stmts.extend(h.body)
        for s in stmts:
            if isinstance(s, _FUNCS):
                self.defs[s.name] = s
            elif isinstance(s, ast.ClassDef):
                self.classes.add(s.name)
            elif isinstance(s, ast.Assign):
                for t in s.targets:
                    for n in _target_names(t):
                        assigned[n] = assigned.get(n, 0) + 1
            elif isinstance(s, ast.AnnAssign) and s.value is not None:
                for n in _target_names(s.target):
                    assigned[n] = assigned.get(n, 0) + 1
        self.consts = {n for n, c in assigned.items()
                       if c == 1 and n not in globaled}
        # in-place writes to module globals anywhere in this module
        module_names = set(assigned)
        for node in ast.walk(src.tree):
            name = _inplace_write_base(node)
            if name and "." not in name and name in module_names:
                self.mut_globals.add(name)


def _inplace_write_base(node: ast.AST) -> str:
    """Dotted path of the object an AST node mutates in place, or ''."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        return dotted_name(node.func.value)
    target = None
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        target = node.targets[0]
    elif isinstance(node, ast.AugAssign):
        target = node.target
    if isinstance(target, ast.Subscript):
        return dotted_name(target.value)
    return ""


def _mutated_attrs(files: Sequence[SourceFile]) -> Set[str]:
    """Attr leaf names with in-place write sites anywhere in the package
    (``recv.X.append(..)`` / ``recv.X[k] = v`` / ``recv.X[k] += v``)."""
    out: Set[str] = set()
    for src in files:
        for node in ast.walk(src.tree):
            base = _inplace_write_base(node)
            if base and "." in base:
                out.add(base.split(".")[-1])
    return out


# ---------------------------------------------------------------------------
# builder-site model
# ---------------------------------------------------------------------------

class Capture:
    """One observable value a cached program's closure tree reads."""
    __slots__ = ("path", "node", "src", "origin", "via")

    def __init__(self, path: str, node: ast.AST, src: SourceFile,
                 via: str = ""):
        self.path = path
        self.node = node
        self.src = src
        self.origin: Optional[str] = None   # key|derived|const|code|delegated
        self.via = via                      # call chain note for messages


class Effect:
    """One trace-time side effect inside a traced body."""
    __slots__ = ("node", "src", "kind", "desc")

    def __init__(self, node: ast.AST, src: SourceFile, kind: str, desc: str):
        self.node = node
        self.src = src
        self.kind = kind
        self.desc = desc


class BuilderSite:
    """One cache-route call with its key paths, captures and effects."""
    __slots__ = ("src", "call", "route", "key_paths", "captures", "effects",
                 "delegated")

    def __init__(self, src: SourceFile, call: ast.Call, route: str):
        self.src = src
        self.call = call
        self.route = route
        self.key_paths: Set[str] = set()
        self.captures: List[Capture] = []
        self.effects: List[Effect] = []
        #: builder is a callable parameter of the enclosing function —
        #: this site is a forwarding wrapper, analyzed at its callers
        self.delegated = False

    @property
    def line(self) -> int:
        return self.call.lineno


# ---------------------------------------------------------------------------
# the analysis
# ---------------------------------------------------------------------------

class _SiteAnalyzer:
    def __init__(self, files: Sequence[SourceFile]):
        self.files = files
        self.graph: CallGraph = graph_for(files)
        self.envs: Dict[str, ModuleEnv] = {
            f.display_path: ModuleEnv(f) for f in files}
        self.mutated_attrs = _mutated_attrs(files)
        self.info_by_node: Dict[int, FunctionInfo] = {
            id(i.node): i for i in self.graph.functions.values()}

    # -- site discovery ------------------------------------------------------
    def sites(self) -> List[BuilderSite]:
        out: List[BuilderSite] = []
        for src in self.files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                leaf = call_name(node).split(".")[-1]
                if leaf in _ROUTE_BUILDER_ARG:
                    out.append(self._analyze(src, node, leaf))
        return out

    # -- per-site ------------------------------------------------------------
    def _analyze(self, src: SourceFile, call: ast.Call,
                 route: str) -> BuilderSite:
        site = BuilderSite(src, call, route)
        stack = [a for a in src.ancestors(call)
                 if isinstance(a, _FUNCS)][::-1]        # outer -> inner
        assigns = self._stack_assigns(stack)
        local_defs = self._stack_defs(stack)
        stack_params: Set[str] = set()
        for fn in stack:
            stack_params.update(_arg_names(fn.args))
        env = self.envs.get(src.display_path) or ModuleEnv(src)

        key_exprs = [call.args[i] for i in _ROUTE_KEY_ARGS[route]
                     if i < len(call.args)]
        key_exprs += [kw.value for kw in call.keywords
                      if kw.arg in _KEY_KWARGS]
        if not key_exprs:
            return site
        site.key_paths = self._key_paths(key_exprs, assigns)
        if route == "cached_program":
            site.key_paths.add("self.name")     # the implicit key prefix

        builder = None
        if len(call.args) > _ROUTE_BUILDER_ARG[route]:
            builder = call.args[_ROUTE_BUILDER_ARG[route]]
        else:
            for kw in call.keywords:
                if kw.arg == _BUILDER_KWARG:
                    builder = kw.value
        if builder is None:
            return site

        reads: Dict[str, ast.AST] = {}
        called: Set[str] = set()
        calls: List[ast.Call] = []
        pending = self._builder_roots(site, builder, local_defs,
                                      stack_params, env, reads, called,
                                      calls)
        # worklist: a builder like ``lambda: make(a, b)`` delegates to a
        # SIBLING def in the enclosing scope — its body is part of the
        # closure tree, so called local defs become roots themselves
        roots: List[ast.AST] = []
        seen_roots: Set[int] = set()
        while pending:
            root = pending.pop()
            if id(root) in seen_roots:
                continue
            seen_roots.add(id(root))
            roots.append(root)
            r, c, cl = free_reads(root)
            for p, n in r.items():
                reads.setdefault(p, n)
            called |= c
            calls.extend(cl)
            if isinstance(root, _FUNCS):    # pinned-default expressions
                a = root.args
                for d in list(a.defaults) + list(a.kw_defaults):
                    if d is not None:
                        _scan([d], set(), reads, called, calls)
            # any referenced local def is part of the program — a builder
            # that only PASSES ``local_step`` into shard_map still bakes
            # local_step's captures into the compiled program
            for p in set(c) | set(r):
                if "." not in p and p in local_defs:
                    pending.append(local_defs[p])

        captures = {p: Capture(p, n, src) for p, n in reads.items()}
        self._follow_calls(site, calls, stack, captures, depth=0,
                           seen=set())
        sanctioned = self._fixpoint(site.key_paths, assigns, captures,
                                    env, local_defs, stack_params, called)
        for cap in captures.values():
            cap.origin = self._classify(cap, site.key_paths, sanctioned,
                                        env, local_defs, stack_params,
                                        called)
        site.captures = sorted(captures.values(), key=lambda c: c.path)

        for root in roots:
            for traced in self._traced_roots(root, local_defs, env, 0):
                self._effect_scan(site, traced, src)
        return site

    # -- enclosing-scope maps -----------------------------------------------
    def _stack_assigns(self, stack: Sequence[ast.AST]
                       ) -> Dict[str, List[Optional[ast.AST]]]:
        out: Dict[str, List[Optional[ast.AST]]] = {}

        def put(name: str, rhs: Optional[ast.AST]) -> None:
            out.setdefault(name, []).append(rhs)

        for fn in stack:
            for node in _local_walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        # element-wise unpack: ``a, b = x.p, x.q`` binds
                        # a to x.p only, not to the whole RHS tuple
                        if isinstance(t, ast.Tuple) and \
                                isinstance(node.value, ast.Tuple) and \
                                len(t.elts) == len(node.value.elts) and \
                                all(isinstance(e, ast.Name)
                                    for e in t.elts):
                            for e, v in zip(t.elts, node.value.elts):
                                put(e.id, v)
                            continue
                        for n in _target_names(t):
                            put(n, node.value)
                elif isinstance(node, ast.AnnAssign):
                    for n in _target_names(node.target):
                        put(n, node.value)
                elif isinstance(node, ast.AugAssign):
                    for n in _target_names(node.target):
                        put(n, None)
                elif isinstance(node, ast.NamedExpr):
                    for n in _target_names(node.target):
                        put(n, node.value)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    for n in _target_names(node.target):
                        put(n, node.iter)
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if item.optional_vars is not None:
                            for n in _target_names(item.optional_vars):
                                put(n, item.context_expr)
                elif isinstance(node, ast.ExceptHandler):
                    if node.name:
                        put(node.name, None)
        return out

    def _stack_defs(self, stack: Sequence[ast.AST]) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for fn in stack:
            for node in _local_walk(fn):
                if isinstance(node, _FUNCS):
                    out[node.name] = node
        return out

    # -- cache-key path extraction ------------------------------------------
    def _key_paths(self, key_exprs: Sequence[ast.AST],
                   assigns: Dict[str, List[Optional[ast.AST]]]) -> Set[str]:
        """Dotted paths the key observes.  Bare names whose value IS the
        key tuple (``key = (...)`` aliases, ``base + (mode,)`` chains)
        expand through their assignments; tuple *components* match
        exactly and never expand — ``cond`` being keyed does not key
        whatever ``cond`` was computed from."""
        paths: Set[str] = set()
        expanding: Set[str] = set()

        def expand_name(name: str, depth: int) -> None:
            if depth > _MAX_DEPTH or name in expanding:
                return
            expanding.add(name)
            for rhs in assigns.get(name, []):
                if rhs is not None:
                    collect(rhs, depth + 1)

        def collect(expr: ast.AST, depth: int) -> None:
            if isinstance(expr, ast.Tuple):
                for el in expr.elts:
                    paths.update(expr_paths(el))
            elif isinstance(expr, ast.BinOp):
                collect(expr.left, depth)
                collect(expr.right, depth)
            elif isinstance(expr, ast.Name):
                paths.add(expr.id)
                expand_name(expr.id, depth)
            elif isinstance(expr, ast.Call) and \
                    call_name(expr).split(".")[-1] == "tuple" and expr.args:
                collect(expr.args[0], depth)
            else:
                paths.update(expr_paths(expr))

        for e in key_exprs:
            collect(e, 0)
        return paths

    # -- builder resolution --------------------------------------------------
    def _builder_roots(self, site: BuilderSite, builder: ast.AST,
                       local_defs: Dict[str, ast.AST],
                       stack_params: Set[str], env: ModuleEnv,
                       reads: Dict[str, ast.AST], called: Set[str],
                       calls: List[ast.Call]) -> List[ast.AST]:
        if isinstance(builder, ast.Lambda):
            return [builder]
        if isinstance(builder, ast.Name):
            if builder.id in local_defs:
                return [local_defs[builder.id]]
            if builder.id in stack_params:
                site.delegated = True       # forwarding wrapper
                return []
            if builder.id in env.defs:
                return [env.defs[builder.id]]
            return []                       # unresolved: contribute nothing
        if isinstance(builder, ast.Call):
            # eager factory: build(mode) — the returned closure pins the
            # argument values; count them as captures at the call site
            for sub in list(builder.args) + [kw.value
                                             for kw in builder.keywords]:
                _scan([sub], set(), reads, called, calls)
            leaf = call_name(builder).split(".")[-1]
            target = local_defs.get(leaf) or env.defs.get(leaf)
            return [target] if target is not None else []
        return []

    # -- interprocedural closure through the call graph ----------------------
    def _follow_calls(self, site: BuilderSite, calls: List[ast.Call],
                      stack: Sequence[ast.AST],
                      captures: Dict[str, Capture], depth: int,
                      seen: Set[str]) -> None:
        if depth >= _MAX_DEPTH or not calls:
            return
        caller = None
        for fn in stack[::-1]:
            caller = self.info_by_node.get(id(fn))
            if caller is not None:
                break
        if caller is None:
            return
        enclosing_q = caller.qualname
        for call in calls:
            targets = self.graph.resolve_call(caller, call)
            if len(targets) != 1:
                continue                    # ambiguous: contribute nothing
            key = targets[0]
            if key in seen:
                continue
            seen.add(key)
            info = self.graph.functions[key]
            if info.module == caller.module and \
                    info.qualname.startswith(enclosing_q + "."):
                continue    # nested sibling: already scanned as closure root
            parts = info.qualname.split(".")
            if len(parts) > 2 or (len(parts) == 2
                                  and parts[0] not in self.graph.classes):
                # a nested def elsewhere: its frees are bound by ITS
                # enclosing closure, not observables of this site — and
                # the unique-name fallback reaching it is over-resolution
                continue
            r, _, inner_calls = free_reads(info.node)
            tenv = self.envs.get(info.module)
            for p, n in r.items():
                base = p.split(".")[0]
                if base in ("self", "cls"):
                    continue                # callee's own instance state
                if self._is_const(p, tenv):
                    continue
                if p not in captures:
                    cap = Capture(p, n, info.src,
                                  via=f"via {info.qualname}()")
                    cap.origin = None       # cross-module, can't be keyed
                    captures[p] = cap
            self._follow_calls(site, inner_calls, [info.node], captures,
                               depth + 1, seen)

    # -- provenance ----------------------------------------------------------
    def _is_const(self, path: str, env: Optional[ModuleEnv]) -> bool:
        base = path.split(".")[0]
        if base in _BUILTIN_NAMES:
            return True
        if env is None:
            return False
        return (base in env.imports or base in env.defs
                or base in env.classes or base in env.consts)

    def _fixpoint(self, key_paths: Set[str],
                  assigns: Dict[str, List[Optional[ast.AST]]],
                  captures: Dict[str, Capture], env: ModuleEnv,
                  local_defs: Dict[str, ast.AST], stack_params: Set[str],
                  called: Set[str]) -> Set[str]:
        """Bare names provably derived from key/const paths: every
        reaching assignment's free paths are sanctioned."""
        sanctioned: Set[str] = set()

        def ok(path: str) -> bool:
            base = path.split(".")[0]
            if base in sanctioned or base in local_defs:
                return True
            if any(path == k or path.startswith(k + ".")
                   for k in key_paths):
                return True
            return self._is_const(path, env)

        changed = True
        while changed:
            changed = False
            for name, rhss in assigns.items():
                if name in sanctioned or not rhss:
                    continue
                if all(rhs is not None
                       and all(ok(p) for p in expr_paths(rhs))
                       for rhs in rhss):
                    sanctioned.add(name)
                    changed = True
        return sanctioned

    def _classify(self, cap: Capture, key_paths: Set[str],
                  sanctioned: Set[str], env: ModuleEnv,
                  local_defs: Dict[str, ast.AST], stack_params: Set[str],
                  called: Set[str]) -> Optional[str]:
        if cap.origin is not None or cap.via:
            return cap.origin               # cross-module: const or None
        p = cap.path
        base = p.split(".")[0]
        # a key path that EXTENDS the capture (capture ``shim``, key
        # ``shim.name``) also sanctions it: the author keyed the
        # identity-bearing attribute — err toward silence
        if any(p == k or p.startswith(k + ".") or k.startswith(p + ".")
               for k in key_paths):
            return "key"
        if base in local_defs:
            return "code"
        if base in stack_params:
            if base not in ("self", "cls") and \
                    (p in called or base in called):
                return "delegated"
            return None
        if base in sanctioned:
            return "derived"
        if self._is_const(p, env):
            return "const"
        return None

    # -- traced-body identification + effect scan ----------------------------
    def _traced_roots(self, root: ast.AST, local_defs: Dict[str, ast.AST],
                      env: ModuleEnv, depth: int) -> List[ast.AST]:
        """The callable(s) a builder returns — what ``jax.jit`` traces."""
        if depth > _MAX_DEPTH:
            return []
        out: List[ast.AST] = []
        nested = {n.name: n for n in _local_walk(root)
                  if isinstance(n, _FUNCS)}

        def from_expr(expr: Optional[ast.AST], depth: int) -> None:
            if expr is None or depth > _MAX_DEPTH:
                return
            if isinstance(expr, ast.Lambda):
                out.append(expr)
                return
            if isinstance(expr, ast.Name):
                target = nested.get(expr.id) or local_defs.get(expr.id)
                if target is not None:
                    out.append(target)
                return
            if isinstance(expr, ast.Call):
                leaf = call_name(expr).split(".")[-1]
                if leaf in ("jit", "shard_map", "pjit") and expr.args:
                    from_expr(expr.args[0], depth + 1)
                    return
                factory = (nested.get(leaf) or local_defs.get(leaf)
                           or env.defs.get(leaf))
                if factory is not None:
                    out.extend(self._traced_roots(factory, local_defs, env,
                                                  depth + 1))

        if isinstance(root, ast.Lambda):
            from_expr(root.body, depth)
        else:
            for node in _local_walk(root):
                if isinstance(node, ast.Return):
                    from_expr(node.value, depth)
        return out

    def _effect_scan(self, site: BuilderSite, traced: ast.AST,
                     src: SourceFile) -> None:
        """Side effects inside a traced body run once per *compile*, not
        per call: the trace replays their result, the effect vanishes."""
        for node in ast.walk(traced):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = dotted_name(item.context_expr)
                    if isinstance(item.context_expr, ast.Call):
                        name = call_name(item.context_expr)
                    leaf = name.split(".")[-1].lower()
                    if any(h in leaf for h in _LOCK_HINTS):
                        site.effects.append(Effect(
                            node, src, "lock",
                            f"lock acquisition 'with {name}'"))
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            parts = name.split(".")
            leaf = parts[-1]
            base = parts[0]
            if leaf in ("print", "open", "input") and len(parts) == 1:
                site.effects.append(Effect(node, src, "host-io",
                                           f"host call '{name}()'"))
            elif base in ("os", "time", "random", "shutil", "socket") and \
                    len(parts) > 1:
                site.effects.append(Effect(node, src, "host-io",
                                           f"host call '{name}()'"))
            elif base in ("log", "logger", "logging") and len(parts) > 1:
                site.effects.append(Effect(node, src, "host-io",
                                           f"logging call '{name}()'"))
            elif leaf == "absorb":
                site.effects.append(Effect(node, src, "absorb",
                                           f"'{name}()' absorbs into "
                                           "host-side state"))
            elif leaf == "acquire":
                site.effects.append(Effect(node, src, "lock",
                                           f"lock acquisition '{name}()'"))
            elif leaf == "count_output":
                site.effects.append(Effect(node, src, "metric",
                                           f"metric bump '{name}()'"))
            elif leaf in ("add", "set_max", "inc", "observe") and \
                    len(parts) > 1:
                recv = ".".join(parts[:-1]).lower()
                sub = node.func.value if isinstance(node.func,
                                                    ast.Attribute) else None
                subscripted = isinstance(sub, ast.Subscript) and \
                    "metric" in dotted_name(sub.value).lower()
                if "metric" in recv or subscripted:
                    site.effects.append(Effect(node, src, "metric",
                                               f"metric bump '{name}()'"))
            elif leaf in ("span", "instant") or "TRACER" in name:
                if "trace" in name.lower():
                    site.effects.append(Effect(node, src, "tracer",
                                               f"tracer call '{name}()'"))

    # -- R017 ----------------------------------------------------------------
    def mutable_hazards(self, site: BuilderSite
                        ) -> List[Tuple[Capture, str]]:
        """Captures whose object identity has in-place write sites: the
        trace snapshots the object at compile time; a repr-recomputed key
        may not reflect the mutation (ndarray reprs truncate), so the
        stale program survives the write."""
        out: List[Tuple[Capture, str]] = []
        for cap in site.captures:
            parts = cap.path.split(".")
            env = self.envs.get(cap.src.display_path)
            if len(parts) == 1 and cap.origin == "const" and env and \
                    cap.path in env.mut_globals:
                out.append((cap, "module global mutated in place in "
                                 f"'{cap.src.display_path}'"))
            elif len(parts) >= 2 and cap.origin == "key" and \
                    parts[-1] in self.mutated_attrs and \
                    parts[0] in ("self", "cls"):
                out.append((cap, f"attribute '{parts[-1]}' has in-place "
                                 "write sites elsewhere in the package"))
        return out


# ---------------------------------------------------------------------------
# cached entry point (rules R016–R018 share one build per file set)
# ---------------------------------------------------------------------------

_SITE_CACHE: Dict[int, Tuple[_SiteAnalyzer, List[BuilderSite]]] = {}


def capture_analysis(files: Sequence[SourceFile]
                     ) -> Tuple[_SiteAnalyzer, List[BuilderSite]]:
    key = hash(tuple(id(f) for f in files))
    got = _SITE_CACHE.get(key)
    if got is None:
        _SITE_CACHE.clear()                 # one live file set at a time
        analyzer = _SiteAnalyzer(files)
        got = (analyzer, analyzer.sites())
        _SITE_CACHE[key] = got
    return got
