"""R007: program-cache bypass in exec execute paths.

The serving layer's compile-once/serve-many contract (serving/
program_cache.py) only holds when every program an operator builds at
execute time routes through the cross-query cache — a direct ``jax.jit``
at call time compiles privately: invisible to hit/miss accounting, never
warmed from the on-disk index, and re-traced per exec instance. R001
already catches per-iteration construction; R007 catches the serving
regression: ANY jit construction reachable from an ``execute`` method in
the exec layer that neither goes through the sanctioned cache entry
points (``_cached_jit`` / ``_shard_jit`` / ``cached_program`` /
``get_or_build``) nor sits in the keyed-cache guard idiom.

Designed exceptions (a program that is genuinely per-query, e.g. keyed on
runtime-only state) carry an inline ``# tpu-lint: disable=R007`` or a
baseline entry with a written justification.
"""
from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, register)
from spark_rapids_tpu.analysis.rules_recompile import (_in_cache_guard,
                                                       is_jit_call)

#: sanctioned cache entry points: a jit construction that is an argument
#: (or lambda-argument body) of one of these is cached, not bypassing
_CACHE_ROUTES = ("_cached_jit", "_shard_jit", "cached_program",
                 "get_or_build")

#: directories whose execute paths are in scope (the exec layer; ops/ and
#: shuffle/ kernels are built through their own keyed caches and stay
#: covered by R001's loop/immediate-invoke forms)
_SCOPE_DIRS = ("execs",)


def _in_scope(src: SourceFile) -> bool:
    p = src.display_path.replace("\\", "/")
    return any(f"/{d}/" in p or p.startswith(f"{d}/") for d in _SCOPE_DIRS)


def _routed_through_cache(src: SourceFile, node: ast.Call) -> bool:
    """True when the jit construction flows into a sanctioned cache entry
    point: ``cache.get_or_build(key, lambda: jax.jit(...))`` or
    ``_cached_jit(key, builder)``-style wrappers."""
    for anc in src.ancestors(node):
        if isinstance(anc, ast.Call):
            name = call_name(anc)
            if name.rsplit(".", 1)[-1] in _CACHE_ROUTES:
                return True
    return False


def _builder_names(src: SourceFile, execute_def) -> set:
    """Names of local builder functions handed to a sanctioned cache route
    inside ``execute`` — the FusedStageExec.cached_program idiom:

        def make(variants, used, cap):
            ...
            return jax.jit(fn)
        fn = self.cached_program(key, lambda: make(variants, used, cap))

    The jit lives in ``make``, lexically on the execute path but invoked
    only through the cache's builder latch — one compile per fused
    plan-signature key. Collected names: bare-name builder arguments
    (``cached_program(key, build)``) and functions called UNDER A LAMBDA
    in a builder-argument expression (the wrapper form above). Only the
    BUILDER argument positions count (everything past the key, i.e.
    args[1:] plus non-``key`` keywords), and two shapes stay flagged:
    a name that execute ALSO calls directly outside a deferred builder,
    and a call evaluated eagerly in the argument expression itself
    (``cached_program(key, make(cap))`` runs ``make`` every batch before
    the cache is even consulted) — both are exactly the per-call compile
    the rule exists to catch."""
    routed, direct, deferred = set(), set(), set()
    for node in ast.walk(execute_def):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node) or ""
        if name.rsplit(".", 1)[-1] not in _CACHE_ROUTES:
            continue
        for arg in (list(node.args)[1:]
                    + [kw.value for kw in node.keywords if kw.arg != "key"]):
            if isinstance(arg, ast.Name):
                routed.add(arg.id)
                continue
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Lambda):
                    continue
                for s2 in ast.walk(sub.body):
                    deferred.add(id(s2))
                    if isinstance(s2, ast.Call) and \
                            isinstance(s2.func, ast.Name):
                        routed.add(s2.func.id)
    for node in ast.walk(execute_def):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and id(node) not in deferred:
            direct.add(node.func.id)
    return routed - direct


def _in_routed_builder(src: SourceFile, node: ast.Call, execute_def) -> bool:
    """True when the jit construction sits inside a function that execute
    passes to a sanctioned cache route (see ``_builder_names``)."""
    builders = _builder_names(src, execute_def)
    if not builders:
        return False
    for anc in src.ancestors(node):
        if anc is execute_def:
            break
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                anc.name in builders:
            return True
    return False


def _enclosing_execute(src: SourceFile, node: ast.AST):
    """The nearest enclosing ``execute`` FunctionDef (directly or through
    nested defs/lambdas), or None when the node is not on an execute
    path's lexical body."""
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                anc.name == "execute":
            return anc
    return None


@register
class ProgramCacheBypass(Rule):
    rule_id = "R007"
    title = "jit bypassing the cross-query program cache in execute paths"

    def check(self, src: SourceFile) -> List[Finding]:
        if not _in_scope(src):
            return []
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not is_jit_call(node):
                continue
            exec_def = _enclosing_execute(src, node)
            if exec_def is None:
                continue
            if _routed_through_cache(src, node):
                continue
            if _in_cache_guard(src, node):
                continue    # the keyed-cache idiom compiles once per key
            if _in_routed_builder(src, node, exec_def):
                continue    # named builder handed to a cache route
            name = call_name(node) or "jit"
            findings.append(src.finding(
                self.rule_id, node,
                f"{name}(...) constructed on an execute path without a "
                f"cache key: the program bypasses the cross-query serving "
                f"cache (no hit/miss accounting, no on-disk warm start, "
                f"re-traced per exec instance); route it through "
                f"_cached_jit / cached_program / get_or_build, or justify "
                f"it in the baseline"))
        return findings
