"""R002: hidden host↔device syncs in hot paths.

Theseus's thesis — accelerated query processing is won or lost on data
movement — shows up in this engine as dispatch-bound queries (0.029x–0.063x)
whose per-batch loops silently round-trip to the host. The checks, scoped to
the hot-path packages (execs/, ops/, shuffle/):

- ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` anywhere: each is an
  unconditional device→host sync; hot-path code must batch its downloads
  through one ``np.asarray`` per program result.
- ``jax.device_get(...)`` inside a loop: one blocking download per iteration.
- ``int()`` / ``float()`` / ``bool()`` on the result of a jit-compiled
  program inside a loop: forces a scalar download per iteration, stalling
  dispatch pipelining. Tracked per function scope: names bound from
  ``jax.jit`` / ``_cached_jit`` / ``_shard_jit`` / ``reorder_program``
  constructions are jit programs; names bound from calling one hold device
  values; ``np.asarray(x)`` re-binds to a host value and clears the taint.
- ``np.asarray(col) for col in jitted_fn(...)`` comprehensions inside a
  loop: downloads every output column of a program once per iteration —
  the full-column-download-per-batch shape that stalled the exchange path.

Designed sync points (the engine's one-scalar-row-count-per-batch contract)
carry inline ``# tpu-lint: disable=R002`` suppressions with a justification
comment; anything new must either batch its downloads or argue its case the
same way.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, register)
from spark_rapids_tpu.analysis.rules_recompile import is_jit_call

#: attribute calls that always synchronize with the device
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

#: factory callables whose result is a compiled program (callable)
_PROGRAM_FACTORIES = {"_cached_jit", "_shard_jit", "reorder_program"}

#: builtins that force a scalar host download when fed a device value
_SCALAR_CASTS = {"int", "float", "bool"}


def _assigned_names(node: ast.Assign) -> List[str]:
    names: List[str] = []
    for t in node.targets:
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            names.extend(e.id for e in t.elts if isinstance(e, ast.Name))
    return names


def _scope_nodes(fn_node: ast.AST):
    """The nodes of one function scope: like ast.walk but does NOT descend
    into nested def/lambda bodies — those are separate scopes whose
    assignments must not taint (or clear taint in) the enclosing one."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _ScopeTaint:
    """Per-function-scope name classification: which locals are jit programs,
    which hold device results of calling one, and which were re-materialized
    to host via np.asarray."""

    def __init__(self, fn_node: ast.AST):
        self.jit_fns: Set[str] = set()
        self.device_vals: Set[str] = set()
        assigns = sorted((n for n in _scope_nodes(fn_node)
                          if isinstance(n, ast.Assign)),
                         key=lambda n: n.lineno)
        for node in assigns:
            value = node.value
            names = _assigned_names(node)
            if not names or not isinstance(value, ast.Call):
                continue
            cname = call_name(value)
            if is_jit_call(value) or cname in _PROGRAM_FACTORIES:
                self.jit_fns.update(names)
            elif cname.split(".")[-1] == "asarray":
                self.device_vals.difference_update(names)
            elif isinstance(value.func, ast.Name) and \
                    value.func.id in self.jit_fns:
                self.device_vals.update(names)

    def is_device(self, node: ast.AST) -> bool:
        """name or name[...] over a tracked device result."""
        if isinstance(node, ast.Subscript):
            node = node.value
        return isinstance(node, ast.Name) and node.id in self.device_vals


@register
class HiddenHostSyncs(Rule):
    rule_id = "R002"
    title = "hidden host↔device syncs in hot paths"

    def check(self, src: SourceFile) -> List[Finding]:
        if not src.is_hot_path():
            return []
        findings: List[Finding] = []
        scopes: Dict[ast.AST, _ScopeTaint] = {}

        def scope_for(node: ast.AST) -> _ScopeTaint:
            fn = src.tree
            for anc in src.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = anc
                    break
            if fn not in scopes:
                scopes[fn] = _ScopeTaint(fn)
            return scopes[fn]

        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            # unconditional sync methods
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_METHODS and not node.args:
                findings.append(src.finding(
                    self.rule_id, node,
                    f".{node.func.attr}() forces a blocking device->host "
                    f"sync; download once via np.asarray on the batched "
                    f"program result instead"))
                continue
            cname = call_name(node)
            if cname == "jax.device_get" and src.inside_loop(node):
                findings.append(src.finding(
                    self.rule_id, node,
                    "jax.device_get inside a loop: one blocking download "
                    "per iteration; hoist the download out of the loop"))
                continue
            # scalar casts of jit-program results inside loops
            if cname in _SCALAR_CASTS and len(node.args) == 1 and \
                    src.inside_loop(node):
                taint = scope_for(node)
                if taint.is_device(node.args[0]):
                    findings.append(src.finding(
                        self.rule_id, node,
                        f"{cname}() on a jit-program result inside a loop "
                        f"syncs a scalar per iteration, stalling dispatch "
                        f"pipelining; batch the downloads or justify the "
                        f"sync point with a suppression"))
                continue
        findings.extend(self._download_comprehensions(src))
        return findings

    def _download_comprehensions(self, src: SourceFile) -> List[Finding]:
        """[np.asarray(a) for a in fn(...)] where fn is a jit program and the
        comprehension itself repeats per outer loop iteration."""
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.ListComp, ast.GeneratorExp)):
                continue
            if not src.inside_loop(node):
                continue
            gen = node.generators[0]
            if not isinstance(gen.iter, ast.Call):
                continue
            fn_expr = gen.iter.func
            if not isinstance(fn_expr, ast.Name):
                continue
            # the scope that owns the comprehension classifies fn
            taint = None
            for anc in src.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    taint = _ScopeTaint(anc)
                    break
            if taint is None or fn_expr.id not in taint.jit_fns:
                continue
            elt = node.elt
            if isinstance(elt, ast.Call) and \
                    call_name(elt).split(".")[-1] == "asarray":
                findings.append(src.finding(
                    self.rule_id, node,
                    f"downloads every output column of jit program "
                    f"'{fn_expr.id}' once per loop iteration; move the "
                    f"selection on device and download only what the host "
                    f"needs, or justify with a suppression"))
        return findings
