"""R006: locks held across blocking I/O.

The shuffle transport runs reader, progress, worker, and accept threads
against shared peer/tag/client tables. A mutex held across a blocking
socket call or a ``Future.result()`` serializes every peer behind the
slowest socket — and with the fetch timeout at 300 s, a wedged peer shows
up as a cluster-wide stall rather than an error.

The check: inside a ``with <lock>`` body (any context-manager expression
whose name contains "lock" — the repo's naming convention for
``threading.Lock``/``RLock``; Condition variables are named ``_available``
/ ``_room`` and correctly wait while releasing), flag calls to

- socket primitives: ``sendall`` / ``send`` on a socket-named receiver,
  ``recv`` / ``recv_into`` / ``accept`` / ``connect`` /
  ``create_connection``
- ``.result()`` (Future) and ``.join()`` (Thread) — unbounded waits

``.wait()`` is NOT flagged: on a Condition acquired by the same ``with``
it releases the lock while waiting (the correct pattern, used by the
bounce-buffer pool and the inflight throttle).

The one legitimate case — a per-socket writer lock serializing frame
writes (tcp.py ``_send_frame``) — carries an inline suppression with its
justification.
"""
from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            call_name, dotted_name, register)

#: attribute calls that block on the network / another thread
_BLOCKING_ATTRS = {"sendall", "recv", "recv_into", "accept", "connect",
                   "result"}
#: receiver-name fragments that make a bare .send/.recv socket-like
_SOCKET_HINTS = ("sock", "socket", "conn")


def _is_lock_expr(node: ast.AST) -> bool:
    name = dotted_name(node)
    if not name and isinstance(node, ast.Call):
        name = call_name(node)
    return "lock" in name.lower()


def _receiver_name(func: ast.Attribute) -> str:
    return dotted_name(func.value).lower()


@register
class LockAcrossBlockingIO(Rule):
    rule_id = "R006"
    title = "lock held across blocking I/O"

    def check(self, src: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_lock_expr(item.context_expr)
                       for item in node.items):
                continue
            for inner in ast.walk(node):
                if not isinstance(inner, ast.Call) or \
                        not isinstance(inner.func, ast.Attribute):
                    continue
                attr = inner.func.attr
                recv = _receiver_name(inner.func)
                blocking = (
                    attr in _BLOCKING_ATTRS
                    or (attr in ("send", "makefile")
                        and any(h in recv for h in _SOCKET_HINTS))
                    or (attr == "join"
                        and any(h in recv for h in ("thread", "proc")))
                    or call_name(inner) == "socket.create_connection")
                if not blocking:
                    continue
                findings.append(src.finding(
                    self.rule_id, inner,
                    f".{attr}() called while holding a lock: a slow or "
                    f"wedged peer stalls every thread contending for it; "
                    f"copy state under the lock, block outside it (or "
                    f"justify a per-socket writer lock with a "
                    f"suppression)"))
        return findings
