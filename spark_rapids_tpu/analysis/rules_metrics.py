"""R011: metric-registry drift — the metrics analog of R004 config drift.

Every dotted ``"section.name"`` counter is declared once in
utils/metrics.py (``NAME = "section.name"``) and listed in its section's
``*_METRIC_NAMES`` registry tuple; the per-action delta surfaces
(``session.last_metrics[section]`` / ``QueryHandle.exec_metrics``) iterate
THE TUPLE, not the bump sites. Two drift modes, both of which ship
silently:

- a counter is bumped somewhere in the package (``X_METRICS[NAME].add``)
  but its name is missing from the registry tuple — the bump happens and
  no snapshot/delta ever reports it: observability that looks wired but
  is invisible;
- a registry entry has NO bump site anywhere — the section reports a
  counter that is always zero, and dashboards trust a dead number.

Scope: dotted lowercase ``section.name`` metrics only (the process-global
MetricSet sections). CamelCase per-operator metric names
(``numOutputRows``) live on per-exec MetricSets with different reporting
paths, and per-query snake_case handle keys are dict literals — both out
of scope. A bump site is ``<...>_METRICS[<key>].add(...)`` or
``.set_max(...)`` where ``<key>`` is a declared constant (by name,
module-qualified or bare) or a dotted string literal.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.analysis.core import (Finding, Rule, SourceFile,
                                            register)

#: a metrics name in scope: lowercase dotted section.name
_DOTTED = re.compile(r"^[a-z][a-z0-9_]*\.[a-z0-9_.]+$")

_REGISTRY_SUFFIX = "_METRIC_NAMES"
_BUMP_METHODS = ("add", "set_max")


def _find_metrics_file(files: Sequence[SourceFile]) -> Optional[SourceFile]:
    for f in files:
        p = f.display_path.replace("\\", "/")
        if p.endswith("utils/metrics.py") or p == "metrics.py":
            return f
    return None


def metric_constants(metrics_src: SourceFile) -> Dict[str, str]:
    """constant name -> dotted metric value from top-level
    ``NAME = "section.name"`` assignments in utils/metrics.py."""
    out: Dict[str, str] = {}
    for node in metrics_src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        val = node.value.value
        if not _DOTTED.match(val):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                out[target.id] = val
    return out


def registry_entries(metrics_src: SourceFile, consts: Dict[str, str]
                     ) -> Dict[str, Tuple[str, int]]:
    """dotted metric name -> (registry tuple name, lineno) from the
    ``X_METRIC_NAMES = (A, B, ...)`` tuples (dotted members only)."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in metrics_src.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        target = node.targets[0]
        if not (isinstance(target, ast.Name)
                and target.id.endswith(_REGISTRY_SUFFIX)):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            continue
        for elt in node.value.elts:
            name = None
            if isinstance(elt, ast.Name):
                name = consts.get(elt.id)
            elif isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                name = elt.value
            if name is not None and _DOTTED.match(name):
                out[name] = (target.id, elt.lineno)
    return out


def _metric_set_aliases(src: SourceFile) -> Set[str]:
    """Local names bound to a metric set (``m = um.TRANSFER_METRICS``) —
    file-scoped, so the subscript recognizer sees through the common
    hot-loop alias idiom."""
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        vname = v.attr if isinstance(v, ast.Attribute) else (
            v.id if isinstance(v, ast.Name) else "")
        if not vname.endswith("_METRICS"):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _resolve_keys(key: ast.AST, consts: Dict[str, str]
                  ) -> List[Tuple[Optional[str], bool]]:
    """Dotted names a subscript key may evaluate to (an IfExp resolves
    both branches). ``(None, False)`` marks an unresolvable computed key —
    skipped, under-approximate like the call-graph rules."""
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        return [(key.value, True)]
    if isinstance(key, ast.Name):
        val = consts.get(key.id)
        return [(val, val is not None)]
    if isinstance(key, ast.Attribute):
        val = consts.get(key.attr)
        return [(val, val is not None)]
    if isinstance(key, ast.IfExp):
        return (_resolve_keys(key.body, consts)
                + _resolve_keys(key.orelse, consts))
    return [(None, False)]


def _bump_keys(node: ast.Call, consts: Dict[str, str], aliases: Set[str]
               ) -> Optional[List[Tuple[Optional[str], bool]]]:
    """The dotted metric names this call bumps, or None when it is not a
    ``<...>_METRICS[key].add/set_max(...)`` bump at all."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr in _BUMP_METHODS):
        return None
    sub = func.value
    if not isinstance(sub, ast.Subscript):
        return None
    base = sub.value
    base_name = base.attr if isinstance(base, ast.Attribute) else (
        base.id if isinstance(base, ast.Name) else "")
    if not (base_name.endswith("_METRICS") or base_name in aliases):
        return None
    return _resolve_keys(sub.slice, consts)


@register
class MetricRegistryDrift(Rule):
    rule_id = "R011"
    title = "metric-registry drift (unregistered bumps or dead entries)"
    is_project_rule = True

    def check_project(self, files: Sequence[SourceFile]) -> List[Finding]:
        metrics_src = _find_metrics_file(files)
        if metrics_src is None:
            return []   # analyzing a subtree without the registry module
        consts = metric_constants(metrics_src)
        registered = registry_entries(metrics_src, consts)
        findings: List[Finding] = []
        bumped: Set[str] = set()
        for src in files:
            aliases = _metric_set_aliases(src)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                resolved = _bump_keys(node, consts, aliases)
                if resolved is None:
                    continue
                for name, ok in resolved:
                    if not ok or not name or not _DOTTED.match(name):
                        continue
                    bumped.add(name)
                    if name not in registered:
                        findings.append(src.finding(
                            self.rule_id, node,
                            f"counter '{name}' is bumped here but missing "
                            f"from its *_METRIC_NAMES registry tuple in "
                            f"utils/metrics.py — the per-action delta "
                            f"iterates the tuple, so this bump is never "
                            f"reported"))
        for name, (tuple_name, lineno) in sorted(registered.items()):
            if name not in bumped:
                findings.append(Finding(
                    self.rule_id, metrics_src.display_path, lineno,
                    f"registry entry '{name}' in {tuple_name} has no "
                    f"bump site (.add/.set_max) anywhere in the package — "
                    f"the section reports a counter that is always zero",
                    metrics_src.line_text(lineno)))
        return findings
