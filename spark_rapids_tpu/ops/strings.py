"""Kernels over the fixed-width device string layout.

Device strings are ``uint8[n, W]`` byte matrices (zero padded) + ``int32[n]``
lengths. All kernels are xp-generic (numpy eager / jax traced) and fully
vectorized — on TPU they map onto VPU lane ops with no scalar loops.

Ordering note: Spark compares strings as unsigned UTF-8 bytes
(UTF8String.compareTo), so byte-lexicographic comparison here is EXACTLY Spark's
ordering — no "incompatible UTF-8 ordering" caveat like the reference's cuDF path.
"""
from __future__ import annotations

import numpy as np


def _bcast_rows(xp, data, lengths, like_data):
    """Broadcast a scalar string (1-D [W]) against a column [n, W]."""
    if data.ndim == 1 and like_data.ndim == 2:
        n = like_data.shape[0]
        data = xp.broadcast_to(data[None, :], (n, data.shape[0]))
        lengths = xp.broadcast_to(xp.reshape(lengths, (1,)), (n,))
    return data, lengths


def pad_width(xp, data, W: int):
    """Zero-pad a byte matrix (or scalar byte vector) to width W."""
    cur = data.shape[-1]
    if cur >= W:
        return data
    pad_shape = data.shape[:-1] + (W - cur,)
    return xp.concatenate([data, xp.zeros(pad_shape, dtype=np.uint8)], axis=-1)


def align_widths(xp, ld, rd):
    """Pad the narrower of two string payloads so binary kernels can mix
    per-column adaptive widths (padding bytes are zero by invariant)."""
    W = max(ld.shape[-1], rd.shape[-1])
    return pad_width(xp, ld, W), pad_width(xp, rd, W)


def string_eq(xp, ld, ll, rd, rl):
    """Equality: lengths equal and all payload bytes equal (padding is zeroed)."""
    ld, rd = align_widths(xp, ld, rd)
    ld, ll = _bcast_rows(xp, ld, ll, rd)
    rd, rl = _bcast_rows(xp, rd, rl, ld)
    axis = -1
    return xp.logical_and(ll == rl, xp.all(ld == rd, axis=axis))


def string_lt(xp, ld, ll, rd, rl):
    """Byte-lexicographic less-than, ties broken by length."""
    ld, rd = align_widths(xp, ld, rd)
    ld, ll = _bcast_rows(xp, ld, ll, rd)
    rd, rl = _bcast_rows(xp, rd, rl, ld)
    diff = ld != rd
    any_diff = xp.any(diff, axis=-1)
    first = xp.argmax(diff, axis=-1)
    lb = xp.take_along_axis(ld, first[..., None], axis=-1)[..., 0]
    rb = xp.take_along_axis(rd, first[..., None], axis=-1)[..., 0]
    return xp.where(any_diff, lb < rb, ll < rl)


def string_compare(xp, op: str, ld, ll, rd, rl):
    if op == "eq":
        return string_eq(xp, ld, ll, rd, rl)
    if op == "ne":
        return xp.logical_not(string_eq(xp, ld, ll, rd, rl))
    if op == "lt":
        return string_lt(xp, ld, ll, rd, rl)
    if op == "gt":
        return string_lt(xp, rd, rl, ld, ll)
    if op == "le":
        return xp.logical_not(string_lt(xp, rd, rl, ld, ll))
    if op == "ge":
        return xp.logical_not(string_lt(xp, ld, ll, rd, rl))
    raise ValueError(op)


def char_lengths(xp, data, lengths):
    """UTF-8 character count: bytes that are not continuation bytes (10xxxxxx)."""
    W = data.shape[-1]
    in_range = np.arange(W, dtype=np.int32) < lengths[..., None]
    non_cont = (data & 0xC0) != 0x80
    return xp.sum(xp.logical_and(in_range, non_cont), axis=-1).astype(np.int32)


def upper_ascii(xp, data):
    is_lower = xp.logical_and(data >= 97, data <= 122)
    return xp.where(is_lower, data - 32, data)


def lower_ascii(xp, data):
    is_upper = xp.logical_and(data >= 65, data <= 90)
    return xp.where(is_upper, data + 32, data)


def starts_with(xp, data, lengths, prefix: bytes, W: int):
    """Row starts with the constant prefix."""
    if len(prefix) > W:
        # a needle longer than the column's width bucket can't match any row
        n = data.shape[0] if data.ndim == 2 else 1
        return xp.zeros((n,) if data.ndim == 2 else (), dtype=bool)
    p = np.zeros(W, dtype=np.uint8)
    p[:len(prefix)] = bytearray(prefix)
    relevant = np.arange(W, dtype=np.int32) < len(prefix)
    match = xp.all(xp.logical_or(~relevant, data == xp.asarray(p)), axis=-1)
    return xp.logical_and(match, lengths >= len(prefix))


def ends_with(xp, data, lengths, suffix: bytes, W: int):
    k = len(suffix)
    if k == 0:
        return xp.ones(data.shape[0], dtype=bool)
    # gather the last k bytes of each row: positions len-k .. len-1
    idx = lengths[:, None] - k + np.arange(k, dtype=np.int32)[None, :]
    idx_safe = xp.clip(idx, 0, W - 1)
    tail = xp.take_along_axis(data, idx_safe, axis=-1)
    suf = xp.asarray(np.frombuffer(suffix, dtype=np.uint8))
    return xp.logical_and(lengths >= k, xp.all(tail == suf, axis=-1))


def contains(xp, data, lengths, needle: bytes, W: int):
    """Constant-needle substring search via shifted window compare.

    Builds a [n, W, k] comparison — fine for the fixed W used on device and fully
    vector-parallel; replaces cuDF's stringContains kernel.
    """
    k = len(needle)
    if k == 0:
        return xp.ones(data.shape[0], dtype=bool)
    if k > W:
        return xp.zeros(data.shape[0], dtype=bool)
    starts = np.arange(W - k + 1, dtype=np.int32)           # [S]
    offs = np.arange(k, dtype=np.int32)                      # [k]
    gather = xp.asarray(starts[:, None] + offs[None, :])     # [S, k]
    windows = data[:, gather]                                # [n, S, k]
    ndl = xp.asarray(np.frombuffer(needle, dtype=np.uint8))
    hit = xp.all(windows == ndl, axis=-1)                    # [n, S]
    valid_start = xp.asarray(starts)[None, :] <= (lengths[:, None] - k)
    return xp.any(xp.logical_and(hit, valid_start), axis=-1)


def substring(xp, data, lengths, start0, slice_len, W: int):
    """Byte-substring (callers handle UTF-8 char positions by precomputing byte
    offsets when needed). start0: 0-based start per row; slice_len: bytes to keep."""
    idx = start0[:, None] + np.arange(W, dtype=np.int32)[None, :]
    idx_safe = xp.clip(idx, 0, W - 1)
    moved = xp.take_along_axis(data, idx_safe, axis=-1)
    new_len = xp.clip(xp.minimum(slice_len, lengths - start0), 0, W).astype(np.int32)
    keep = np.arange(W, dtype=np.int32)[None, :] < new_len[:, None]
    return xp.where(keep, moved, 0).astype(np.uint8), new_len


def int_to_string(xp, v, W: int):
    """Integral column -> decimal string bytes, fully vectorized.

    Digits come from uint64 division by constant powers of ten (Long.MIN_VALUE is
    handled by two's-complement negation in uint64). Replaces cuDF's
    itos kernel; on TPU this is 20 lanes of VPU math per value, no scalar loop.
    """
    v64 = v.astype(np.int64)
    neg = v64 < 0
    a = xp.where(neg, (0 - v64.astype(np.uint64)), v64.astype(np.uint64))
    powers = xp.asarray(np.array([10 ** (19 - i) for i in range(20)], dtype=np.uint64))
    digits = ((a[:, None] // powers) % 10).astype(np.uint8)       # [n, 20]
    nonzero = digits != 0
    any_nz = xp.any(nonzero, axis=-1)
    first_nz = xp.argmax(nonzero, axis=-1)
    ndigits = xp.where(any_nz, 20 - first_nz, 1).astype(np.int32)
    nlen = (ndigits + neg.astype(np.int32)).astype(np.int32)
    # output position j holds: '-' at j=0 if neg; digit (20 - ndigits + j - neg) else
    j = np.arange(W, dtype=np.int32)[None, :]
    src = 20 - ndigits[:, None] + j - neg.astype(np.int32)[:, None]
    src_safe = xp.clip(src, 0, 19)
    out = xp.take_along_axis(digits, src_safe, axis=-1) + np.uint8(48)
    minus = xp.logical_and(neg[:, None], j == 0)
    out = xp.where(minus, np.uint8(45), out)
    keep = j < nlen[:, None]
    return xp.where(keep, out, 0).astype(np.uint8), nlen


def bool_to_string(xp, v, W: int):
    """boolean -> 'true'/'false' byte rows."""
    true_row = np.zeros(W, dtype=np.uint8)
    true_row[:4] = bytearray(b"true")
    false_row = np.zeros(W, dtype=np.uint8)
    false_row[:5] = bytearray(b"false")
    data = xp.where(v[:, None], xp.asarray(true_row), xp.asarray(false_row))
    lengths = xp.where(v, 4, 5).astype(np.int32)
    return data, lengths


def concat2(xp, ld, ll, rd, rl, W: int):
    """Concatenate two string columns row-wise, truncating at W bytes."""
    ld, ll = _bcast_rows(xp, ld, ll, rd)
    rd, rl = _bcast_rows(xp, rd, rl, ld)
    ld, rd = pad_width(xp, ld, W), pad_width(xp, rd, W)
    pos = np.arange(W, dtype=np.int32)[None, :]
    from_right = pos >= ll[:, None]
    ridx = xp.clip(pos - ll[:, None], 0, W - 1)
    right_bytes = xp.take_along_axis(rd, ridx, axis=-1)
    out = xp.where(from_right, right_bytes, ld)
    new_len = xp.minimum(ll + rl, W).astype(np.int32)
    keep = pos < new_len[:, None]
    return xp.where(keep, out, 0).astype(np.uint8), new_len
