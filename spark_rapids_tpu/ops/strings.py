"""Kernels over the fixed-width device string layout.

Device strings are ``uint8[n, W]`` byte matrices (zero padded) + ``int32[n]``
lengths. All kernels are xp-generic (numpy eager / jax traced) and fully
vectorized — on TPU they map onto VPU lane ops with no scalar loops.

Ordering note: Spark compares strings as unsigned UTF-8 bytes
(UTF8String.compareTo), so byte-lexicographic comparison here is EXACTLY Spark's
ordering — no "incompatible UTF-8 ordering" caveat like the reference's cuDF path.
"""
from __future__ import annotations

import numpy as np


def _bcast_rows(xp, data, lengths, like_data):
    """Broadcast a scalar string (1-D [W]) against a column [n, W]."""
    if data.ndim == 1 and like_data.ndim == 2:
        n = like_data.shape[0]
        data = xp.broadcast_to(data[None, :], (n, data.shape[0]))
        lengths = xp.broadcast_to(xp.reshape(lengths, (1,)), (n,))
    return data, lengths


def pad_width(xp, data, W: int):
    """Zero-pad a byte matrix (or scalar byte vector) to width W."""
    cur = data.shape[-1]
    if cur >= W:
        return data
    pad_shape = data.shape[:-1] + (W - cur,)
    return xp.concatenate([data, xp.zeros(pad_shape, dtype=np.uint8)], axis=-1)


def align_widths(xp, ld, rd):
    """Pad the narrower of two string payloads so binary kernels can mix
    per-column adaptive widths (padding bytes are zero by invariant)."""
    W = max(ld.shape[-1], rd.shape[-1])
    return pad_width(xp, ld, W), pad_width(xp, rd, W)


def string_eq(xp, ld, ll, rd, rl):
    """Equality: lengths equal and all payload bytes equal (padding is zeroed)."""
    ld, rd = align_widths(xp, ld, rd)
    ld, ll = _bcast_rows(xp, ld, ll, rd)
    rd, rl = _bcast_rows(xp, rd, rl, ld)
    axis = -1
    return xp.logical_and(ll == rl, xp.all(ld == rd, axis=axis))


def string_lt(xp, ld, ll, rd, rl):
    """Byte-lexicographic less-than, ties broken by length."""
    ld, rd = align_widths(xp, ld, rd)
    ld, ll = _bcast_rows(xp, ld, ll, rd)
    rd, rl = _bcast_rows(xp, rd, rl, ld)
    diff = ld != rd
    any_diff = xp.any(diff, axis=-1)
    first = xp.argmax(diff, axis=-1)
    lb = xp.take_along_axis(ld, first[..., None], axis=-1)[..., 0]
    rb = xp.take_along_axis(rd, first[..., None], axis=-1)[..., 0]
    return xp.where(any_diff, lb < rb, ll < rl)


def string_compare(xp, op: str, ld, ll, rd, rl):
    if op == "eq":
        return string_eq(xp, ld, ll, rd, rl)
    if op == "ne":
        return xp.logical_not(string_eq(xp, ld, ll, rd, rl))
    if op == "lt":
        return string_lt(xp, ld, ll, rd, rl)
    if op == "gt":
        return string_lt(xp, rd, rl, ld, ll)
    if op == "le":
        return xp.logical_not(string_lt(xp, rd, rl, ld, ll))
    if op == "ge":
        return xp.logical_not(string_lt(xp, ld, ll, rd, rl))
    raise ValueError(op)


def char_lengths(xp, data, lengths):
    """UTF-8 character count: bytes that are not continuation bytes (10xxxxxx)."""
    W = data.shape[-1]
    in_range = np.arange(W, dtype=np.int32) < lengths[..., None]
    non_cont = (data & 0xC0) != 0x80
    return xp.sum(xp.logical_and(in_range, non_cont), axis=-1).astype(np.int32)


def upper_ascii(xp, data):
    is_lower = xp.logical_and(data >= 97, data <= 122)
    return xp.where(is_lower, data - 32, data)


def lower_ascii(xp, data):
    is_upper = xp.logical_and(data >= 65, data <= 90)
    return xp.where(is_upper, data + 32, data)


def starts_with(xp, data, lengths, prefix: bytes, W: int):
    """Row starts with the constant prefix."""
    if len(prefix) > W:
        # a needle longer than the column's width bucket can't match any row
        n = data.shape[0] if data.ndim == 2 else 1
        return xp.zeros((n,) if data.ndim == 2 else (), dtype=bool)
    p = np.zeros(W, dtype=np.uint8)
    p[:len(prefix)] = bytearray(prefix)
    relevant = np.arange(W, dtype=np.int32) < len(prefix)
    match = xp.all(xp.logical_or(~relevant, data == xp.asarray(p)), axis=-1)
    return xp.logical_and(match, lengths >= len(prefix))


def ends_with(xp, data, lengths, suffix: bytes, W: int):
    k = len(suffix)
    if k == 0:
        return xp.ones(data.shape[0], dtype=bool)
    # gather the last k bytes of each row: positions len-k .. len-1
    idx = lengths[:, None] - k + np.arange(k, dtype=np.int32)[None, :]
    idx_safe = xp.clip(idx, 0, W - 1)
    tail = xp.take_along_axis(data, idx_safe, axis=-1)
    suf = xp.asarray(np.frombuffer(suffix, dtype=np.uint8))
    return xp.logical_and(lengths >= k, xp.all(tail == suf, axis=-1))


def _needle_hits(xp, data, lengths, needle: bytes, W: int):
    """Shifted-window compare for a constant needle: ok[n, S] is True where a
    whole in-bounds occurrence starts (S = W - k + 1). The one [n, S, k]
    comparison is the shared core of contains/locate/greedy_matches."""
    k = len(needle)
    starts = np.arange(W - k + 1, dtype=np.int32)            # [S]
    offs = np.arange(k, dtype=np.int32)                      # [k]
    gather = xp.asarray(starts[:, None] + offs[None, :])     # [S, k]
    windows = data[:, gather]                                # [n, S, k]
    ndl = xp.asarray(np.frombuffer(needle, dtype=np.uint8))
    hit = xp.all(windows == ndl, axis=-1)                    # [n, S]
    valid_start = xp.asarray(starts)[None, :] <= (lengths[:, None] - k)
    return xp.logical_and(hit, valid_start)


def contains(xp, data, lengths, needle: bytes, W: int):
    """Constant-needle substring search (cuDF stringContains analog)."""
    k = len(needle)
    if k == 0:
        return xp.ones(data.shape[0], dtype=bool)
    if k > W:
        return xp.zeros(data.shape[0], dtype=bool)
    return xp.any(_needle_hits(xp, data, lengths, needle, W), axis=-1)


def substring(xp, data, lengths, start0, slice_len, W: int):
    """Byte-substring (callers handle UTF-8 char positions by precomputing byte
    offsets when needed). start0: 0-based start per row; slice_len: bytes to keep."""
    idx = start0[:, None] + np.arange(W, dtype=np.int32)[None, :]
    idx_safe = xp.clip(idx, 0, W - 1)
    moved = xp.take_along_axis(data, idx_safe, axis=-1)
    new_len = xp.clip(xp.minimum(slice_len, lengths - start0), 0, W).astype(np.int32)
    keep = np.arange(W, dtype=np.int32)[None, :] < new_len[:, None]
    return xp.where(keep, moved, 0).astype(np.uint8), new_len


def int_to_string(xp, v, W: int):
    """Integral column -> decimal string bytes, fully vectorized.

    Digits come from uint64 division by constant powers of ten (Long.MIN_VALUE is
    handled by two's-complement negation in uint64). Replaces cuDF's
    itos kernel; on TPU this is 20 lanes of VPU math per value, no scalar loop.
    """
    v64 = v.astype(np.int64)
    neg = v64 < 0
    a = xp.where(neg, (0 - v64.astype(np.uint64)), v64.astype(np.uint64))
    powers = xp.asarray(np.array([10 ** (19 - i) for i in range(20)], dtype=np.uint64))
    digits = ((a[:, None] // powers) % 10).astype(np.uint8)       # [n, 20]
    nonzero = digits != 0
    any_nz = xp.any(nonzero, axis=-1)
    first_nz = xp.argmax(nonzero, axis=-1)
    ndigits = xp.where(any_nz, 20 - first_nz, 1).astype(np.int32)
    nlen = (ndigits + neg.astype(np.int32)).astype(np.int32)
    # output position j holds: '-' at j=0 if neg; digit (20 - ndigits + j - neg) else
    j = np.arange(W, dtype=np.int32)[None, :]
    src = 20 - ndigits[:, None] + j - neg.astype(np.int32)[:, None]
    src_safe = xp.clip(src, 0, 19)
    out = xp.take_along_axis(digits, src_safe, axis=-1) + np.uint8(48)
    minus = xp.logical_and(neg[:, None], j == 0)
    out = xp.where(minus, np.uint8(45), out)
    keep = j < nlen[:, None]
    return xp.where(keep, out, 0).astype(np.uint8), nlen


def bool_to_string(xp, v, W: int):
    """boolean -> 'true'/'false' byte rows."""
    true_row = np.zeros(W, dtype=np.uint8)
    true_row[:4] = bytearray(b"true")
    false_row = np.zeros(W, dtype=np.uint8)
    false_row[:5] = bytearray(b"false")
    data = xp.where(v[:, None], xp.asarray(true_row), xp.asarray(false_row))
    lengths = xp.where(v, 4, 5).astype(np.int32)
    return data, lengths


def char_starts(xp, data, lengths, W: int):
    """Bool [n, W]: position begins a UTF-8 character (non-continuation byte
    within the row's length)."""
    in_range = np.arange(W, dtype=np.int32)[None, :] < lengths[:, None]
    return xp.logical_and((data & 0xC0) != 0x80, in_range)


def char_to_byte_offset(xp, data, lengths, char_count, W: int):
    """Byte offset of the given 0-based per-row character index (number of
    bytes spanned by the first char_count characters)."""
    char_idx = xp.cumsum(char_starts(xp, data, lengths, W).astype(np.int32),
                         axis=-1)                            # 1-based char no.
    in_range = np.arange(W, dtype=np.int32)[None, :] < lengths[:, None]
    return xp.sum(xp.logical_and(in_range, char_idx <= char_count[:, None]),
                  axis=-1).astype(np.int32)


def locate(xp, data, lengths, needle: bytes, start1, W: int):
    """1-based *character* position of the first occurrence of the constant
    needle at or after 1-based character position start1; 0 when absent
    (Spark StringLocate is char-based; cuDF's stringLocate analog)."""
    n = data.shape[0]
    k = len(needle)
    if k == 0 or k > W:
        return xp.zeros(n, dtype=np.int32)
    start1 = xp.broadcast_to(xp.asarray(np.int32(start1)), (n,)) \
        if np.ndim(start1) == 0 else start1
    byte_start = char_to_byte_offset(xp, data, lengths, start1 - 1, W)
    ok = _needle_hits(xp, data, lengths, needle, W)
    S = ok.shape[1]
    ok = xp.logical_and(
        ok, np.arange(S, dtype=np.int32)[None, :] >= byte_start[:, None])
    any_ok = xp.any(ok, axis=-1)
    first = xp.argmax(ok, axis=-1).astype(np.int32)
    # byte offset -> 1-based char position: chars beginning strictly before it
    starts = char_starts(xp, data, lengths, W)
    nchars_before = xp.sum(xp.logical_and(
        starts, np.arange(W, dtype=np.int32)[None, :] < first[:, None]),
        axis=-1).astype(np.int32)
    return xp.where(any_ok, nchars_before + 1, 0).astype(np.int32)


def trim_bounds(xp, data, lengths, W: int, left: bool, right: bool,
                chars: bytes = b" "):
    """(start, new_len) after stripping any of the given chars from the chosen
    ends (Spark trim family; default strips ASCII space only)."""
    pos = np.arange(W, dtype=np.int32)[None, :]
    in_range = pos < lengths[:, None]
    member = xp.zeros(data.shape, dtype=bool)
    for ch in bytearray(chars):
        member = xp.logical_or(member, data == np.uint8(ch))
    keepable = xp.logical_and(xp.logical_not(member), in_range)
    any_keep = xp.any(keepable, axis=-1)
    first = xp.argmax(keepable, axis=-1).astype(np.int32)
    last = (W - 1 - xp.argmax(keepable[:, ::-1], axis=-1)).astype(np.int32)
    start = xp.where(xp.logical_and(any_keep, left), first, 0)
    end = xp.where(any_keep, xp.where(right, last + 1, lengths), 0)
    new_len = xp.maximum(end - start, 0)
    return start, new_len


def initcap(xp, data, lengths):
    """Spark initcap: lowercase everything, then uppercase the first character
    and any character following a space (UTF8String.toLowerCase().toTitleCase():
    the word delimiter is the single space char)."""
    low = lower_ascii(xp, data)
    after_space = xp.concatenate(
        [xp.ones(data.shape[:-1] + (1,), dtype=bool), data[..., :-1] == 32],
        axis=-1)
    return xp.where(after_space, upper_ascii(xp, low), low)


def pad_fill_total_bytes(pad_bytes: bytes, target: int) -> int:
    """Byte length of `target` characters of the cyclic pad (worst-case fill);
    O(len(pad)) arithmetic, not O(target)."""
    chars = _utf8_chars(pad_bytes)
    if not chars or target <= 0:
        return 0
    q, r = divmod(target, len(chars))
    return q * sum(len(c) for c in chars) + sum(len(c) for c in chars[:r])


def _utf8_chars(b: bytes):
    """Split bytes on UTF-8 character boundaries (non-continuation bytes)."""
    starts = [i for i, c in enumerate(b) if (c & 0xC0) != 0x80]
    return [b[s:e] for s, e in zip(starts, starts[1:] + [len(b)])]


def pad(xp, data, lengths, target: int, pad_bytes: bytes, side: str, W: int):
    """lpad/rpad to a constant target length in CHARACTERS with a cyclic
    constant pad; strings longer than target chars are truncated on a char
    boundary (Spark semantics — stringFunctions BasePad is char-based). An
    empty pad can only truncate."""
    n = data.shape[0]
    data = pad_width(xp, data, W)
    j = np.arange(W, dtype=np.int32)[None, :]
    charcnt = char_lengths(xp, data, lengths)
    keep_chars = xp.minimum(charcnt, np.int32(target))
    # byte length of the surviving prefix — always a char boundary
    keep_bytes = char_to_byte_offset(xp, data, lengths, keep_chars, W)
    pchars = _utf8_chars(pad_bytes)
    if not pchars:
        new_len = keep_bytes
        keep = j < new_len[:, None]
        return xp.where(keep, data, 0).astype(np.uint8), new_len
    # Cyclic fill of up to T pad characters, precomputed host-side (pad is a
    # literal); fill_len[m] = bytes of the first m fill chars. T clamps the
    # host work to the output width: every fill char is >= 1 byte and the
    # output is truncated at W bytes, so chars past W are provably discarded.
    T = min(target, W)
    fill = b"".join(pchars[i % len(pchars)] for i in range(T))
    fill_len = np.zeros(T + 1, dtype=np.int32)
    acc = 0
    for m in range(T):
        acc += len(pchars[m % len(pchars)])
        fill_len[m + 1] = acc
    farr = xp.asarray(np.frombuffer(fill, dtype=np.uint8)) if fill \
        else xp.zeros(1, dtype=np.uint8)
    pad_chars = xp.clip(np.int64(target) - charcnt, 0, T).astype(np.int32)
    fill_bytes = xp.asarray(fill_len)[pad_chars]
    new_len = xp.minimum(keep_bytes + fill_bytes, W).astype(np.int32)
    fcap = max(len(fill), 1)
    if side == "right":
        from_fill = j >= keep_bytes[:, None]
        fidx = xp.clip(j - keep_bytes[:, None], 0, fcap - 1)
        out = xp.where(from_fill, farr[fidx], data)
    else:
        from_fill = j < fill_bytes[:, None]
        fidx = xp.clip(j, 0, fcap - 1)
        src = xp.clip(j - fill_bytes[:, None], 0, W - 1)
        moved = xp.take_along_axis(data, src, axis=-1)
        out = xp.where(from_fill, farr[fidx], moved)
    # The W-clamp above cuts at a raw byte offset; round it down to a char
    # boundary so a split multibyte pad (or input) char can never emit
    # invalid UTF-8. Last char start within the kept bytes + its lead-byte
    # length decide whether the final char survives whole.
    start_keep = xp.logical_and((out & 0xC0) != 0x80, j < new_len[:, None])
    s = (W - 1 - xp.argmax(start_keep[:, ::-1], axis=-1)).astype(np.int32)
    lead = xp.take_along_axis(out, s[:, None], axis=-1)[:, 0]
    clen = xp.where(lead < 0xC0, 1,
                    xp.where(lead < 0xE0, 2,
                             xp.where(lead < 0xF0, 3, 4))).astype(np.int32)
    new_len = xp.where(new_len > 0,
                       xp.where(s + clen <= new_len, new_len, s),
                       new_len).astype(np.int32)
    keep = j < new_len[:, None]
    return xp.where(keep, out, 0).astype(np.uint8), new_len


def greedy_matches(xp, data, lengths, needle: bytes, W: int):
    """Non-overlapping left-to-right constant-needle match starts (the scan
    order Spark's indexOf-based replace/substring_index use). Returns
    (sel [n, W] bool, plain [n, W] int32) where plain is 1 for a byte emitted
    as-is, 0 at and inside a selected match span.

    The greedy selection is inherently sequential in W; it runs as a
    compiled lax.scan on device (constant program size) and a plain loop on
    the numpy path."""
    n = data.shape[0]
    k = len(needle)
    pos_all = np.arange(W, dtype=np.int32)
    in_range = pos_all[None, :] < lengths[:, None]
    if k == 0 or k > W:
        sel = xp.zeros((n, W), dtype=bool)
        return sel, in_range.astype(np.int32)
    ok = _needle_hits(xp, data, lengths, needle, W)
    S = ok.shape[1]
    okW = xp.concatenate(
        [ok, xp.zeros((n, W - S), dtype=bool)], axis=1) if S < W else ok

    if xp is np:
        sel = np.zeros((n, W), dtype=bool)
        inside = np.zeros((n, W), dtype=bool)
        nxt = np.zeros(n, dtype=np.int32)
        for i in range(W):
            can = np.logical_and(okW[:, i], nxt <= i)
            inside[:, i] = nxt > i
            sel[:, i] = can
            nxt = np.where(can, np.int32(i + k), nxt)
    else:
        import jax

        def step(nxt, col):
            ok_i, i = col
            can = xp.logical_and(ok_i, nxt <= i)
            inside_i = nxt > i
            nxt = xp.where(can, i + np.int32(k), nxt)
            return nxt, (can, inside_i)

        iota = xp.arange(W, dtype=np.int32)
        _, (sel_t, inside_t) = jax.lax.scan(
            step, xp.zeros(n, dtype=np.int32), (okW.T, iota))
        sel, inside = sel_t.T, inside_t.T
    plain = xp.logical_and(in_range,
                           xp.logical_not(xp.logical_or(sel, inside)))
    return sel, plain.astype(np.int32)


def replace_const(xp, data, lengths, search: bytes, repl: bytes, W_out: int):
    """replace(str, search, repl) with constant search/repl via greedy match
    selection + rank-gather reassembly (cuDF stringReplace analog). Output is
    truncated at W_out bytes."""
    W = data.shape[-1]
    sel, plain = greedy_matches(xp, data, lengths, search, W)
    return reassemble_spans(xp, data, sel, plain, repl, W_out)


def reassemble_spans(xp, data, sel, plain, repl: bytes, W_out: int):
    """Rank-gather reassembly shared by constant replace and regex replace:
    ``sel`` marks span starts (each emits the whole replacement), ``plain``
    is 1 where a byte passes through unchanged, 0 inside spans/padding."""
    W = data.shape[-1]
    r = len(repl)
    n = data.shape[0]
    emit = xp.where(sel, np.int32(r), plain)                  # [n, W]
    csum = xp.cumsum(emit, axis=-1)
    dst = (csum - emit).astype(np.int32)                      # exclusive
    new_len = xp.minimum(csum[:, -1], W_out).astype(np.int32)
    o = np.arange(W_out, dtype=np.int32)
    # Source position for output o: scatter each emitting head i to its
    # destination slot, then forward-fill with a running max — O(n*(W+W_out))
    # instead of materializing an [n, W, W_out] comparison.
    i_idx = np.arange(W, dtype=np.int32)[None, :]
    emitting = xp.logical_and(emit > 0, dst < W_out)
    d = xp.where(emitting, dst, W_out - 1)
    vals = xp.where(emitting, i_idx, -1).astype(np.int32)
    rows = np.broadcast_to(np.arange(n, dtype=np.int32)[:, None], (n, W))
    if xp is np:
        head = np.full((n, W_out), -1, dtype=np.int32)
        np.maximum.at(head, (rows.ravel(), np.asarray(d).ravel()),
                      np.asarray(vals).ravel())
        inv = np.maximum.accumulate(head, axis=1)
    else:
        import jax
        head = xp.full((n, W_out), -1, dtype=np.int32)
        head = head.at[xp.asarray(rows), d].max(vals)
        inv = jax.lax.cummax(head, axis=1)
    inv = xp.clip(inv, 0, W - 1).astype(np.int32)
    src_char = xp.take_along_axis(data, inv, axis=-1)
    is_repl = xp.take_along_axis(sel, inv, axis=-1)
    kk = o[None, :] - xp.take_along_axis(dst, inv, axis=-1)
    if r > 0:
        rarr = xp.asarray(np.frombuffer(repl, dtype=np.uint8))
        repl_char = rarr[xp.clip(kk, 0, r - 1)]
    else:
        repl_char = xp.zeros_like(src_char)
    out = xp.where(is_repl, repl_char, src_char)
    keep = o[None, :] < new_len[:, None]
    return xp.where(keep, out, 0).astype(np.uint8), new_len


def substring_index(xp, data, lengths, delim: bytes, count: int, W: int):
    """substring_index(str, delim, count): text before the count-th delimiter
    (count > 0), after the |count|-th-from-last (count < 0), or empty
    (count == 0); the whole string when there are fewer delimiters."""
    n = data.shape[0]
    if count == 0 or len(delim) == 0:
        return (xp.zeros_like(data), xp.zeros(n, dtype=np.int32))
    sel, _ = greedy_matches(xp, data, lengths, delim, W)
    occ = xp.cumsum(sel.astype(np.int32), axis=-1)            # [n, W]
    total = occ[:, -1]
    k = len(delim)
    if count > 0:
        # cut before the count-th occurrence
        is_cut = xp.logical_and(sel, occ == count)
        has = total >= count
        cut = xp.argmax(is_cut, axis=-1).astype(np.int32)
        start = xp.zeros(n, dtype=np.int32)
        new_len = xp.where(has, cut, lengths)
    else:
        want = total + count + 1                               # 1-based index
        is_cut = xp.logical_and(sel, occ == want[:, None])
        has = total >= -count
        cut = xp.argmax(is_cut, axis=-1).astype(np.int32)
        start = xp.where(has, cut + k, 0).astype(np.int32)
        new_len = xp.where(has, lengths - start, lengths)
    return substring(xp, data, lengths, start, new_len, W)


def concat2(xp, ld, ll, rd, rl, W: int):
    """Concatenate two string columns row-wise, truncating at W bytes."""
    ld, ll = _bcast_rows(xp, ld, ll, rd)
    rd, rl = _bcast_rows(xp, rd, rl, ld)
    ld, rd = pad_width(xp, ld, W), pad_width(xp, rd, W)
    pos = np.arange(W, dtype=np.int32)[None, :]
    from_right = pos >= ll[:, None]
    ridx = xp.clip(pos - ll[:, None], 0, W - 1)
    right_bytes = xp.take_along_axis(rd, ridx, axis=-1)
    out = xp.where(from_right, right_bytes, ld)
    new_len = xp.minimum(ll + rl, W).astype(np.int32)
    keep = pos < new_len[:, None]
    return xp.where(keep, out, 0).astype(np.uint8), new_len


def spans_inside(xp, sel, span_len, W: int):
    """Positions covered by a span but not its start, from span starts +
    per-start lengths (the regex analog of greedy_matches' `inside`)."""
    starts = sel.astype(np.int32)
    ends_pos = xp.clip(xp.where(sel, np.arange(W, dtype=np.int32)[None, :]
                                + span_len, W), 0, W)
    n = sel.shape[0]
    # scatter -1 at each span end (bucket W collects off-the-end)
    if xp is np:
        delta = np.zeros((n, W + 1), dtype=np.int32)
        np.add.at(delta, (np.arange(n)[:, None], ends_pos), -starts)
    else:
        delta = xp.zeros((n, W + 1), dtype=np.int32)
        rows = xp.asarray(np.broadcast_to(np.arange(n)[:, None], sel.shape))
        delta = delta.at[rows, ends_pos].add(-starts)
    delta = delta[:, :W] + starts
    covered = xp.cumsum(delta, axis=-1) > 0
    return xp.logical_and(covered, xp.logical_not(sel))


def split_field(xp, data, lengths, sel, span_len, k: int, W: int):
    """Field k (0-based) of each row split at the selected delimiter spans
    (Spark split(str, regex)[k]): bytes between the end of span k-1 and the
    start of span k. Returns (data, lengths, exists)."""
    pos = np.arange(W, dtype=np.int32)[None, :]
    occ = xp.cumsum(sel.astype(np.int32), axis=-1)            # 1-based at sel
    total = occ[:, -1]
    if k == 0:
        start = xp.zeros(lengths.shape[0], dtype=np.int32)
    else:
        is_k = xp.logical_and(sel, occ == k)
        has_k = total >= k
        p = xp.argmax(is_k, axis=-1).astype(np.int32)
        slen = xp.take_along_axis(span_len, p[:, None], axis=-1)[:, 0]
        start = xp.where(has_k, p + xp.maximum(slen, 0), lengths)
    is_next = xp.logical_and(sel, occ == k + 1)
    has_next = total >= k + 1
    endp = xp.where(has_next, xp.argmax(is_next, axis=-1).astype(np.int32),
                    lengths)
    exists = total >= k
    d, l = substring(xp, data, lengths, start.astype(np.int32),
                     xp.maximum(endp - start, 0), W)
    return d, l, exists
