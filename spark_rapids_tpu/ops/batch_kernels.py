"""Batch-level kernels: filter compaction, sort, group-by, segment reduction.

These replace the cuDF Table ops the reference leans on (Table.filter,
Table.orderBy, Table.groupBy().aggregate(), contiguousSplit) with XLA-native
formulations designed around static shapes:

- outputs keep the input capacity; the *logical* row/group count is returned as a
  traced scalar (the "row-count sidecar" pattern for dynamic cardinality on TPU);
- compaction and grouping ride on stable argsort — XLA's sort is highly tuned for
  TPU, and a sort-based group-by avoids data-dependent hash-table shapes entirely;
- string keys sort exactly (byte-lexicographic == Spark's UTF8String order) via
  big-endian uint64 chunk passes, least-significant chunk first;
- everything here is traceable and fuses into the surrounding jit program.

All functions take/return ColV and plain arrays; ``xp`` is numpy or jax.numpy.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV


def _stable_argsort(xp, keys):
    if xp is np:
        return np.argsort(keys, kind="stable")
    return xp.argsort(keys, stable=True)


# ---------------------------------------------------------------------------
# 64-bit row hashing (the grouping fast path's sort key)
# ---------------------------------------------------------------------------
_HSEED = np.uint64(0x243F6A8885A308D3)
_HNULL = np.uint64(0x452821E638D01377)
_HGOLD = np.uint64(0x9E3779B97F4A7C15)


def _mix64(xp, z):
    """splitmix64 finalizer (wrapping uint64 arithmetic)."""
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _float_canon(xp, d):
    """Canonical frexp decomposition of float64 data: returns
    (sign, e, mi, zero, inf, nan) with m in [1,2) scaled so mi = m*2^52 is an
    exact integer, identical on every engine (no bitcasts — the TPU x64
    emulation cannot compile an f64 bitcast). Shared by the hash and the
    injective key-word encodings so both see the same classes."""
    sign = d < 0
    ax = xp.abs(d)
    nan = xp.isnan(d)
    inf = xp.isinf(d)
    finite_pos = xp.logical_and(ax > 0,
                                xp.logical_not(xp.logical_or(nan, inf)))
    ax_safe = xp.where(finite_pos, ax, 1.0)
    e = xp.clip(xp.floor(xp.log2(ax_safe)), -1074.0, 1023.0)
    m = ax_safe / xp.exp2(e)
    for _ in range(2):  # each step fixes one off-by-one in the estimate
        too_big = m >= 2.0
        too_small = m < 1.0
        e = xp.where(too_big, e + 1.0, xp.where(too_small, e - 1.0, e))
        m = xp.where(too_big, m * 0.5, xp.where(too_small, m * 2.0, m))
    mi = (m * np.float64(2 ** 52)).astype(np.int64)
    return sign, e, mi, ax == 0, inf, nan


def _hash64_col(xp, v: ColV):
    """Per-row 64-bit hash of one column; equal keys (Spark grouping
    semantics: null==null, NaN==NaN, -0.0==0.0) hash equal."""
    if v.dtype is DType.STRING:
        W = v.data.shape[-1]
        # pack each 8-byte chunk into a uint64 (injective) and mix it through
        # splitmix64 with a per-chunk offset before combining — a linear
        # base-31 fold has structured everyday collisions ("Aa" == "BB")
        # that would permanently defeat the hash fast path
        pad = (-W) % 8
        data = v.data
        if pad:
            data = xp.concatenate(
                [data, xp.zeros(data.shape[:-1] + (pad,), dtype=np.uint8)],
                axis=-1)
        shifts = xp.asarray((np.arange(7, -1, -1) * 8).astype(np.uint64))
        chunks = data.reshape(data.shape[:-1] + (-1, 8)).astype(np.uint64)
        words = xp.sum(chunks << shifts, axis=-1)           # [n, W/8]
        n_words = words.shape[-1]
        bits = v.lengths.astype(np.uint64)
        for i in range(n_words):
            # wrapping multiply precomputed in python ints: numpy warns on
            # scalar uint64 overflow even though wrapping is intended
            off = np.uint64(((i + 1) * int(_HGOLD)) & 0xFFFFFFFFFFFFFFFF)
            bits = _mix64(xp, bits ^ _mix64(xp, words[..., i] + off))
    elif v.dtype.is_floating:
        # arithmetic mantissa/exponent decomposition (shared _float_canon) —
        # both engines must use the SAME derivation so group output order
        # matches across CPU and device. (mi, e) is the unique normalized
        # frexp pair on every engine, and m * 2^52 is an exact integer.
        d = v.data.astype(np.float64)
        sign, e, mi, zero, inf, nan = _float_canon(xp, d)
        bits = (mi.astype(np.uint64)
                ^ _mix64(xp, e.astype(np.int64).astype(np.uint64) + _HGOLD)
                ^ (xp.where(sign, np.uint64(1), np.uint64(0))
                   << np.uint64(63)))
        # canonical classes: +/-0.0 hash as one value, every NaN as one
        # value, +/-inf as their own values (distinct from finite 1.0)
        bits = xp.where(zero, xp.full_like(bits, np.uint64(0)), bits)
        bits = xp.where(inf, xp.full_like(bits, np.uint64(0x7FF0000000000000))
                        ^ (xp.where(sign, np.uint64(1), np.uint64(0))
                           << np.uint64(63)), bits)
        bits = xp.where(nan, xp.full_like(bits, np.uint64(0x7FF8000000000000)),
                        bits)
    elif v.dtype is DType.BOOLEAN:
        bits = v.data.astype(np.uint64)
    else:
        bits = v.data.astype(np.int64).astype(np.uint64)
    h = _mix64(xp, bits + _HGOLD)
    return xp.where(v.validity, h, _HNULL)


def hash64_cols(xp, cols: Sequence[ColV]):
    """Combined 64-bit row hash over the key columns."""
    n = cols[0].validity.shape[0]
    h = xp.full((n,), _HSEED, dtype=np.uint64)
    for v in cols:
        h = _mix64(xp, (h ^ _hash64_col(xp, v)) * _HGOLD + _HGOLD)
    return h


def hash_group_order(xp, keys: Sequence[ColV], alive_or_n):
    """Grouping fast path: one stable argsort over the 64-bit key hash instead
    of a full multi-key lexsort (string keys make the exact sort especially
    expensive: their order needs rank sub-sorts). Equal keys land contiguous
    (equal hash + stable order); boundaries still come from exact key
    comparison (rows_equal_adjacent), so the only hazard is two DIFFERENT keys
    sharing a hash — detect_hash_collision flags that and callers fall back to
    the exact sort. Returns (order, hashes)."""
    cap = keys[0].validity.shape[0]
    alive = alive_mask(xp, cap, alive_or_n)
    h = hash64_cols(xp, keys)
    # dead rows sort last: their key is the max uint64, unreachable by h >> 1
    sort_key = xp.where(alive, h >> np.uint64(1),
                        np.uint64(0xFFFFFFFFFFFFFFFF))
    order = _stable_argsort(xp, sort_key)
    return order, h


def detect_hash_collision(xp, hashes, order, starts, alive_or_n):
    """True when any group boundary separates two alive rows with the SAME
    sort key — i.e. two distinct keys collided. (A run holding two distinct
    keys always has an adjacent differing pair, so the adjacent check is
    sufficient to detect every split-group hazard.) Rows sort by h >> 1, so
    the comparison must use the same shifted key: hashes differing only in
    the lowest bit still interleave in sort order."""
    cap = order.shape[0]
    alive = alive_mask(xp, cap, alive_or_n)
    hs = hashes[order] >> np.uint64(1)
    prev_h = xp.concatenate([hs[:1], hs[:-1]])
    a = alive[order]
    prev_a = xp.concatenate([xp.zeros(1, dtype=bool), a[:-1]])
    return xp.any(xp.logical_and(
        xp.logical_and(starts, hs == prev_h),
        xp.logical_and(a, prev_a)))


def as_column(xp, v: ColV, capacity: int) -> ColV:
    """Broadcast a scalar ColV (a literal, e.g. after project inlining) to a
    full column so row-wise kernels can index it."""
    scalar = (v.data.ndim == 1 if v.dtype is DType.STRING
              else v.data.ndim == 0)
    if not scalar:
        return v
    if v.dtype is DType.STRING:
        W = v.data.shape[-1]
        data = xp.broadcast_to(xp.reshape(v.data, (1, W)), (capacity, W))
        lengths = xp.broadcast_to(xp.reshape(v.lengths, (1,)), (capacity,))
    else:
        data = xp.broadcast_to(xp.reshape(v.data, (1,)), (capacity,))
        lengths = None
    validity = xp.broadcast_to(xp.reshape(v.validity, (1,)), (capacity,))
    return ColV(v.dtype, data, validity, lengths)


def take_colv(xp, v: ColV, indices) -> ColV:
    """Permute/gather rows of a column."""
    if v.dtype is DType.STRING:
        return ColV(v.dtype, v.data[indices], v.validity[indices],
                    v.lengths[indices])
    return ColV(v.dtype, v.data[indices], v.validity[indices])


# ---------------------------------------------------------------------------
# variadic payload sort — the TPU replacement for argsort + gathers
# ---------------------------------------------------------------------------
# On TPU a random-access gather of n rows costs ~2x the SORT of n rows (the
# sorting network streams memory; gathers do not vectorize), so
# "argsort + one gather per column" is the single most expensive pattern in
# the engine. XLA's variadic sort moves payload operands WITH the keys, so
# one lax.sort replaces the argsort and every gather.

def multi_sort(xp, passes: Sequence, payloads: Sequence):
    """Stable lexicographic sort by ``passes`` (most significant first),
    carrying ``payloads`` along. Returns (sorted_passes, sorted_payloads)."""
    if xp is np:
        order = np.lexsort(tuple(reversed([np.asarray(p) for p in passes])))
        return ([np.asarray(p)[order] for p in passes],
                [np.asarray(p)[order] for p in payloads])
    import jax
    res = jax.lax.sort(tuple(passes) + tuple(payloads),
                       num_keys=len(passes), is_stable=True)
    return list(res[:len(passes)]), list(res[len(passes):])


def _pack_bytes(xp, data):
    """[n, W] uint8 -> list of [n] uint64 big-endian words (strings ride a
    variadic sort as a few word operands instead of a 2-D gather)."""
    n, W = data.shape
    n_words = (W + 7) // 8
    pad = n_words * 8 - W
    if pad:
        data = xp.concatenate([data, xp.zeros((n, pad), np.uint8)], axis=-1)
    chunks = data.reshape(n, n_words, 8).astype(np.uint64)
    shifts = xp.asarray(np.arange(56, -8, -8, dtype=np.uint64))
    words = xp.sum(chunks << shifts[None, None, :], axis=-1)
    return [words[:, i] for i in range(n_words)]


def _unpack_bytes(xp, words: Sequence, W: int):
    stacked = xp.stack(list(words), axis=1)          # [n, n_words]
    shifts = xp.asarray(np.arange(56, -8, -8, dtype=np.uint64))
    bytes_ = ((stacked[:, :, None] >> shifts[None, None, :])
              & np.uint64(0xFF)).astype(np.uint8)
    n = stacked.shape[0]
    return bytes_.reshape(n, len(words) * 8)[:, :W]


#: XLA TPU compile time for a variadic sort grows steeply with TOTAL
#: operand count (keys + payloads; multi-key stable sorts with many
#: payloads have been observed to wedge the compiler outright); above this
#: bound the argsort+gather fallback is the safer end-to-end choice
MAX_SORT_PAYLOADS = 16


def sort_colvs(xp, passes: Sequence, colvs: Sequence[ColV],
               extras: Sequence = ()):
    """Sort whole columns by the key passes in ONE pass: device side uses a
    single variadic lax.sort (string payloads packed into uint64 words,
    duplicate arrays sorted once, all validity vectors bit-packed into one
    word operand); the CPU side keeps lexsort + gathers. Returns
    (sorted colvs, sorted extras). Ordering is identical across engines
    (both stable lexicographic)."""
    if xp is np:
        order = np.lexsort(tuple(reversed([np.asarray(p) for p in passes])))
        return ([take_colv(np, v, order) for v in colvs],
                [np.asarray(e)[order] for e in extras])
    # dedup payload arrays by identity: BoundReference evaluation returns the
    # SAME tracer for repeated uses of a column (sum(x) and avg(x) share x),
    # so each distinct buffer rides the sort once
    slot_of: dict = {}
    payloads: List = []
    bools: List = []          # validity vectors, bit-packed into u64 words
    bool_slot: dict = {}

    def add(a):
        key = id(a)
        if key not in slot_of:
            slot_of[key] = len(payloads)
            payloads.append(a)
        return slot_of[key]

    def _is_half(a) -> bool:
        # 4-byte payloads pair up into u64 words: sort cost is per OPERAND
        # (~equal for u32 and u64 on TPU), so two halves in one word halve
        # the payload movement of every narrow column
        return (getattr(a, "dtype", None) is not None
                and a.ndim == 1 and a.dtype.itemsize == 4
                and a.dtype.kind in "iuf")

    def add_bool(a):
        key = id(a)
        if key not in bool_slot:
            bool_slot[key] = len(bools)
            bools.append(a)
        return bool_slot[key]

    specs = []
    for v in colvs:
        if v.dtype is DType.STRING:
            words = _pack_bytes(xp, v.data)
            specs.append((v.dtype, [add(w) for w in words],
                          v.data.shape[-1], add(v.lengths),
                          add_bool(v.validity)))
        else:
            specs.append((v.dtype, None, 0, add(v.data),
                          add_bool(v.validity)))
    extra_slots = []
    for e in extras:
        if getattr(e, "dtype", None) == np.bool_:
            extra_slots.append(("b", add_bool(e)))
        else:
            extra_slots.append(("p", add(e)))
    n_bool_words = (len(bools) + 63) // 64
    packed_bools = []
    for w in range(n_bool_words):
        chunk = bools[w * 64:(w + 1) * 64]
        word = None
        for i, b in enumerate(chunk):
            piece = b.astype(np.uint64) << np.uint64(i)
            word = piece if word is None else word | piece
        packed_bools.append(word)

    import jax.lax as _lax

    def _u32(a):
        return (a if a.dtype == np.uint32
                else _lax.bitcast_convert_type(a, np.uint32))

    def _from_u32(a, dtype):
        return (a if dtype == np.uint32
                else _lax.bitcast_convert_type(a, dtype))

    halves = [i for i, a in enumerate(payloads) if _is_half(a)]
    fulls = [i for i, a in enumerate(payloads) if not _is_half(a)]
    n_ops = len(fulls) + (len(halves) + 1) // 2
    if n_ops + n_bool_words + len(passes) > MAX_SORT_PAYLOADS:
        # too many operands for a fast compile: one sort for the permutation,
        # then gathers (the pre-variadic pattern); checked BEFORE any packing
        # work is traced
        cap = passes[0].shape[0]
        iota = xp.arange(cap, dtype=np.int32)
        _, (order,) = multi_sort(xp, passes, [iota])
        return ([take_colv(xp, v, order) for v in colvs],
                [e[order] for e in extras])

    operands = [payloads[i] for i in fulls]
    for w in range(0, len(halves), 2):
        word = _u32(payloads[halves[w]]).astype(np.uint64) << np.uint64(32)
        if w + 1 < len(halves):
            word = word | _u32(payloads[halves[w + 1]]).astype(np.uint64)
        operands.append(word)

    all_payloads = operands + packed_bools

    _, sp = multi_sort(xp, passes, all_payloads)
    recovered: List = [None] * len(payloads)
    for k, i in enumerate(fulls):
        recovered[i] = sp[k]
    base = len(fulls)
    for w in range(0, len(halves), 2):
        word = sp[base + w // 2]
        recovered[halves[w]] = _from_u32(
            (word >> np.uint64(32)).astype(np.uint32),
            payloads[halves[w]].dtype)
        if w + 1 < len(halves):
            recovered[halves[w + 1]] = _from_u32(
                word.astype(np.uint32), payloads[halves[w + 1]].dtype)
    n_operands = len(operands)
    sorted_bools = []
    for w in range(n_bool_words):
        word = sp[n_operands + w]
        sorted_bools.extend(
            ((word >> np.uint64(i)) & np.uint64(1)).astype(bool)
            for i in range(min(64, len(bools) - w * 64)))
    out = []
    for dt, word_slots, W, data_slot, valid_slot in specs:
        if word_slots is not None:
            data = _unpack_bytes(xp, [recovered[s] for s in word_slots], W)
            out.append(ColV(dt, data, sorted_bools[valid_slot],
                            recovered[data_slot]))
        else:
            out.append(ColV(dt, recovered[data_slot],
                            sorted_bools[valid_slot]))
    sorted_extras = [sorted_bools[s] if kind == "b" else recovered[s]
                     for kind, s in extra_slots]
    return out, sorted_extras


def starts_from_sorted(xp, sorted_keys: Sequence[ColV], sorted_alive):
    """Group-start marks over ALREADY-SORTED key columns (the adjacent
    compare of rows_equal_adjacent without the order indirection)."""
    cap = sorted_alive.shape[0]
    first = xp.arange(cap) == 0
    new_group = xp.zeros(cap, dtype=bool)

    def prev(a):
        return xp.concatenate([a[:1], a[:-1]], axis=0)

    for v in sorted_keys:
        a_valid = v.validity
        b_valid = prev(v.validity)
        if v.dtype is DType.STRING:
            same_data = xp.logical_and(
                xp.all(v.data == prev(v.data), axis=-1),
                v.lengths == prev(v.lengths))
        elif v.dtype.is_floating:
            a, b = v.data, prev(v.data)
            same_data = xp.logical_or(
                a == b, xp.logical_and(xp.isnan(a), xp.isnan(b)))
        else:
            same_data = v.data == prev(v.data)
        same = xp.where(xp.logical_and(a_valid, b_valid), same_data,
                        a_valid == b_valid)
        new_group = xp.logical_or(new_group, xp.logical_not(same))
    new_group = xp.logical_or(new_group, first)
    return xp.logical_and(new_group, sorted_alive)


def detect_hash_collision_sorted(xp, hs_sorted, starts, sorted_alive):
    """Collision flag over hash-sorted rows: a group boundary between two
    alive rows with the same (shifted) hash means two distinct keys collided."""
    prev_h = xp.concatenate([hs_sorted[:1], hs_sorted[:-1]])
    prev_a = xp.concatenate([xp.zeros(1, dtype=bool), sorted_alive[:-1]])
    return xp.any(xp.logical_and(
        xp.logical_and(starts, hs_sorted == prev_h),
        xp.logical_and(sorted_alive, prev_a)))


def compact(xp, mask, columns: Sequence[ColV], num_rows):
    """Move rows where mask is true to the front, preserving order; invalidate the
    rest. Returns (columns, new_count). Replaces cudf Table.filter.

    ``mask`` must already be False for padding rows (>= num_rows). One
    variadic sort on device (no per-column gathers).
    """
    keep = xp.asarray(mask, dtype=bool)
    new_count = xp.sum(keep).astype(np.int32)
    cap = keep.shape[0]
    alive = xp.arange(cap, dtype=np.int32) < new_count
    sorted_cols, _ = sort_colvs(
        xp, [xp.logical_not(keep).astype(np.int8)], columns)
    out = [g.with_validity(xp.logical_and(g.validity, alive))
           for g in sorted_cols]
    return out, new_count


def _null_rank(xp, v: ColV, nulls_first: bool):
    """Null position key (explicit in SortOrder, independent of direction)."""
    return xp.where(v.validity, np.int8(0), np.int8(-1 if nulls_first else 1))


def _key_passes(xp, v: ColV, ascending: bool, nulls_first: bool) -> List:
    """One sort key -> list of argsort passes, most significant first.

    Each pass is an int/float array whose ascending order realizes the desired
    order for that component. Composition runs least-significant pass first
    (stable LSD).
    """
    # descending integer keys use bitwise complement (~x is monotone decreasing
    # with no overflow at INT_MIN, unlike unary minus)
    def flip_i(k):
        return k if ascending else ~k

    def flip_f(k):
        return k if ascending else -k

    passes: List = []
    if v.dtype is DType.STRING:
        W = v.data.shape[-1]
        n_chunks = (W + 7) // 8
        pad = n_chunks * 8 - W
        data = v.data
        if pad:
            data = xp.concatenate(
                [data, xp.zeros(data.shape[:-1] + (pad,), dtype=np.uint8)],
                axis=-1)
        chunks = data.reshape(data.shape[0], n_chunks, 8).astype(np.uint64)
        shifts = xp.asarray(np.arange(56, -8, -8, dtype=np.uint64))
        keys = xp.sum(chunks << shifts, axis=-1)  # big-endian uint64 per chunk
        # unsigned -> order-preserving signed so argsort compares byte order.
        # passes[0] (chunk 0) is applied last in LSD composition = most
        # significant; the length tiebreak at the end is least significant.
        for i in range(n_chunks):
            signed = (keys[:, i] ^ np.uint64(2 ** 63)).astype(np.int64)
            passes.append(flip_i(signed))
        passes.append(flip_i(v.lengths.astype(np.int64)))
    elif v.dtype.is_floating:
        d = v.data.astype(np.float64)
        nan = xp.isnan(d)
        val = xp.where(nan, np.float64(np.inf), d)
        # -0.0 == 0.0 for ordering; canonicalize to avoid backend-dependent ties
        val = xp.where(val == 0, np.float64(0.0), val)
        # Spark: NaN is the largest double. Primary comparison is (is_nan, value)
        passes = [flip_i(nan.astype(np.int8)), flip_f(val)]
    elif v.dtype is DType.BOOLEAN:
        passes.append(flip_i(v.data.astype(np.int8)))
    else:
        passes.append(flip_i(v.data.astype(np.int64)))
    # most significant overall: null rank
    return [_null_rank(xp, v, nulls_first)] + passes


def alive_mask(xp, capacity: int, alive_or_n):
    """Normalize a row-liveness spec: an int num_rows -> prefix mask; an array
    passes through (scattered liveness appears after all-gather of partials)."""
    if isinstance(alive_or_n, (int, np.integer)):
        return xp.arange(capacity, dtype=np.int32) < alive_or_n
    if getattr(alive_or_n, "ndim", None) == 0:
        return xp.arange(capacity, dtype=np.int32) < alive_or_n
    return alive_or_n


def sort_indices(xp, keys: Sequence[Tuple[ColV, bool, bool]], alive_or_n):
    """Lexicographic multi-key sort -> row permutation (dead rows last).

    keys: (column, ascending, nulls_first), most significant first. Implemented
    as stable argsort passes composed least-significant-first (LSD); XLA's sort
    is used with stability so earlier passes' order survives ties.
    """
    cap = keys[0][0].validity.shape[0]
    alive = alive_mask(xp, cap, alive_or_n)
    order = xp.arange(cap, dtype=np.int32)
    all_passes: List = []
    for v, asc, nf in keys:
        all_passes.extend(_key_passes(xp, v, asc, nf))
    for k in reversed(all_passes):
        order = order[_stable_argsort(xp, k[order])]
    # most significant of all: dead/padding rows to the back
    is_pad = xp.logical_not(alive[order]).astype(np.int8)
    order = order[_stable_argsort(xp, is_pad)]
    return order


def rows_equal_adjacent(xp, keys: Sequence[ColV], order, alive_or_n):
    """After sorting by `order`, mark rows that START a new group.

    Spark grouping semantics: null == null, NaN == NaN (keys are normalized
    upstream for -0.0).
    """
    cap = order.shape[0]
    prev = xp.concatenate([order[:1], order[:-1]])
    new_group = xp.zeros(cap, dtype=bool)
    first = xp.arange(cap) == 0
    for v in keys:
        a_valid = v.validity[order]
        b_valid = v.validity[prev]
        if v.dtype is DType.STRING:
            same_data = xp.logical_and(
                xp.all(v.data[order] == v.data[prev], axis=-1),
                v.lengths[order] == v.lengths[prev])
        elif v.dtype.is_floating:
            a, b = v.data[order], v.data[prev]
            same_data = xp.logical_or(a == b,
                                      xp.logical_and(xp.isnan(a), xp.isnan(b)))
        else:
            same_data = v.data[order] == v.data[prev]
        same = xp.where(xp.logical_and(a_valid, b_valid), same_data,
                        a_valid == b_valid)
        new_group = xp.logical_or(new_group, xp.logical_not(same))
    new_group = xp.logical_or(new_group, first)
    # padding rows never start a group
    alive = alive_mask(xp, cap, alive_or_n)
    return xp.logical_and(new_group, alive[order])


def segment_pick(xp, validity, seg_ids, num_segments: int, kind: str,
                 alive=None, ignore_nulls: bool = False):
    """Row index of the first/last participating row per segment.

    Participation: alive rows (non-padding); with ignore_nulls additionally
    valid rows. Returns (pick_index, has_pick) — callers gather data/lengths/
    validity with pick_index themselves (needed for string columns with
    multiple per-row arrays).
    """
    n = validity.shape[0]
    if alive is None:
        alive = xp.ones_like(validity)
    candidate = xp.logical_and(alive, validity) if ignore_nulls else alive
    idx = xp.arange(n, dtype=np.int64)
    if xp is np:
        sentinel = n + 1 if kind == "first" else -1
        pick = np.full(num_segments, sentinel, dtype=np.int64)
        key = np.where(candidate, idx, sentinel)
        op = np.minimum if kind == "first" else np.maximum
        op.at(pick, seg_ids, key)
    else:
        import jax
        ops = jax.ops
        if kind == "first":
            key = xp.where(candidate, idx, np.int64(n + 1))
            pick = ops.segment_min(key, seg_ids, num_segments=num_segments)
        else:
            key = xp.where(candidate, idx, np.int64(-1))
            pick = ops.segment_max(key, seg_ids, num_segments=num_segments)
    has = xp.logical_and(pick >= 0, pick < n)
    return xp.clip(pick, 0, max(n - 1, 0)), has


def segment_reduce(xp, data, validity, seg_ids, num_segments: int, kind: str,
                   ignore_nulls: bool = False):
    """Per-segment reduction. data/validity are row-aligned; seg_ids in
    [0, num_segments); rows with seg_id == num_segments-1 reserved for padding
    are fine because their validity is False.

    Returns (seg_data, seg_validity). For first/last, picks the value at the
    first/last (valid, if ignore_nulls) row of each segment.
    """
    if xp is np:
        return _segment_reduce_np(data, validity, seg_ids, num_segments, kind,
                                  ignore_nulls)
    import jax
    import jax.numpy as jnp
    ops = jax.ops
    counts = ops.segment_sum(validity.astype(np.int32), seg_ids,
                             num_segments=num_segments)
    seg_valid = counts > 0
    if kind == "sum":
        contrib = jnp.where(validity, data, 0).astype(data.dtype)
        return ops.segment_sum(contrib, seg_ids, num_segments=num_segments), seg_valid
    if kind in ("min", "max"):
        return (_segment_minmax_jax(jnp, ops, data, validity, seg_ids,
                                    num_segments, kind), seg_valid)
    if kind in ("first", "last"):
        pick, has = segment_pick(jnp, validity, seg_ids, num_segments, kind,
                                 ignore_nulls=ignore_nulls)
        return data[pick], jnp.logical_and(has, validity[pick])
    raise ValueError(kind)


def _segment_minmax_jax(jnp, ops, data, validity, seg_ids, num_segments, kind):
    if data.dtype == np.bool_:
        d = data.astype(np.int8)
        neutral = np.int8(1 if kind == "min" else 0)
        contrib = jnp.where(validity, d, neutral)
        f = ops.segment_min if kind == "min" else ops.segment_max
        return f(contrib, seg_ids, num_segments=num_segments).astype(np.bool_)
    if np.issubdtype(np.dtype(data.dtype), np.floating):
        neutral = np.asarray(np.inf if kind == "min" else -np.inf,
                             dtype=data.dtype)
        # Spark NaN ordering: NaN is the largest value
        nan = jnp.isnan(data)
        d = jnp.where(nan, jnp.asarray(np.inf, dtype=data.dtype), data)
        contrib = jnp.where(validity, d, neutral)
        f = ops.segment_min if kind == "min" else ops.segment_max
        res = f(contrib, seg_ids, num_segments=num_segments)
        # a max that saw any NaN must return NaN; a min returns NaN only if every
        # valid value was NaN
        valid_nan = jnp.logical_and(nan, validity)
        nan_count = ops.segment_sum(valid_nan.astype(np.int32), seg_ids,
                                    num_segments=num_segments)
        valid_count = ops.segment_sum(validity.astype(np.int32), seg_ids,
                                      num_segments=num_segments)
        if kind == "max":
            res = jnp.where(nan_count > 0,
                            jnp.asarray(np.nan, dtype=data.dtype), res)
        else:
            res = jnp.where(jnp.logical_and(valid_count > 0,
                                            nan_count == valid_count),
                            jnp.asarray(np.nan, dtype=data.dtype), res)
        return res
    neutral = (np.iinfo(np.dtype(data.dtype)).max if kind == "min"
               else np.iinfo(np.dtype(data.dtype)).min)
    contrib = jnp.where(validity, data, neutral)
    f = ops.segment_min if kind == "min" else ops.segment_max
    return f(contrib, seg_ids, num_segments=num_segments)


def _segment_reduce_np(data, validity, seg_ids, num_segments, kind, ignore_nulls):
    """Eager numpy reference implementation (CPU engine path)."""
    seg_ids = np.asarray(seg_ids)
    validity = np.asarray(validity)
    counts = np.zeros(num_segments, dtype=np.int64)
    np.add.at(counts, seg_ids, validity.astype(np.int64))
    seg_valid = counts > 0
    if kind == "sum":
        out = np.zeros(num_segments, dtype=data.dtype)
        np.add.at(out, seg_ids, np.where(validity, data, 0))
        return out, seg_valid
    if kind in ("min", "max"):
        return _np_minmax(data, validity, seg_ids, num_segments, kind), seg_valid
    if kind in ("first", "last"):
        pick, has = segment_pick(np, validity, seg_ids, num_segments, kind,
                                 ignore_nulls=ignore_nulls)
        return data[pick], has & validity[pick]
    raise ValueError(kind)


def _np_minmax(data, validity, seg_ids, num_segments, kind):
    isfloat = np.issubdtype(data.dtype, np.floating)
    if data.dtype == np.bool_:
        d = data.astype(np.int8)
        neutral = 1 if kind == "min" else 0
        out = np.full(num_segments, neutral, dtype=np.int8)
        getattr(np, "minimum" if kind == "min" else "maximum").at(
            out, seg_ids, np.where(validity, d, neutral))
        return out.astype(np.bool_)
    if isfloat:
        nan = np.isnan(data)
        d = np.where(nan, np.inf, data)
        neutral = np.inf if kind == "min" else -np.inf
        out = np.full(num_segments, neutral, dtype=data.dtype)
        getattr(np, "minimum" if kind == "min" else "maximum").at(
            out, seg_ids, np.where(validity, d, neutral))
        valid_nan = nan & validity
        nan_count = np.zeros(num_segments, dtype=np.int64)
        np.add.at(nan_count, seg_ids, valid_nan.astype(np.int64))
        valid_count = np.zeros(num_segments, dtype=np.int64)
        np.add.at(valid_count, seg_ids, validity.astype(np.int64))
        if kind == "max":
            out = np.where(nan_count > 0, np.nan, out)
        else:
            out = np.where((valid_count > 0) & (nan_count == valid_count),
                           np.nan, out)
        return out.astype(data.dtype)
    neutral = (np.iinfo(data.dtype).max if kind == "min"
               else np.iinfo(data.dtype).min)
    out = np.full(num_segments, neutral, dtype=data.dtype)
    getattr(np, "minimum" if kind == "min" else "maximum").at(
        out, seg_ids, np.where(validity, data, neutral))
    return out


class SegmentStacker:
    """Batches many same-kind per-segment reductions into ONE segment op.

    TPU scatters pay a cost proportional to the row count per CALL, so k
    separate segment_sum/min/max calls over the same seg_ids cost ~k scatters;
    stacking the contributions as an [n, k] payload makes them ONE scatter
    (measured ~8x on a v5 chip for 12 columns). Register contributions with
    :meth:`add` (caller applies its own neutral-element masking), call
    :meth:`run` once, then fetch columns via the returned handles.
    """

    def __init__(self, xp, seg_ids, num_segments: int):
        self.xp = xp
        self.seg_ids = seg_ids
        self.num_segments = num_segments
        self._buckets = {}
        self._results = {}
        self._ran = False

    def add(self, kind: str, contrib):
        assert not self._ran
        key = (kind, str(contrib.dtype))
        bucket = self._buckets.setdefault(key, [])
        bucket.append(contrib)
        return (key, len(bucket) - 1)

    def run(self) -> None:
        import jax
        self._ran = True
        ops = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}
        for key, arrs in self._buckets.items():
            kind, _ = key
            m = self.xp.stack(arrs, axis=1)
            self._results[key] = ops[kind](m, self.seg_ids,
                                           num_segments=self.num_segments)

    def get(self, handle):
        key, idx = handle
        return self._results[key][:, idx]


def key_words(xp, v: ColV) -> List:
    """Injective uint64 encoding of one grouping-key column: a static-length
    word list such that two rows are grouping-equal (Spark semantics:
    null==null, NaN==NaN, -0.0==0.0) IFF all their words are equal. Invalid
    rows canonicalize every word to 0 — pair with a validity word (see
    ``validity_word``) to separate null from a zero-encoded value.

    Used by the one-hot aggregation path for EXACT hash-collision detection:
    per group, min(word) != max(word) for any word proves two distinct keys
    shared a hash.
    """
    if v.dtype is DType.STRING:
        W = v.data.shape[-1]
        pad = (-W) % 8
        data = v.data
        if pad:
            data = xp.concatenate(
                [data, xp.zeros(data.shape[:-1] + (pad,), dtype=np.uint8)],
                axis=-1)
        shifts = xp.asarray((np.arange(7, -1, -1) * 8).astype(np.uint64))
        chunks = data.reshape(data.shape[:-1] + (-1, 8)).astype(np.uint64)
        words = xp.sum(chunks << shifts, axis=-1)
        out = [xp.where(v.validity, words[..., i], np.uint64(0))
               for i in range(words.shape[-1])]
        out.append(xp.where(v.validity, v.lengths.astype(np.uint64),
                            np.uint64(0)))
        return out
    if v.dtype.is_floating:
        d = v.data.astype(np.float64)
        sign, e, mi, zero, inf, nan = _float_canon(xp, d)
        # finite: w0 = mi (in [2^52, 2^53)); specials use small codes that a
        # finite mi can never take. w1 = sign/exponent field.
        w0 = mi.astype(np.uint64)
        w0 = xp.where(zero, np.uint64(1), w0)
        w0 = xp.where(inf, np.uint64(2), w0)
        w0 = xp.where(nan, np.uint64(3), w0)
        w1 = ((e.astype(np.int64) + np.int64(1074)).astype(np.uint64)
              | (xp.where(sign, np.uint64(1), np.uint64(0)) << np.uint64(13)))
        w1 = xp.where(zero, np.uint64(0), w1)
        w1 = xp.where(nan, np.uint64(0), w1)
        w1 = xp.where(inf, xp.where(sign, np.uint64(1), np.uint64(0)), w1)
        return [xp.where(v.validity, w0, np.uint64(0)),
                xp.where(v.validity, w1, np.uint64(0))]
    if v.dtype is DType.BOOLEAN:
        return [xp.where(v.validity, v.data.astype(np.uint64), np.uint64(0))]
    bits = v.data.astype(np.int64).astype(np.uint64)
    return [xp.where(v.validity, bits, np.uint64(0))]


def validity_word(xp, keys: Sequence[ColV]):
    """One uint64 packing every key column's validity bit (<=64 columns)."""
    w = None
    for i, v in enumerate(keys[:64]):
        piece = v.validity.astype(np.uint64) << np.uint64(i)
        w = piece if w is None else w | piece
    return w


#: block shape of the sorted-segment reduction: B consecutive sorted rows
#: reduce into L block-local one-hot slots. A block spanning >= L distinct
#: segments trips the traced overflow flag and the program falls back to the
#: full scatter (correct at the old speed).
_SEG_BLOCK_B = 512
_SEG_BLOCK_L = 16


class SortedSegmentStacker(SegmentStacker):
    """SegmentStacker over SORTED (non-decreasing) seg_ids.

    TPU scatters cost ~100ns/row regardless of segment count, which made the
    stacked scatter the dominant kernel of every aggregation (~0.6s for 6M
    rows on v5e). With sorted ids, rows reduce block-locally first: each
    block of B rows builds a [B, L] one-hot against its local id offsets and
    reduces to L partials, then only nb*L partials (a ~B/L-fold reduction in
    scattered rows) go through the real scatter. Blocks spanning >= L
    segments flip a traced overflow flag; a lax.cond then routes the stacked
    contributions through the plain full scatter instead, so skewed/tiny-group
    inputs stay correct. Measured ~9x over the full scatter at 6M rows.
    """

    def run(self) -> None:
        import jax
        xp = self.xp
        gids = self.seg_ids
        cap = gids.shape[0]
        B, L = _SEG_BLOCK_B, _SEG_BLOCK_L
        if xp is np or cap % B or cap < 4 * B:
            super().run()
            return
        nb = cap // B
        g2 = gids.reshape(nb, B)
        first = g2[:, :1]
        overflow = xp.any((g2[:, -1:] - first) >= L)
        loc = xp.clip(g2 - first, 0, L - 1)
        onehot = loc[:, :, None] == xp.arange(L, dtype=gids.dtype)[None, None, :]
        pg = xp.clip(first + xp.arange(L, dtype=np.int32)[None, :], 0,
                     self.num_segments - 1).reshape(-1)

        ops = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}
        self._ran = True
        for key, arrs in self._buckets.items():
            kind, _ = key
            m = xp.stack(arrs, axis=1)          # [cap, k]
            dt = m.dtype
            if kind == "sum":
                neutral = xp.zeros((), dtype=dt)
            elif kind == "min":
                neutral = (xp.asarray(np.inf, dt)
                           if np.issubdtype(dt, np.floating)
                           else xp.asarray(np.iinfo(dt).max, dt))
            else:
                neutral = (xp.asarray(-np.inf, dt)
                           if np.issubdtype(dt, np.floating)
                           else xp.asarray(np.iinfo(dt).min, dt))

            def blocked(m, kind=kind, neutral=neutral):
                k = m.shape[1]
                mb = m.reshape(nb, B, 1, k)
                masked = xp.where(onehot[:, :, :, None], mb, neutral)
                if kind == "sum":
                    part = xp.sum(masked, axis=1, dtype=m.dtype)
                elif kind == "min":
                    part = xp.min(masked, axis=1)
                else:
                    part = xp.max(masked, axis=1)
                return ops[kind](part.reshape(nb * L, k).astype(m.dtype), pg,
                                 num_segments=self.num_segments)

            def full(m, kind=kind):
                return ops[kind](m, gids, num_segments=self.num_segments)

            self._results[key] = jax.lax.cond(overflow, full, blocked, m)


def take_columns(xp, columns: Sequence[ColV], indices) -> List[ColV]:
    """Permute many columns by one index vector, stacking same-dtype 1-D
    buffers so the device does one gather per dtype group instead of one per
    buffer (~2x on TPU for wide batches; gathers dominate compact/sort)."""
    if xp is np:
        return [take_colv(xp, v, indices) for v in columns]
    slots = {}   # dtype str -> list of (col_idx, role, array)
    for i, v in enumerate(columns):
        entries = [(i, "data", v.data), (i, "validity", v.validity)]
        if v.lengths is not None:
            entries.append((i, "lengths", v.lengths))
        for e in entries:
            arr = e[2]
            if arr.ndim == 1:
                slots.setdefault(str(arr.dtype), []).append(e)
            else:
                slots.setdefault(f"2d{i}{e[1]}", []).append(e)
    gathered = {}
    for key, entries in slots.items():
        if len(entries) == 1 or key.startswith("2d"):
            for i, role, arr in entries:
                gathered[(i, role)] = arr[indices]
        else:
            m = xp.stack([arr for _, _, arr in entries], axis=1)[indices]
            for j, (i, role, _) in enumerate(entries):
                gathered[(i, role)] = m[:, j]
    out = []
    for i, v in enumerate(columns):
        out.append(ColV(v.dtype, gathered[(i, "data")],
                        gathered[(i, "validity")],
                        gathered.get((i, "lengths"))))
    return out
