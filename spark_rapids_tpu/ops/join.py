"""Equi-join kernels (reference: shims/spark300/GpuHashJoin.scala:220-230 —
cudf Table.innerJoin/leftJoin/leftSemiJoin/leftAntiJoin/fullJoin).

TPU re-design: instead of a device hash table (dynamic shapes), both sides' keys
are assigned *dense group ids* by one shared sort over the union of keys — rows
join iff they share a gid. Join cardinality is dynamic, so the kernel is split:

  phase 1 (size):   one jit program computes per-emit-group counts, offsets and
                    the total output size (a traced scalar, synced to host once);
  phase 2 (gather): a second jit program with the bucketed static output
                    capacity gathers the matching row pairs.

This is the two-pass size-then-gather pattern for dynamic cardinality on XLA.
Spark semantics: null keys never match (any-null rows are excluded from
grouping); NaN keys match each other; supported: inner, left, right, full,
left_semi, left_anti, cross.

Emit-group layout: groups [0, S) are stream (left) rows — each emits its match
count (or 1 null-padded row for left/full when unmatched, or 0/1 for
semi/anti); groups [S, S+B) are build (right) rows — each emits 1 when
unmatched under right/full. A single exclusive-scan over all S+B groups gives
output offsets for both halves.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from spark_rapids_tpu.exprs.core import ColV
from spark_rapids_tpu.ops import batch_kernels as bk

JOIN_KINDS = ("inner", "left", "right", "full", "left_semi", "left_anti", "cross")


def _any_null(xp, keys: Sequence[ColV]):
    out = None
    for k in keys:
        inv = xp.logical_not(k.validity)
        out = inv if out is None else xp.logical_or(out, inv)
    return out


def _concat_colv(xp, a: ColV, b: ColV) -> ColV:
    if a.lengths is not None:
        from spark_rapids_tpu.ops.strings import align_widths
        ad, bd = align_widths(xp, a.data, b.data)
        a = ColV(a.dtype, ad, a.validity, a.lengths)
        b = ColV(b.dtype, bd, b.validity, b.lengths)
    data = xp.concatenate([a.data, b.data], axis=0)
    validity = xp.concatenate([a.validity, b.validity], axis=0)
    lengths = (xp.concatenate([a.lengths, b.lengths], axis=0)
               if a.lengths is not None else None)
    return ColV(a.dtype, data, validity, lengths)


def _exclusive_cumsum(xp, x):
    c = xp.cumsum(x)
    return c - x


def join_size(xp, l_keys: Sequence[ColV], r_keys: Sequence[ColV],
              l_alive, r_alive, how: str):
    """Phase 1. Returns a dict of device arrays:
    emit_counts [S+B], emit_offsets [S+B], total (scalar), border [B],
    start_b [S+B], sgid [S], matches_l [S].
    """
    S = l_keys[0].validity.shape[0] if l_keys else l_alive.shape[0]
    B = r_keys[0].validity.shape[0] if r_keys else r_alive.shape[0]
    G = S + B

    if how == "cross":
        B_count = xp.sum(r_alive).astype(np.int64)
        emit_counts = xp.where(l_alive, B_count, 0).astype(np.int64)
        emit_counts = xp.concatenate(
            [emit_counts, xp.zeros(B, dtype=np.int64)])
        emit_offsets = _exclusive_cumsum(xp, emit_counts)
        total = xp.sum(emit_counts)
        # build rows in original order, compacted to the front
        border = bk._stable_argsort(xp, xp.logical_not(r_alive))
        return dict(emit_counts=emit_counts, emit_offsets=emit_offsets,
                    total=total, border=border.astype(np.int32),
                    start_b=xp.zeros(G, dtype=np.int64),
                    sgid=xp.zeros(S, dtype=np.int32),
                    matches_l=xp.where(l_alive, B_count, 0).astype(np.int64))

    l_null = _any_null(xp, l_keys)
    r_null = _any_null(xp, r_keys)
    l_match_ok = xp.logical_and(l_alive, xp.logical_not(l_null))
    r_match_ok = xp.logical_and(r_alive, xp.logical_not(r_null))

    keys_all = [_concat_colv(xp, lk, rk) for lk, rk in zip(l_keys, r_keys)]
    alive_all = xp.concatenate([l_match_ok, r_match_ok])
    order = bk.sort_indices(xp, [(k, True, True) for k in keys_all], alive_all)
    starts = bk.rows_equal_adjacent(xp, keys_all, order, alive_all)
    gids_sorted = xp.cumsum(starts.astype(np.int32)) - 1
    # scatter gids back to row order; dead rows get -1
    inv = bk._stable_argsort(xp, order)      # inverse permutation
    gid_by_row = gids_sorted[inv]
    gid_by_row = xp.where(alive_all, gid_by_row, -1).astype(np.int32)
    sgid = gid_by_row[:S]
    bgid = gid_by_row[S:]

    bgid_safe = xp.clip(bgid, 0, G - 1)
    ones_b = xp.where(bgid >= 0, 1, 0).astype(np.int64)
    counts_b = _segment_sum(xp, ones_b, bgid_safe, G)
    ones_s = xp.where(sgid >= 0, 1, 0).astype(np.int64)
    counts_s = _segment_sum(xp, ones_s, xp.clip(sgid, 0, G - 1), G)

    matches_l = xp.where(sgid >= 0, counts_b[xp.clip(sgid, 0, G - 1)], 0)
    matched_b = xp.where(bgid >= 0, counts_s[bgid_safe] > 0, False)

    if how == "inner":
        emit_l = matches_l
        emit_r = xp.zeros(B, dtype=np.int64)
    elif how in ("left",):
        emit_l = xp.where(l_alive, xp.maximum(matches_l, 1), 0)
        emit_r = xp.zeros(B, dtype=np.int64)
    elif how == "right":
        emit_l = matches_l
        emit_r = xp.where(xp.logical_and(r_alive, xp.logical_not(matched_b)),
                          1, 0).astype(np.int64)
    elif how == "full":
        emit_l = xp.where(l_alive, xp.maximum(matches_l, 1), 0)
        emit_r = xp.where(xp.logical_and(r_alive, xp.logical_not(matched_b)),
                          1, 0).astype(np.int64)
    elif how == "left_semi":
        emit_l = xp.where(matches_l > 0, 1, 0).astype(np.int64)
        emit_r = xp.zeros(B, dtype=np.int64)
    elif how == "left_anti":
        emit_l = xp.where(xp.logical_and(l_alive, matches_l == 0), 1, 0
                          ).astype(np.int64)
        emit_r = xp.zeros(B, dtype=np.int64)
    else:
        raise ValueError(how)

    emit_counts = xp.concatenate([emit_l.astype(np.int64), emit_r])
    emit_offsets = _exclusive_cumsum(xp, emit_counts)
    total = xp.sum(emit_counts)

    # build rows sorted by gid (dead rows last); first border-index per gid
    bkey = xp.where(bgid >= 0, bgid, G).astype(np.int64)
    border = bk._stable_argsort(xp, bkey).astype(np.int32)
    pos = xp.arange(B, dtype=np.int64)
    bgid_sorted = bgid[border]
    start_b = _segment_min(xp, xp.where(bgid_sorted >= 0, pos, np.int64(B)),
                           xp.clip(bgid_sorted, 0, G - 1), G)

    return dict(emit_counts=emit_counts, emit_offsets=emit_offsets, total=total,
                border=border, start_b=start_b, sgid=sgid,
                matches_l=matches_l.astype(np.int64))


def join_gather(xp, sized: dict, S: int, B: int, out_cap: int, how: str):
    """Phase 2: output row -> (left_row, left_valid, right_row, right_valid).

    left/right_row are gather indices into the original batches; *_valid False
    means that side is null-padded (outer joins) or absent (semi/anti emit only
    the left side).
    """
    emit_offsets = sized["emit_offsets"]
    emit_counts = sized["emit_counts"]
    border = sized["border"]
    start_b = sized["start_b"]
    sgid = sized["sgid"]
    matches_l = sized["matches_l"]
    total = sized["total"]

    p = xp.arange(out_cap, dtype=np.int64)
    in_range = p < total
    g = xp.searchsorted(emit_offsets, p, side="right") - 1
    g = xp.clip(g, 0, S + B - 1).astype(np.int64)
    k = p - emit_offsets[g]

    from_stream = g < S
    srow = xp.clip(g, 0, S - 1)
    brow_unmatched = xp.clip(g - S, 0, max(B - 1, 0))

    if how == "cross":
        bpos = xp.clip(k, 0, max(B - 1, 0))
        right_row = border[bpos]
        left_valid = xp.logical_and(in_range, from_stream)
        right_valid = left_valid
        return (srow.astype(np.int32), left_valid,
                right_row.astype(np.int32), right_valid, total)

    has_match = matches_l[srow] > 0
    sg = xp.clip(sgid[srow], 0, S + B - 1)
    bpos = xp.clip(start_b[sg] + k, 0, max(B - 1, 0))
    right_from_match = border[bpos]

    if how in ("left_semi", "left_anti"):
        left_row = srow
        left_valid = in_range
        right_row = xp.zeros_like(srow)
        right_valid = xp.zeros_like(in_range)
        return (left_row.astype(np.int32), left_valid,
                right_row.astype(np.int32), right_valid, total)

    left_row = xp.where(from_stream, srow, 0)
    left_valid = xp.logical_and(in_range, from_stream)
    right_row = xp.where(from_stream, right_from_match, brow_unmatched)
    right_valid = xp.logical_and(
        in_range, xp.logical_or(xp.logical_and(from_stream, has_match),
                                xp.logical_not(from_stream)))
    return (left_row.astype(np.int32), left_valid,
            right_row.astype(np.int32), right_valid, total)


def gather_join_output(xp, l_cols: Sequence[ColV], r_cols: Sequence[ColV],
                       left_row, left_valid, right_row, right_valid
                       ) -> List[ColV]:
    """Materialize output columns from gather indices; a False side-valid bit
    nulls out that side's columns (outer padding)."""
    out: List[ColV] = []
    for v in l_cols:
        g = bk.take_colv(xp, v, left_row)
        out.append(g.with_validity(xp.logical_and(g.validity, left_valid)))
    for v in r_cols:
        g = bk.take_colv(xp, v, right_row)
        out.append(g.with_validity(xp.logical_and(g.validity, right_valid)))
    return out


def _segment_sum(xp, data, seg_ids, num_segments: int):
    if xp is np:
        out = np.zeros(num_segments, dtype=data.dtype)
        np.add.at(out, seg_ids, data)
        return out
    import jax
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def _segment_min(xp, data, seg_ids, num_segments: int):
    if xp is np:
        out = np.full(num_segments, np.iinfo(data.dtype).max, dtype=data.dtype)
        np.minimum.at(out, seg_ids, data)
        return out
    import jax
    return jax.ops.segment_min(data, seg_ids, num_segments=num_segments)
