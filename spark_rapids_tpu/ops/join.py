"""Equi-join kernels (reference: shims/spark300/GpuHashJoin.scala:220-230 —
cudf Table.innerJoin/leftJoin/leftSemiJoin/leftAntiJoin/fullJoin).

TPU re-design: instead of a device hash table (dynamic shapes), both sides' keys
are assigned *dense group ids* by one shared sort over the union of keys — rows
join iff they share a gid. Join cardinality is dynamic, so the kernel is split:

  phase 1 (size):   one jit program computes per-emit-group counts, offsets and
                    the total output size (a traced scalar, synced to host once);
  phase 2 (gather): a second jit program with the bucketed static output
                    capacity gathers the matching row pairs.

This is the two-pass size-then-gather pattern for dynamic cardinality on XLA.
Spark semantics: null keys never match (any-null rows are excluded from
grouping); NaN keys match each other; supported: inner, left, right, full,
left_semi, left_anti, cross.

Emit-group layout: groups [0, S) are stream (left) rows — each emits its match
count (or 1 null-padded row for left/full when unmatched, or 0/1 for
semi/anti); groups [S, S+B) are build (right) rows — each emits 1 when
unmatched under right/full. A single exclusive-scan over all S+B groups gives
output offsets for both halves.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from spark_rapids_tpu.exprs.core import ColV
from spark_rapids_tpu.ops import batch_kernels as bk

JOIN_KINDS = ("inner", "left", "right", "full", "left_semi", "left_anti", "cross")


def _any_null(xp, keys: Sequence[ColV]):
    out = None
    for k in keys:
        inv = xp.logical_not(k.validity)
        out = inv if out is None else xp.logical_or(out, inv)
    return out


def _concat_colv(xp, a: ColV, b: ColV) -> ColV:
    if a.lengths is not None:
        from spark_rapids_tpu.ops.strings import align_widths
        ad, bd = align_widths(xp, a.data, b.data)
        a = ColV(a.dtype, ad, a.validity, a.lengths)
        b = ColV(b.dtype, bd, b.validity, b.lengths)
    data = xp.concatenate([a.data, b.data], axis=0)
    validity = xp.concatenate([a.validity, b.validity], axis=0)
    lengths = (xp.concatenate([a.lengths, b.lengths], axis=0)
               if a.lengths is not None else None)
    return ColV(a.dtype, data, validity, lengths)


def _exclusive_cumsum(xp, x):
    c = xp.cumsum(x)
    return c - x


def join_size(xp, l_keys: Sequence[ColV], r_keys: Sequence[ColV],
              l_alive, r_alive, how: str):
    """Phase 1. Returns a dict of device arrays:
    emit_counts [S+B], emit_offsets [S+B], total (scalar), border [B],
    start_b [S] (PER STREAM ROW: the row's group's first build-row index
    within `border`), sgid [S], matches_l [S].
    """
    S = l_keys[0].validity.shape[0] if l_keys else l_alive.shape[0]
    B = r_keys[0].validity.shape[0] if r_keys else r_alive.shape[0]
    G = S + B

    if how == "cross":
        B_count = xp.sum(r_alive).astype(np.int64)
        emit_counts = xp.where(l_alive, B_count, 0).astype(np.int64)
        emit_counts = xp.concatenate(
            [emit_counts, xp.zeros(B, dtype=np.int64)])
        emit_offsets = _exclusive_cumsum(xp, emit_counts)
        total = xp.sum(emit_counts)
        # build rows in original order, compacted to the front
        border = bk._stable_argsort(xp, xp.logical_not(r_alive))
        return dict(emit_counts=emit_counts, emit_offsets=emit_offsets,
                    total=total, border=border.astype(np.int32),
                    start_b=xp.zeros(S, dtype=np.int64),
                    sgid=xp.zeros(S, dtype=np.int32),
                    matches_l=xp.where(l_alive, B_count, 0).astype(np.int64))

    l_null = _any_null(xp, l_keys)
    r_null = _any_null(xp, r_keys)
    l_match_ok = xp.logical_and(l_alive, xp.logical_not(l_null))
    r_match_ok = xp.logical_and(r_alive, xp.logical_not(r_null))

    keys_all = [_concat_colv(xp, lk, rk) for lk, rk in zip(l_keys, r_keys)]
    alive_all = xp.concatenate([l_match_ok, r_match_ok])
    order = bk.sort_indices(xp, [(k, True, True) for k in keys_all], alive_all)
    starts = bk.rows_equal_adjacent(xp, keys_all, order, alive_all)
    gids_sorted = xp.cumsum(starts.astype(np.int32)) - 1
    # scatter gids back to row order; dead rows get -1
    inv = bk._stable_argsort(xp, order)      # inverse permutation
    gid_by_row = gids_sorted[inv]
    gid_by_row = xp.where(alive_all, gid_by_row, -1).astype(np.int32)
    sgid = gid_by_row[:S]
    bgid = gid_by_row[S:]

    # per-row group counts WITHOUT scatters (1.16 s per scatter-segment_sum
    # at 8.4M rows on this chip vs ~30 ms per scan): compute in SORTED
    # space — group-start/end positions from cummax/cummin over the start
    # marks, member counts as inclusive-cumsum differences — then gather
    # back to row order through the inverse permutation.
    pos = xp.arange(G, dtype=np.int64)
    alive_sorted = alive_all[order]
    is_b_sorted = xp.logical_and(order >= S, alive_sorted)
    is_s_sorted = xp.logical_and(order < S, alive_sorted)
    csum_b = xp.cumsum(is_b_sorted.astype(np.int64))
    csum_s = xp.cumsum(is_s_sorted.astype(np.int64))
    if xp is np:
        st = np.maximum.accumulate(xp.where(starts, pos, 0))
        nxt = xp.where(starts, pos, G)
        nxt_rev = np.minimum.accumulate(nxt[::-1])[::-1]
    else:
        import jax
        st = jax.lax.cummax(xp.where(starts, pos, np.int64(0)))
        nxt = xp.where(starts, pos, np.int64(G))
        nxt_rev = jax.lax.cummin(nxt[::-1])[::-1]
    # next group's start strictly after i = min start at/after i+1
    en = xp.concatenate([nxt_rev[1:], xp.full((1,), G, np.int64)]) - 1
    en = xp.clip(en, 0, G - 1)
    b_at_st = is_b_sorted[st].astype(np.int64)
    s_at_st = is_s_sorted[st].astype(np.int64)
    cnt_b_sorted = csum_b[en] - csum_b[st] + b_at_st
    cnt_s_sorted = csum_s[en] - csum_s[st] + s_at_st
    startb_sorted = csum_b[st] - b_at_st       # build rows before my group
    cnt_b_row = cnt_b_sorted[inv]
    cnt_s_row = cnt_s_sorted[inv]
    startb_row = startb_sorted[inv]

    matches_l = xp.where(sgid >= 0, cnt_b_row[:S], 0)
    matched_b = xp.where(bgid >= 0, cnt_s_row[S:] > 0, False)
    #: per-STREAM-row index of the group's first build row within `border`
    start_b_stream = xp.where(sgid >= 0, startb_row[:S], 0).astype(np.int64)

    if how == "inner":
        emit_l = matches_l
        emit_r = xp.zeros(B, dtype=np.int64)
    elif how in ("left",):
        emit_l = xp.where(l_alive, xp.maximum(matches_l, 1), 0)
        emit_r = xp.zeros(B, dtype=np.int64)
    elif how == "right":
        emit_l = matches_l
        emit_r = xp.where(xp.logical_and(r_alive, xp.logical_not(matched_b)),
                          1, 0).astype(np.int64)
    elif how == "full":
        emit_l = xp.where(l_alive, xp.maximum(matches_l, 1), 0)
        emit_r = xp.where(xp.logical_and(r_alive, xp.logical_not(matched_b)),
                          1, 0).astype(np.int64)
    elif how == "left_semi":
        emit_l = xp.where(matches_l > 0, 1, 0).astype(np.int64)
        emit_r = xp.zeros(B, dtype=np.int64)
    elif how == "left_anti":
        emit_l = xp.where(xp.logical_and(l_alive, matches_l == 0), 1, 0
                          ).astype(np.int64)
        emit_r = xp.zeros(B, dtype=np.int64)
    else:
        raise ValueError(how)

    emit_counts = xp.concatenate([emit_l.astype(np.int64), emit_r])
    emit_offsets = _exclusive_cumsum(xp, emit_counts)
    total = xp.sum(emit_counts)

    # build rows sorted by gid (dead rows last); start_b is PER STREAM ROW
    # (the first border-index of the row's group), replacing the dense
    # per-group segment_min with the sorted-space prefix computed above
    bkey = xp.where(bgid >= 0, bgid, G).astype(np.int64)
    border = bk._stable_argsort(xp, bkey).astype(np.int32)

    return dict(emit_counts=emit_counts, emit_offsets=emit_offsets, total=total,
                border=border, start_b=start_b_stream, sgid=sgid,
                matches_l=matches_l.astype(np.int64))


def join_gather(xp, sized: dict, S: int, B: int, out_cap: int, how: str):
    """Phase 2: output row -> (left_row, left_valid, right_row, right_valid).

    left/right_row are gather indices into the original batches; *_valid False
    means that side is null-padded (outer joins) or absent (semi/anti emit only
    the left side).
    """
    emit_offsets = sized["emit_offsets"]
    emit_counts = sized["emit_counts"]
    border = sized["border"]
    start_b = sized["start_b"]
    sgid = sized["sgid"]
    matches_l = sized["matches_l"]
    total = sized["total"]

    p = xp.arange(out_cap, dtype=np.int64)
    in_range = p < total
    g = _searchsorted_right(xp, emit_offsets, p) - 1
    g = xp.clip(g, 0, S + B - 1).astype(np.int64)
    k = p - emit_offsets[g]

    from_stream = g < S
    srow = xp.clip(g, 0, S - 1)
    brow_unmatched = xp.clip(g - S, 0, max(B - 1, 0))

    if how == "cross":
        bpos = xp.clip(k, 0, max(B - 1, 0))
        right_row = border[bpos]
        left_valid = xp.logical_and(in_range, from_stream)
        right_valid = left_valid
        return (srow.astype(np.int32), left_valid,
                right_row.astype(np.int32), right_valid, total)

    has_match = matches_l[srow] > 0
    bpos = xp.clip(start_b[srow] + k, 0, max(B - 1, 0))
    right_from_match = border[bpos]

    if how in ("left_semi", "left_anti"):
        left_row = srow
        left_valid = in_range
        right_row = xp.zeros_like(srow)
        right_valid = xp.zeros_like(in_range)
        return (left_row.astype(np.int32), left_valid,
                right_row.astype(np.int32), right_valid, total)

    left_row = xp.where(from_stream, srow, 0)
    left_valid = xp.logical_and(in_range, from_stream)
    right_row = xp.where(from_stream, right_from_match, brow_unmatched)
    right_valid = xp.logical_and(
        in_range, xp.logical_or(xp.logical_and(from_stream, has_match),
                                xp.logical_not(from_stream)))
    return (left_row.astype(np.int32), left_valid,
            right_row.astype(np.int32), right_valid, total)


def gather_join_output(xp, l_cols: Sequence[ColV], r_cols: Sequence[ColV],
                       left_row, left_valid, right_row, right_valid
                       ) -> List[ColV]:
    """Materialize output columns from gather indices; a False side-valid bit
    nulls out that side's columns (outer padding)."""
    out: List[ColV] = []
    for v in l_cols:
        g = bk.take_colv(xp, v, left_row)
        out.append(g.with_validity(xp.logical_and(g.validity, left_valid)))
    for v in r_cols:
        g = bk.take_colv(xp, v, right_row)
        out.append(g.with_validity(xp.logical_and(g.validity, right_valid)))
    return out


def _searchsorted_right(xp, a, v):
    """searchsorted(side='right') that lowers well on TPU: the default
    binary-search lowering measured 7.1 s for 8.4M queries on this chip;
    method='sort' (one co-sort of a and v) is ~320 ms."""
    if xp is np:
        return np.searchsorted(a, v, side="right")
    return xp.searchsorted(a, v, side="right", method="sort")


