"""Sort-based group-by aggregation pipeline.

The TPU replacement for cuDF's hash groupby (reference: aggregate.scala:227
GpuHashAggregateExec -> Table.groupBy().aggregate()): keys are sorted (XLA's TPU
sort is excellent and shape-static), group boundaries become segment ids, and
aggregation buffers reduce via segment ops. The whole pipeline — key evaluation,
buffer projection, sort, boundary detection, reduction, final evaluation — traces
into ONE XLA program; group count is a traced scalar (row-count sidecar).

Used eagerly with numpy by the CPU engine and traced with jax.numpy by the TPU
exec, so both paths share one semantics definition.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.aggregates import AggregateFunction
from spark_rapids_tpu.exprs.core import ColV, EvalCtx
from spark_rapids_tpu.ops import batch_kernels as bk


def group_aggregate(xp, ctx: EvalCtx, key_exprs, agg_fns: Sequence[AggregateFunction],
                    num_rows, capacity: int, evaluate: bool = True,
                    grouping: str = "sort", extra_mask=None):
    """Full grouped aggregation over one batch.

    Returns (key_cols, result_cols, num_groups): reduced key columns, final
    aggregate result columns (one per agg fn), and the traced group count.
    With no keys, produces exactly one group (Spark's global aggregate,
    including the empty-input row).

    With ``evaluate=False`` this is the *Partial* mode of the reference's
    GpuHashAggregateExec (aggregate.scala modes Partial/Final): result_cols are
    the reduced aggregation BUFFERS (flattened across fns) rather than final
    values, ready for ``merge_aggregate`` after an exchange/all-gather.

    ``grouping="hash"`` orders rows by a 64-bit key hash (one argsort) instead
    of the exact multi-key lexsort and returns a 4th value: a traced collision
    flag. When it is True two distinct keys shared a hash and the result may
    have split groups — the caller must re-run with grouping="sort".

    ``grouping="onehot"`` is the sort-free low-cardinality fast path (the
    scatter/one-hot segment-reduce the reference gets from cuDF's hash
    groupby, aggregate.scala:728): distinct key hashes are extracted with a
    bounded min-extraction loop (<= ONEHOT_CAP groups), group ids come from a
    searchsorted against that tiny table, and every reduction is a masked
    one-hot reduce — no sort, no scatter, ~20x the sort path on TPU for
    TPC-H Q1. Returns the same 4-tuple as "hash"; the collision flag also
    covers group-count overflow and is EXACT (per-group min/max equality of
    injective key words), so callers fall back to "hash"/"sort" on True.
    Requires keys and no string min/max buffers (see onehot_supported).

    ``extra_mask`` excludes rows (a fused upstream filter predicate): a masked
    row participates in no group, exactly as if it had been compacted away.
    """
    if grouping == "onehot" and not key_exprs:
        grouping = "hash"  # no-key aggregate: one group, nothing to one-hot

    alive = bk.alive_mask(xp, capacity, num_rows)
    if extra_mask is not None:
        alive = xp.logical_and(alive, extra_mask)

    # scalar keys/buffers (literals, e.g. after project inlining) broadcast to
    # full columns so the grouping kernels can index them
    keys = [bk.as_column(xp, e.eval(ctx), capacity) for e in key_exprs]
    # padding rows must not merge with null-key groups: mask handled via `alive`
    projections: List[List[ColV]] = []
    for fn in agg_fns:
        bufs = [bk.as_column(xp, b, capacity) for b in fn.project(ctx)]
        # padding rows never contribute
        projections.append([b.with_validity(xp.logical_and(b.validity, alive))
                            for b in bufs])

    if keys and grouping == "onehot":
        return _onehot_aggregate(xp, keys, projections, agg_fns, alive,
                                 capacity, evaluate)

    collision = xp.asarray(False)
    out_cap = capacity
    if keys:
        # ONE variadic sort carries every key and aggregation buffer with the
        # sort keys — no argsort + per-column gathers (a TPU gather costs
        # ~2x the sort itself; see bk.multi_sort)
        flat_projs = [b for bufs in projections for b in bufs]
        if grouping == "hash":
            h = bk.hash64_cols(xp, keys)
            hs = h >> np.uint64(1)
            # dead rows sort last: max uint64, unreachable by h >> 1
            passes = [xp.where(alive, hs,
                               np.uint64(0xFFFFFFFFFFFFFFFF))]
            extras = [alive, hs]
        else:
            passes = [xp.logical_not(alive).astype(np.int8)]
            for k in keys:
                passes.extend(bk._key_passes(xp, k, True, True))
            extras = [alive]
        sorted_all, sorted_extras = bk.sort_colvs(
            xp, passes, list(keys) + flat_projs, extras)
        sorted_keys = sorted_all[:len(keys)]
        sorted_alive = sorted_extras[0]
        starts = bk.starts_from_sorted(xp, sorted_keys, sorted_alive)
        if grouping == "hash":
            collision = bk.detect_hash_collision_sorted(
                xp, sorted_extras[1], starts, sorted_alive)
        gids = xp.cumsum(starts.astype(np.int32)) - 1
        gids = xp.clip(gids, 0, capacity - 1)
        num_groups = xp.sum(starts).astype(np.int32)
        sorted_projs = []
        i = len(keys)
        for bufs in projections:
            sorted_projs.append(sorted_all[i:i + len(bufs)])
            i += len(bufs)
    else:
        gids = xp.zeros(capacity, dtype=np.int32)
        num_groups = xp.asarray(np.int32(1))
        sorted_alive = alive
        sorted_keys = []
        sorted_projs = projections

    if keys and grouping == "hash":
        # bounded group space: boundary-scan reduction emits GROUP_CAP-sized
        # outputs; more groups than that re-runs through the exact sort path
        # (flagged exactly like a hash collision)
        out_cap = min(capacity, GROUP_CAP)
        collision = xp.logical_or(collision, num_groups > out_cap)
        key_cols, reduced_per_fn = _reduce_phase_scan(
            xp, sorted_keys, list(zip(agg_fns, sorted_projs)), gids,
            num_groups, capacity, out_cap, sorted_alive)
    else:
        key_cols, reduced_per_fn = _reduce_phase(
            xp, sorted_keys, list(zip(agg_fns, sorted_projs)), gids, capacity,
            sorted_alive)

    group_alive = xp.arange(out_cap, dtype=np.int32) < num_groups
    result_cols = []
    for fn, reduced in zip(agg_fns, reduced_per_fn):
        if evaluate:
            out = fn.evaluate(xp, reduced)
            result_cols.append(out.with_validity(
                xp.logical_and(out.validity, group_alive)))
        else:
            result_cols.extend(
                b.with_validity(xp.logical_and(b.validity, group_alive))
                for b in reduced)

    key_cols = [k.with_validity(xp.logical_and(k.validity, group_alive))
                for k in key_cols]
    if grouping == "hash":
        return key_cols, result_cols, num_groups, collision
    return key_cols, result_cols, num_groups


#: static group-space bound of the boundary-scan reduction; queries producing
#: more groups re-run through the exact sort path
GROUP_CAP = 65536

#: static group-space bound of the one-hot fast path; more groups than this
#: flips the collision/overflow flag and the caller re-runs with "hash"
ONEHOT_CAP = 64

_U64MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


def onehot_supported(agg_fns: Sequence[AggregateFunction]) -> bool:
    """The one-hot path covers every reduction except string min/max (those
    need the rank sort the path exists to avoid)."""
    for fn in agg_fns:
        for spec in fn.buffer_specs():
            if spec.dtype is DType.STRING and spec.kind in ("min", "max"):
                return False
    return True


def onehot_keys_supported(keys) -> bool:
    """validity_word packs one bit per key column into a u64; beyond that the
    exact null-vs-zero-encoding check would lose coverage."""
    return 0 < len(keys) <= 64


def grouping_modes(keys, agg_fns: Sequence[AggregateFunction]) -> List[str]:
    """Escalation order for an aggregate exec: each mode re-runs only on the
    previous one's flagged collision/overflow. The single policy for both the
    single-device and the mesh aggregate."""
    modes = []
    if onehot_keys_supported(keys) and onehot_supported(agg_fns):
        modes.append("onehot")
    return modes + ["hash", "sort"]


def _onehot_aggregate(xp, keys, projections, agg_fns, alive, capacity: int,
                      evaluate: bool):
    """Sort-free grouped aggregation over <= ONEHOT_CAP groups.

    hash -> bounded distinct extraction -> searchsorted gid -> masked one-hot
    reductions. All group-id plumbing is 32/64-bit elementwise + [n, G]
    reduces, which XLA fuses into a handful of HBM passes; there is no sort
    and no scatter anywhere. Collision exactness: per group, every injective
    key word (bk.key_words) must be constant — checked with masked min/max
    reduces — so a collided or overflowed run is ALWAYS flagged.
    """
    G = ONEHOT_CAP
    h = bk.hash64_cols(xp, keys)
    # reserve the all-ones value for dead rows (a real hash there would make
    # its group indistinguishable from padding; the clamp maps it onto
    # MAX-1, and if that collides with a genuine MAX-1 group the exact word
    # check below flags it)
    h = xp.minimum(h, _U64MAX - np.uint64(1))
    hm = xp.where(alive, h, _U64MAX)

    if xp is np:
        cand = np.unique(hm)
        overflow = np.asarray(cand[cand != _U64MAX].shape[0] > G)
        cand = np.concatenate([cand[:G],
                               np.full(max(0, G - cand.shape[0]), _U64MAX,
                                       dtype=np.uint64)])
    else:
        import jax

        def body(i, st):
            cand, prev, first = st
            nxt = xp.min(xp.where(xp.logical_or(first, hm > prev), hm,
                                  _U64MAX))
            return cand.at[i].set(nxt), nxt, xp.zeros((), bool)

        cand0 = xp.full((G,), _U64MAX)
        cand, _, _ = jax.lax.fori_loop(
            0, G, body, (cand0, np.uint64(0), xp.ones((), bool)))
        # dead rows carry hm == MAX and are NOT an overflow; a real hash can
        # never be MAX (clamped above)
        overflow = xp.logical_and(
            cand[G - 1] != _U64MAX,
            xp.any(xp.logical_and(hm > cand[G - 1], hm != _U64MAX)))
    num_groups = xp.sum(cand != _U64MAX).astype(np.int32)

    gid = xp.clip(xp.searchsorted(cand, hm), 0, G - 1).astype(np.int32)
    E = xp.logical_and(gid[:, None] == xp.arange(G, dtype=np.int32)[None, :],
                       alive[:, None])
    idx = xp.arange(capacity, dtype=np.int64)

    def masked_min(contrib, neutral):
        return xp.min(xp.where(E, contrib[:, None], neutral), axis=0)

    def masked_max(contrib, neutral):
        return xp.max(xp.where(E, contrib[:, None], neutral), axis=0)

    def masked_sum(contrib):
        return xp.sum(xp.where(E, contrib[:, None], 0), axis=0)

    # exact collision detection over injective key words + packed validity
    words = [bk.validity_word(xp, keys)]
    for v in keys:
        words.extend(bk.key_words(xp, v))
    collision = overflow
    for w in words:
        wmin = masked_min(w, _U64MAX)
        wmax = masked_max(w, np.uint64(0))
        bad = xp.logical_and(wmin != _U64MAX, wmin != wmax)
        collision = xp.logical_or(collision, xp.any(bad))

    # representative row per group -> key output (G tiny gathers)
    rep = masked_min(idx, np.int64(capacity))
    has = rep < capacity
    repc = xp.clip(rep, 0, capacity - 1)
    group_alive = xp.arange(G, dtype=np.int32) < num_groups
    key_cols = []
    for v in keys:
        kv = bk.take_colv(xp, v, repc)
        key_cols.append(kv.with_validity(
            xp.logical_and(kv.validity, xp.logical_and(has, group_alive))))

    result_cols = []
    for fn, bufs in zip(agg_fns, projections):
        reduced = []
        for spec, b in zip(fn.buffer_specs(), bufs):
            reduced.append(_onehot_reduce_buffer(
                xp, spec, b, E, idx, capacity, masked_min, masked_max,
                masked_sum))
        if evaluate:
            out = fn.evaluate(xp, reduced)
            result_cols.append(out.with_validity(
                xp.logical_and(out.validity, group_alive)))
        else:
            result_cols.extend(
                r.with_validity(xp.logical_and(r.validity, group_alive))
                for r in reduced)
    return key_cols, result_cols, num_groups, collision


def _onehot_reduce_buffer(xp, spec, b: ColV, E, idx, capacity: int,
                          masked_min, masked_max, masked_sum):
    """One buffer's one-hot reduction (sum/min/max/first/last with Spark
    null + NaN semantics, mirroring _register_minmax / segment_pick)."""
    Ev = xp.logical_and(E, b.validity[:, None])
    seg_valid = xp.any(Ev, axis=0)
    if spec.kind == "sum":
        contrib = xp.where(b.validity, b.data, 0).astype(b.data.dtype)
        return ColV(b.dtype, masked_sum(contrib), seg_valid)
    if spec.kind in ("first", "last"):
        candidate = Ev if spec.ignore_nulls else E
        if spec.kind == "first":
            key = xp.min(xp.where(candidate, idx[:, None],
                                  np.int64(capacity)), axis=0)
            pick_has = key < capacity
        else:
            key = xp.max(xp.where(candidate, idx[:, None], np.int64(-1)),
                         axis=0)
            pick_has = key >= 0
        pick = xp.clip(key, 0, capacity - 1)
        out = bk.take_colv(xp, b, pick)
        return out.with_validity(xp.logical_and(pick_has, out.validity))
    # numeric/bool min-max
    npdt = np.dtype(b.data.dtype)
    if npdt == np.bool_:
        d = b.data.astype(np.int8)
        neutral = np.int8(1 if spec.kind == "min" else 0)
        m = masked_min if spec.kind == "min" else masked_max
        return ColV(b.dtype,
                    m(xp.where(b.validity, d, neutral),
                      neutral).astype(np.bool_), seg_valid)
    if np.issubdtype(npdt, np.floating):
        neutral = np.asarray(np.inf if spec.kind == "min" else -np.inf,
                             dtype=npdt)
        nan = xp.isnan(b.data)
        d = xp.where(nan, xp.asarray(np.inf, dtype=npdt), b.data)
        m = masked_min if spec.kind == "min" else masked_max
        res = m(xp.where(b.validity, d, neutral), neutral)
        saw_nan = xp.any(xp.logical_and(Ev, nan[:, None]), axis=0)
        all_nan = xp.logical_not(
            xp.any(xp.logical_and(Ev, xp.logical_not(nan)[:, None]), axis=0))
        if spec.kind == "max":
            res = xp.where(saw_nan, xp.asarray(np.nan, dtype=npdt), res)
        else:
            res = xp.where(xp.logical_and(seg_valid, all_nan),
                           xp.asarray(np.nan, dtype=npdt), res)
        return ColV(b.dtype, res, seg_valid)
    neutral = (np.iinfo(npdt).max if spec.kind == "min"
               else np.iinfo(npdt).min)
    m = masked_min if spec.kind == "min" else masked_max
    return ColV(b.dtype, m(xp.where(b.validity, b.data, neutral), neutral),
                seg_valid)


def _reduce_phase_scan(xp, sorted_keys, fn_bufs, gids, num_groups,
                       capacity: int, out_cap: int, sorted_alive):
    """Boundary-scan reduction over hash-ordered rows.

    TPU scatters cost ~100ns/row regardless of the segment space, while
    cumsum and gathers run at memory bandwidth. With rows sorted by group,
    INTEGER sums/counts reduce as cumsum differences at the group boundaries
    (found with two searchsorted calls over the non-decreasing gids —
    wrapping int arithmetic keeps them exact through any cumsum overflow) and
    first/last/keys are single gathers at the boundary rows. FLOAT sums must
    not use cumsum differences: the accumulator mixes other groups' values,
    so a group that cancels to exactly 0.0 picks up an epsilon residue and
    flips predicates like `HAVING sum(x) > 0` — they go through the stacked
    scatter instead (one scatter per dtype, shared with min/max)."""
    g = xp.arange(out_cap, dtype=np.int32)
    start_pos = xp.searchsorted(gids, g, side="left")
    end_pos = xp.searchsorted(gids, g, side="right") - 1
    # dead rows keep the final gid: clamp the last group's end to alive rows
    n_alive = xp.sum(sorted_alive).astype(np.int32)
    end_pos = xp.minimum(end_pos, xp.maximum(n_alive - 1, 0))
    has = g < num_groups
    start_c = xp.clip(start_pos, 0, capacity - 1).astype(np.int32)
    end_c = xp.clip(end_pos, 0, capacity - 1).astype(np.int32)
    gids_b = xp.minimum(gids, np.int32(out_cap - 1))

    key_cols = [_gather_key(xp, k, start_c, has) for k in sorted_keys]

    def seg_sum(contrib):
        c = xp.cumsum(contrib)
        tail = c[end_c]
        head = xp.where(start_c > 0, c[xp.clip(start_c - 1, 0, capacity - 1)],
                        xp.zeros_like(tail))
        return tail - head

    stacker = (bk.SortedSegmentStacker(xp, gids_b, out_cap) if xp is not np
               else None)
    idx64 = xp.arange(capacity, dtype=np.int64)
    thunk_lists = []
    for fn, bufs in fn_bufs:
        thunks = []
        for spec, b in zip(fn.buffer_specs(), bufs):
            if b.dtype is DType.STRING and spec.kind in ("min", "max"):
                if stacker is not None:
                    thunks.append(_register_minmax_string(
                        xp, b, spec.kind, stacker, sorted_alive))
                else:
                    thunks.append(lambda b=b, spec=spec:
                                  _segment_minmax_string(
                                      xp, b, gids_b, out_cap, spec.kind,
                                      sorted_alive))
            elif spec.kind in ("first", "last") and spec.ignore_nulls:
                if stacker is not None:
                    thunks.append(_register_pick(
                        xp, b, spec.kind, stacker, idx64, capacity,
                        xp.logical_and(sorted_alive, b.validity)))
                else:
                    def pick(b=b, spec=spec):
                        p2, h2 = bk.segment_pick(xp, b.validity, gids_b,
                                                 out_cap, spec.kind,
                                                 alive=sorted_alive,
                                                 ignore_nulls=True)
                        valid = xp.logical_and(h2, b.validity[p2])
                        return bk.take_colv(xp, b, p2).with_validity(valid)
                    thunks.append(pick)
            elif spec.kind in ("first", "last"):
                pos = start_c if spec.kind == "first" else end_c
                thunks.append(lambda b=b, pos=pos: bk.take_colv(xp, b, pos)
                              .with_validity(xp.logical_and(has,
                                                            b.validity[pos])))
            elif spec.kind == "sum" and not np.issubdtype(
                    np.dtype(b.data.dtype), np.floating):
                def int_sum(b=b):
                    contrib = xp.where(b.validity, b.data,
                                       0).astype(b.data.dtype)
                    s = seg_sum(contrib)
                    cnt = seg_sum(b.validity.astype(np.int32))
                    return ColV(b.dtype, s, cnt > 0)
                thunks.append(int_sum)
            elif spec.kind == "sum":  # float: scatter, stacked on device
                if stacker is not None:
                    contrib = xp.where(b.validity, b.data,
                                       0).astype(b.data.dtype)
                    h = stacker.add("sum", contrib)
                    hc = stacker.add("sum", b.validity.astype(np.int32))
                    thunks.append(lambda b=b, h=h, hc=hc: ColV(
                        b.dtype, stacker.get(h), stacker.get(hc) > 0))
                else:
                    def np_sum(b=b):
                        data, valid = bk.segment_reduce(
                            xp, b.data, b.validity, gids_b, out_cap, "sum")
                        return ColV(b.dtype, data, valid)
                    thunks.append(np_sum)
            else:  # numeric/bool min-max
                if stacker is not None:
                    thunks.append(_register_minmax(xp, b, spec.kind, stacker))
                else:
                    def np_mm(b=b, spec=spec):
                        data, valid = bk.segment_reduce(
                            xp, b.data, b.validity, gids_b, out_cap,
                            spec.kind)
                        return ColV(b.dtype, data, valid)
                    thunks.append(np_mm)
        thunk_lists.append(thunks)
    if stacker is not None and stacker._buckets:
        stacker.run()
    reduced = [[t() for t in thunks] for thunks in thunk_lists]
    return key_cols, reduced


def _reduce_phase(xp, sorted_keys, fn_bufs, gids, capacity: int, sorted_alive):
    """Representative-key pick + per-fn buffer reduction.

    numpy path: eager per-buffer segment ops. Device path: every segment
    contribution — the key pick's index min and each buffer's reduction —
    registers with ONE SegmentStacker, so all reductions of a kind/dtype run
    as a single stacked scatter."""
    if xp is np:
        pick, has = bk.segment_pick(xp, xp.ones_like(sorted_alive), gids,
                                    capacity, "first", alive=sorted_alive)
        key_cols = [_gather_key(xp, k, pick, has) for k in sorted_keys]
        reduced = [_reduce_buffers(xp, fn, bufs, gids, capacity, sorted_alive)
                   for fn, bufs in fn_bufs]
        return key_cols, reduced

    stacker = bk.SortedSegmentStacker(xp, gids, capacity)
    idx = xp.arange(capacity, dtype=np.int64)
    hpick = stacker.add("min", xp.where(sorted_alive, idx,
                                        np.int64(capacity + 1)))
    thunk_lists = [_register_reduce(xp, fn, bufs, gids, capacity,
                                    sorted_alive, stacker)
                   for fn, bufs in fn_bufs]
    stacker.run()
    key = stacker.get(hpick)
    has = key < capacity
    pick = xp.clip(key, 0, capacity - 1)
    key_cols = [_gather_key(xp, k, pick, has) for k in sorted_keys]
    reduced = [[t() for t in thunks] for thunks in thunk_lists]
    return key_cols, reduced


def _gather_key(xp, k: ColV, pick, has) -> ColV:
    valid = xp.logical_and(has, k.validity[pick])
    if k.dtype is DType.STRING:
        return ColV(k.dtype, k.data[pick], valid, k.lengths[pick])
    return ColV(k.dtype, k.data[pick], valid)


def _string_rank(xp, b: ColV, kind: str, sorted_alive):
    """Shared preamble of string min/max: rank rows by byte order (the sort is
    unavoidable — strings don't reduce), sentinel-mask non-participants.
    Returns (order, masked_rank, n)."""
    participating = xp.logical_and(sorted_alive, b.validity)
    order = bk.sort_indices(xp, [(b, True, True)], participating)
    # inverse permutation = rank of each row in sorted order
    rank = bk._stable_argsort(xp, order).astype(np.int64)
    n = rank.shape[0]
    sentinel = np.int64(n + 1) if kind == "min" else np.int64(-1)
    return order, xp.where(participating, rank, sentinel), n


def _string_pick(xp, b: ColV, order, seg, n: int) -> ColV:
    """Shared tail of string min/max: reduced per-segment rank -> row pick.
    Both sentinels (n+1 for min, -1 for max) fail the bounds check."""
    has = xp.logical_and(seg >= 0, seg <= n)
    pick = order[xp.clip(seg, 0, n - 1)]
    valid = xp.logical_and(has, b.validity[pick])
    return ColV(b.dtype, b.data[pick], valid, b.lengths[pick])


def _segment_minmax_string(xp, b: ColV, gids, capacity: int, kind: str,
                           sorted_alive) -> ColV:
    """min/max over strings, eager reduction (cuDF's string minmax analog,
    built from the existing sort + segment machinery)."""
    order, masked, n = _string_rank(xp, b, kind, sorted_alive)
    seg = bk.segment_reduce(xp, masked, xp.ones(n, dtype=bool), gids,
                            capacity, kind)[0]
    return _string_pick(xp, b, order, seg, n)


def _reduce_buffers(xp, fn: AggregateFunction, bufs: Sequence[ColV], gids,
                    capacity: int, sorted_alive) -> List[ColV]:
    reduced: List[ColV] = []
    for spec, b in zip(fn.buffer_specs(), bufs):
        if b.dtype is DType.STRING and spec.kind in ("min", "max"):
            reduced.append(_segment_minmax_string(xp, b, gids, capacity,
                                                  spec.kind, sorted_alive))
        elif spec.kind in ("first", "last"):
            p2, h2 = bk.segment_pick(xp, b.validity, gids, capacity,
                                     spec.kind, alive=sorted_alive,
                                     ignore_nulls=spec.ignore_nulls)
            valid = xp.logical_and(h2, b.validity[p2])
            if b.dtype is DType.STRING:
                reduced.append(ColV(b.dtype, b.data[p2], valid, b.lengths[p2]))
            else:
                reduced.append(ColV(b.dtype, b.data[p2], valid))
        else:
            data, valid = bk.segment_reduce(xp, b.data, b.validity, gids,
                                            capacity, spec.kind)
            reduced.append(ColV(b.dtype, data, valid))
    return reduced


def _register_reduce(xp, fn: AggregateFunction, bufs: Sequence[ColV], gids,
                     capacity: int, sorted_alive, stacker: "bk.SegmentStacker"):
    """Device-path reduction, phase 1: register every segment contribution
    with the stacker; returns a thunk producing the reduced ColVs after
    stacker.run(). One stacked scatter per (kind, dtype) replaces the
    per-buffer segment calls of _reduce_buffers."""
    idx = xp.arange(capacity, dtype=np.int64)
    thunks = []
    for spec, b in zip(fn.buffer_specs(), bufs):
        if b.dtype is DType.STRING and spec.kind in ("min", "max"):
            thunks.append(_register_minmax_string(xp, b, spec.kind, stacker,
                                                  sorted_alive))
        elif spec.kind in ("first", "last"):
            candidate = (xp.logical_and(sorted_alive, b.validity)
                         if spec.ignore_nulls else sorted_alive)
            thunks.append(_register_pick(xp, b, spec.kind, stacker, idx,
                                         capacity, candidate))
        elif spec.kind == "sum":
            contrib = xp.where(b.validity, b.data, 0).astype(b.data.dtype)
            h = stacker.add("sum", contrib)
            hc = stacker.add("sum", b.validity.astype(np.int32))
            thunks.append(lambda b=b, h=h, hc=hc: ColV(
                b.dtype, stacker.get(h), stacker.get(hc) > 0))
        else:  # numeric min/max
            thunks.append(_register_minmax(xp, b, spec.kind, stacker))
    return thunks


def _register_pick(xp, b: ColV, kind: str, stacker: "bk.SegmentStacker",
                   idx, capacity: int, candidate):
    """first/last pick through the stacker: masked row-index min/max, then a
    tiny gather — replaces the full-row segment_pick scatter."""
    if kind == "first":
        h = stacker.add("min", xp.where(candidate, idx,
                                        np.int64(capacity + 1)))
    else:
        h = stacker.add("max", xp.where(candidate, idx, np.int64(-1)))

    def thunk(b=b, h=h):
        key = stacker.get(h)
        has = xp.logical_and(key >= 0, key < capacity)
        p2 = xp.clip(key, 0, capacity - 1)
        valid = xp.logical_and(has, b.validity[p2])
        return bk.take_colv(xp, b, p2).with_validity(valid)
    return thunk


def _register_minmax_string(xp, b: ColV, kind: str,
                            stacker: "bk.SegmentStacker", sorted_alive):
    """String min/max through the stacker: the per-segment lowest/highest-
    ranked pick rides the stacked int reduction instead of a full-row
    scatter."""
    order, masked, n = _string_rank(xp, b, kind, sorted_alive)
    h = stacker.add(kind, masked)
    return lambda: _string_pick(xp, b, order, stacker.get(h), n)


def _register_minmax(xp, b: ColV, kind: str, stacker: "bk.SegmentStacker"):
    """Stacked numeric/bool min-max with Spark NaN ordering (mirrors
    bk._segment_minmax_jax semantics)."""
    hc = stacker.add("sum", b.validity.astype(np.int32))
    npdt = np.dtype(b.data.dtype)
    if npdt == np.bool_:
        d = b.data.astype(np.int8)
        neutral = np.int8(1 if kind == "min" else 0)
        h = stacker.add(kind, xp.where(b.validity, d, neutral))
        return lambda: ColV(b.dtype, stacker.get(h).astype(np.bool_),
                            stacker.get(hc) > 0)
    if np.issubdtype(npdt, np.floating):
        neutral = np.asarray(np.inf if kind == "min" else -np.inf, dtype=npdt)
        nan = xp.isnan(b.data)
        d = xp.where(nan, xp.asarray(np.inf, dtype=npdt), b.data)
        h = stacker.add(kind, xp.where(b.validity, d, neutral))
        hn = stacker.add("sum",
                         xp.logical_and(nan, b.validity).astype(np.int32))

        def thunk():
            res = stacker.get(h)
            nan_count = stacker.get(hn)
            valid_count = stacker.get(hc)
            if kind == "max":
                res = xp.where(nan_count > 0,
                               xp.asarray(np.nan, dtype=npdt), res)
            else:
                res = xp.where(xp.logical_and(valid_count > 0,
                                              nan_count == valid_count),
                               xp.asarray(np.nan, dtype=npdt), res)
            return ColV(b.dtype, res, valid_count > 0)
        return thunk
    neutral = (np.iinfo(npdt).max if kind == "min" else np.iinfo(npdt).min)
    h = stacker.add(kind, xp.where(b.validity, b.data, neutral))
    return lambda: ColV(b.dtype, stacker.get(h), stacker.get(hc) > 0)


def merge_aggregate(xp, key_cols: Sequence[ColV], buffer_cols: Sequence[ColV],
                    agg_fns: Sequence[AggregateFunction], num_rows, capacity: int):
    """Final mode: merge partially-aggregated buffers (after an exchange or
    all-gather) — group by keys again, combine each buffer with its own
    reduction kind (sum-of-sums, min-of-mins, first-of-firsts...), then run each
    aggregate's evaluate() (aggregate.scala Final/PartialMerge analog).

    buffer_cols: the flattened partial buffers as produced by
    group_aggregate(evaluate=False). Returns (key_cols, result_cols, num_groups).
    Always uses the exact sort ordering: inputs here are already-reduced
    partials (tiny), so the hash fast path has nothing to win.
    """
    alive = bk.alive_mask(xp, capacity, num_rows)
    key_cols = [k.with_validity(xp.logical_and(k.validity, alive))
                for k in key_cols]
    buffer_cols = [b.with_validity(xp.logical_and(b.validity, alive))
                   for b in buffer_cols]

    if key_cols:
        passes = [xp.logical_not(alive).astype(np.int8)]
        for k in key_cols:
            passes.extend(bk._key_passes(xp, k, True, True))
        sorted_all, sorted_extras = bk.sort_colvs(
            xp, passes, list(key_cols) + list(buffer_cols), [alive])
        sorted_keys = sorted_all[:len(key_cols)]
        sorted_bufs = sorted_all[len(key_cols):]
        sorted_alive = sorted_extras[0]
        starts = bk.starts_from_sorted(xp, sorted_keys, sorted_alive)
        gids = xp.clip(xp.cumsum(starts.astype(np.int32)) - 1, 0, capacity - 1)
        num_groups = xp.sum(starts).astype(np.int32)
    else:
        gids = xp.zeros(capacity, dtype=np.int32)
        num_groups = xp.asarray(np.int32(1))
        sorted_alive = alive
        sorted_keys = []
        sorted_bufs = list(buffer_cols)

    fn_bufs = []
    i = 0
    for fn in agg_fns:
        specs = fn.buffer_specs()
        fn_bufs.append((fn, sorted_bufs[i:i + len(specs)]))
        i += len(specs)
    out_keys, reduced_per_fn = _reduce_phase(xp, sorted_keys, fn_bufs, gids,
                                             capacity, sorted_alive)

    group_alive = xp.arange(capacity, dtype=np.int32) < num_groups
    result_cols = []
    for fn, reduced in zip(agg_fns, reduced_per_fn):
        out = fn.evaluate(xp, reduced)
        result_cols.append(out.with_validity(
            xp.logical_and(out.validity, group_alive)))

    out_keys = [k.with_validity(xp.logical_and(k.validity, group_alive))
                for k in out_keys]
    return out_keys, result_cols, num_groups
