"""Sort-based group-by aggregation pipeline.

The TPU replacement for cuDF's hash groupby (reference: aggregate.scala:227
GpuHashAggregateExec -> Table.groupBy().aggregate()): keys are sorted (XLA's TPU
sort is excellent and shape-static), group boundaries become segment ids, and
aggregation buffers reduce via segment ops. The whole pipeline — key evaluation,
buffer projection, sort, boundary detection, reduction, final evaluation — traces
into ONE XLA program; group count is a traced scalar (row-count sidecar).

Used eagerly with numpy by the CPU engine and traced with jax.numpy by the TPU
exec, so both paths share one semantics definition.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.aggregates import AggregateFunction
from spark_rapids_tpu.exprs.core import ColV, EvalCtx
from spark_rapids_tpu.ops import batch_kernels as bk


def group_aggregate(xp, ctx: EvalCtx, key_exprs, agg_fns: Sequence[AggregateFunction],
                    num_rows, capacity: int, evaluate: bool = True):
    """Full grouped aggregation over one batch.

    Returns (key_cols, result_cols, num_groups): reduced key columns, final
    aggregate result columns (one per agg fn), and the traced group count.
    With no keys, produces exactly one group (Spark's global aggregate,
    including the empty-input row).

    With ``evaluate=False`` this is the *Partial* mode of the reference's
    GpuHashAggregateExec (aggregate.scala modes Partial/Final): result_cols are
    the reduced aggregation BUFFERS (flattened across fns) rather than final
    values, ready for ``merge_aggregate`` after an exchange/all-gather.
    """
    alive = bk.alive_mask(xp, capacity, num_rows)

    keys = [e.eval(ctx) for e in key_exprs]
    # padding rows must not merge with null-key groups: mask handled via `alive`
    projections: List[List[ColV]] = []
    for fn in agg_fns:
        bufs = fn.project(ctx)
        # padding rows never contribute
        projections.append([b.with_validity(xp.logical_and(b.validity, alive))
                            for b in bufs])

    if keys:
        order = bk.sort_indices(xp, [(k, True, True) for k in keys], alive)
        starts = bk.rows_equal_adjacent(xp, keys, order, alive)
        gids = xp.cumsum(starts.astype(np.int32)) - 1
        gids = xp.clip(gids, 0, capacity - 1)
        num_groups = xp.sum(starts).astype(np.int32)
        sorted_alive = alive[order]
        flat_projs = [b for bufs in projections for b in bufs]
        taken = bk.take_columns(xp, list(keys) + flat_projs, order)
        sorted_keys = taken[:len(keys)]
        sorted_projs = []
        i = len(keys)
        for bufs in projections:
            sorted_projs.append(taken[i:i + len(bufs)])
            i += len(bufs)
    else:
        order = xp.arange(capacity, dtype=np.int32)
        gids = xp.zeros(capacity, dtype=np.int32)
        num_groups = xp.asarray(np.int32(1))
        sorted_alive = alive
        sorted_keys = []
        sorted_projs = projections

    key_cols, reduced_per_fn = _reduce_phase(
        xp, sorted_keys, list(zip(agg_fns, sorted_projs)), gids, capacity,
        sorted_alive)

    group_alive = xp.arange(capacity, dtype=np.int32) < num_groups
    result_cols = []
    for fn, reduced in zip(agg_fns, reduced_per_fn):
        if evaluate:
            out = fn.evaluate(xp, reduced)
            result_cols.append(out.with_validity(
                xp.logical_and(out.validity, group_alive)))
        else:
            result_cols.extend(
                b.with_validity(xp.logical_and(b.validity, group_alive))
                for b in reduced)

    key_cols = [k.with_validity(xp.logical_and(k.validity, group_alive))
                for k in key_cols]
    return key_cols, result_cols, num_groups


def _reduce_phase(xp, sorted_keys, fn_bufs, gids, capacity: int, sorted_alive):
    """Representative-key pick + per-fn buffer reduction.

    numpy path: eager per-buffer segment ops. Device path: every segment
    contribution — the key pick's index min and each buffer's reduction —
    registers with ONE SegmentStacker, so all reductions of a kind/dtype run
    as a single stacked scatter."""
    if xp is np:
        pick, has = bk.segment_pick(xp, xp.ones_like(sorted_alive), gids,
                                    capacity, "first", alive=sorted_alive)
        key_cols = [_gather_key(xp, k, pick, has) for k in sorted_keys]
        reduced = [_reduce_buffers(xp, fn, bufs, gids, capacity, sorted_alive)
                   for fn, bufs in fn_bufs]
        return key_cols, reduced

    stacker = bk.SegmentStacker(xp, gids, capacity)
    idx = xp.arange(capacity, dtype=np.int64)
    hpick = stacker.add("min", xp.where(sorted_alive, idx,
                                        np.int64(capacity + 1)))
    thunk_lists = [_register_reduce(xp, fn, bufs, gids, capacity,
                                    sorted_alive, stacker)
                   for fn, bufs in fn_bufs]
    stacker.run()
    key = stacker.get(hpick)
    has = key < capacity
    pick = xp.clip(key, 0, capacity - 1)
    key_cols = [_gather_key(xp, k, pick, has) for k in sorted_keys]
    reduced = [[t() for t in thunks] for thunks in thunk_lists]
    return key_cols, reduced


def _gather_key(xp, k: ColV, pick, has) -> ColV:
    valid = xp.logical_and(has, k.validity[pick])
    if k.dtype is DType.STRING:
        return ColV(k.dtype, k.data[pick], valid, k.lengths[pick])
    return ColV(k.dtype, k.data[pick], valid)


def _segment_minmax_string(xp, b: ColV, gids, capacity: int, kind: str,
                           sorted_alive) -> ColV:
    """min/max over device strings: rank rows by byte order once, then pick the
    lowest/highest-ranked participating row per segment (cuDF's string minmax
    analog, built from the existing sort + segment machinery)."""
    participating = xp.logical_and(sorted_alive, b.validity)
    order = bk.sort_indices(xp, [(b, True, True)], participating)
    # inverse permutation = rank of each row in sorted order
    rank = bk._stable_argsort(xp, order).astype(np.int64)
    n = rank.shape[0]
    if kind == "min":
        key = xp.where(participating, rank, np.int64(n + 1))
        seg = bk.segment_reduce(xp, key, xp.ones_like(participating), gids,
                                capacity, "min")[0]
        has = seg <= n
    else:
        key = xp.where(participating, rank, np.int64(-1))
        seg = bk.segment_reduce(xp, key, xp.ones_like(participating), gids,
                                capacity, "max")[0]
        has = seg >= 0
    pick = order[xp.clip(seg, 0, n - 1)]
    valid = xp.logical_and(has, b.validity[pick])
    return ColV(b.dtype, b.data[pick], valid, b.lengths[pick])


def _reduce_buffers(xp, fn: AggregateFunction, bufs: Sequence[ColV], gids,
                    capacity: int, sorted_alive) -> List[ColV]:
    reduced: List[ColV] = []
    for spec, b in zip(fn.buffer_specs(), bufs):
        if b.dtype is DType.STRING and spec.kind in ("min", "max"):
            reduced.append(_segment_minmax_string(xp, b, gids, capacity,
                                                  spec.kind, sorted_alive))
        elif spec.kind in ("first", "last"):
            p2, h2 = bk.segment_pick(xp, b.validity, gids, capacity,
                                     spec.kind, alive=sorted_alive,
                                     ignore_nulls=spec.ignore_nulls)
            valid = xp.logical_and(h2, b.validity[p2])
            if b.dtype is DType.STRING:
                reduced.append(ColV(b.dtype, b.data[p2], valid, b.lengths[p2]))
            else:
                reduced.append(ColV(b.dtype, b.data[p2], valid))
        else:
            data, valid = bk.segment_reduce(xp, b.data, b.validity, gids,
                                            capacity, spec.kind)
            reduced.append(ColV(b.dtype, data, valid))
    return reduced


def _register_reduce(xp, fn: AggregateFunction, bufs: Sequence[ColV], gids,
                     capacity: int, sorted_alive, stacker: "bk.SegmentStacker"):
    """Device-path reduction, phase 1: register every segment contribution
    with the stacker; returns a thunk producing the reduced ColVs after
    stacker.run(). One stacked scatter per (kind, dtype) replaces the
    per-buffer segment calls of _reduce_buffers."""
    idx = xp.arange(capacity, dtype=np.int64)
    thunks = []
    for spec, b in zip(fn.buffer_specs(), bufs):
        if b.dtype is DType.STRING and spec.kind in ("min", "max"):
            # rare path; the rank sort dominates it anyway
            thunks.append(lambda b=b, spec=spec: _segment_minmax_string(
                xp, b, gids, capacity, spec.kind, sorted_alive))
        elif spec.kind in ("first", "last"):
            candidate = (xp.logical_and(sorted_alive, b.validity)
                         if spec.ignore_nulls else sorted_alive)
            if spec.kind == "first":
                h = stacker.add("min", xp.where(candidate, idx,
                                                np.int64(capacity + 1)))
            else:
                h = stacker.add("max", xp.where(candidate, idx, np.int64(-1)))

            def pick_thunk(b=b, h=h):
                key = stacker.get(h)
                has = xp.logical_and(key >= 0, key < capacity)
                p2 = xp.clip(key, 0, capacity - 1)
                valid = xp.logical_and(has, b.validity[p2])
                if b.dtype is DType.STRING:
                    return ColV(b.dtype, b.data[p2], valid, b.lengths[p2])
                return ColV(b.dtype, b.data[p2], valid)
            thunks.append(pick_thunk)
        elif spec.kind == "sum":
            contrib = xp.where(b.validity, b.data, 0).astype(b.data.dtype)
            h = stacker.add("sum", contrib)
            hc = stacker.add("sum", b.validity.astype(np.int32))
            thunks.append(lambda b=b, h=h, hc=hc: ColV(
                b.dtype, stacker.get(h), stacker.get(hc) > 0))
        else:  # numeric min/max
            thunks.append(_register_minmax(xp, b, spec.kind, stacker))
    return thunks


def _register_minmax(xp, b: ColV, kind: str, stacker: "bk.SegmentStacker"):
    """Stacked numeric/bool min-max with Spark NaN ordering (mirrors
    bk._segment_minmax_jax semantics)."""
    hc = stacker.add("sum", b.validity.astype(np.int32))
    npdt = np.dtype(b.data.dtype)
    if npdt == np.bool_:
        d = b.data.astype(np.int8)
        neutral = np.int8(1 if kind == "min" else 0)
        h = stacker.add(kind, xp.where(b.validity, d, neutral))
        return lambda: ColV(b.dtype, stacker.get(h).astype(np.bool_),
                            stacker.get(hc) > 0)
    if np.issubdtype(npdt, np.floating):
        neutral = np.asarray(np.inf if kind == "min" else -np.inf, dtype=npdt)
        nan = xp.isnan(b.data)
        d = xp.where(nan, xp.asarray(np.inf, dtype=npdt), b.data)
        h = stacker.add(kind, xp.where(b.validity, d, neutral))
        hn = stacker.add("sum",
                         xp.logical_and(nan, b.validity).astype(np.int32))

        def thunk():
            res = stacker.get(h)
            nan_count = stacker.get(hn)
            valid_count = stacker.get(hc)
            if kind == "max":
                res = xp.where(nan_count > 0,
                               xp.asarray(np.nan, dtype=npdt), res)
            else:
                res = xp.where(xp.logical_and(valid_count > 0,
                                              nan_count == valid_count),
                               xp.asarray(np.nan, dtype=npdt), res)
            return ColV(b.dtype, res, valid_count > 0)
        return thunk
    neutral = (np.iinfo(npdt).max if kind == "min" else np.iinfo(npdt).min)
    h = stacker.add(kind, xp.where(b.validity, b.data, neutral))
    return lambda: ColV(b.dtype, stacker.get(h), stacker.get(hc) > 0)


def merge_aggregate(xp, key_cols: Sequence[ColV], buffer_cols: Sequence[ColV],
                    agg_fns: Sequence[AggregateFunction], num_rows, capacity: int):
    """Final mode: merge partially-aggregated buffers (after an exchange or
    all-gather) — group by keys again, combine each buffer with its own
    reduction kind (sum-of-sums, min-of-mins, first-of-firsts...), then run each
    aggregate's evaluate() (aggregate.scala Final/PartialMerge analog).

    buffer_cols: the flattened partial buffers as produced by
    group_aggregate(evaluate=False). Returns (key_cols, result_cols, num_groups).
    """
    alive = bk.alive_mask(xp, capacity, num_rows)
    key_cols = [k.with_validity(xp.logical_and(k.validity, alive))
                for k in key_cols]
    buffer_cols = [b.with_validity(xp.logical_and(b.validity, alive))
                   for b in buffer_cols]

    if key_cols:
        order = bk.sort_indices(xp, [(k, True, True) for k in key_cols], alive)
        starts = bk.rows_equal_adjacent(xp, key_cols, order, alive)
        gids = xp.clip(xp.cumsum(starts.astype(np.int32)) - 1, 0, capacity - 1)
        num_groups = xp.sum(starts).astype(np.int32)
        sorted_alive = alive[order]
        taken = bk.take_columns(xp, list(key_cols) + list(buffer_cols), order)
        sorted_keys = taken[:len(key_cols)]
        sorted_bufs = taken[len(key_cols):]
    else:
        gids = xp.zeros(capacity, dtype=np.int32)
        num_groups = xp.asarray(np.int32(1))
        sorted_alive = alive
        sorted_keys = []
        sorted_bufs = list(buffer_cols)

    fn_bufs = []
    i = 0
    for fn in agg_fns:
        specs = fn.buffer_specs()
        fn_bufs.append((fn, sorted_bufs[i:i + len(specs)]))
        i += len(specs)
    out_keys, reduced_per_fn = _reduce_phase(xp, sorted_keys, fn_bufs, gids,
                                             capacity, sorted_alive)

    group_alive = xp.arange(capacity, dtype=np.int32) < num_groups
    result_cols = []
    for fn, reduced in zip(agg_fns, reduced_per_fn):
        out = fn.evaluate(xp, reduced)
        result_cols.append(out.with_validity(
            xp.logical_and(out.validity, group_alive)))

    out_keys = [k.with_validity(xp.logical_and(k.validity, group_alive))
                for k in out_keys]
    return out_keys, result_cols, num_groups
