"""Sort-based group-by aggregation pipeline.

The TPU replacement for cuDF's hash groupby (reference: aggregate.scala:227
GpuHashAggregateExec -> Table.groupBy().aggregate()): keys are sorted (XLA's TPU
sort is excellent and shape-static), group boundaries become segment ids, and
aggregation buffers reduce via segment ops. The whole pipeline — key evaluation,
buffer projection, sort, boundary detection, reduction, final evaluation — traces
into ONE XLA program; group count is a traced scalar (row-count sidecar).

Used eagerly with numpy by the CPU engine and traced with jax.numpy by the TPU
exec, so both paths share one semantics definition.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.aggregates import AggregateFunction
from spark_rapids_tpu.exprs.core import ColV, EvalCtx
from spark_rapids_tpu.ops import batch_kernels as bk


def _take(xp, v: ColV, order) -> ColV:
    return bk.take_colv(xp, v, order)


def group_aggregate(xp, ctx: EvalCtx, key_exprs, agg_fns: Sequence[AggregateFunction],
                    num_rows, capacity: int, evaluate: bool = True):
    """Full grouped aggregation over one batch.

    Returns (key_cols, result_cols, num_groups): reduced key columns, final
    aggregate result columns (one per agg fn), and the traced group count.
    With no keys, produces exactly one group (Spark's global aggregate,
    including the empty-input row).

    With ``evaluate=False`` this is the *Partial* mode of the reference's
    GpuHashAggregateExec (aggregate.scala modes Partial/Final): result_cols are
    the reduced aggregation BUFFERS (flattened across fns) rather than final
    values, ready for ``merge_aggregate`` after an exchange/all-gather.
    """
    alive = bk.alive_mask(xp, capacity, num_rows)

    keys = [e.eval(ctx) for e in key_exprs]
    # padding rows must not merge with null-key groups: mask handled via `alive`
    projections: List[List[ColV]] = []
    for fn in agg_fns:
        bufs = fn.project(ctx)
        # padding rows never contribute
        projections.append([b.with_validity(xp.logical_and(b.validity, alive))
                            for b in bufs])

    if keys:
        order = bk.sort_indices(xp, [(k, True, True) for k in keys], alive)
        starts = bk.rows_equal_adjacent(xp, keys, order, alive)
        gids = xp.cumsum(starts.astype(np.int32)) - 1
        gids = xp.clip(gids, 0, capacity - 1)
        num_groups = xp.sum(starts).astype(np.int32)
        sorted_alive = alive[order]
        sorted_keys = [_take(xp, k, order) for k in keys]
        sorted_projs = [[_take(xp, b, order) for b in bufs]
                        for bufs in projections]
    else:
        order = xp.arange(capacity, dtype=np.int32)
        gids = xp.zeros(capacity, dtype=np.int32)
        num_groups = xp.asarray(np.int32(1))
        sorted_alive = alive
        sorted_keys = []
        sorted_projs = projections

    # ---- reduce keys: representative row per group -----------------------------
    pick, has = bk.segment_pick(xp, xp.ones_like(sorted_alive), gids, capacity,
                                "first", alive=sorted_alive)
    key_cols = []
    for k in sorted_keys:
        if k.dtype is DType.STRING:
            key_cols.append(ColV(k.dtype, k.data[pick],
                                 xp.logical_and(has, k.validity[pick]),
                                 k.lengths[pick]))
        else:
            key_cols.append(ColV(k.dtype, k.data[pick],
                                 xp.logical_and(has, k.validity[pick])))

    # ---- reduce buffers --------------------------------------------------------
    group_alive = xp.arange(capacity, dtype=np.int32) < num_groups
    result_cols = []
    for fn, bufs in zip(agg_fns, sorted_projs):
        reduced = _reduce_buffers(xp, fn, bufs, gids, capacity, sorted_alive)
        if evaluate:
            out = fn.evaluate(xp, reduced)
            result_cols.append(out.with_validity(
                xp.logical_and(out.validity, group_alive)))
        else:
            result_cols.extend(
                b.with_validity(xp.logical_and(b.validity, group_alive))
                for b in reduced)

    key_cols = [k.with_validity(xp.logical_and(k.validity, group_alive))
                for k in key_cols]
    return key_cols, result_cols, num_groups


def _segment_minmax_string(xp, b: ColV, gids, capacity: int, kind: str,
                           sorted_alive) -> ColV:
    """min/max over device strings: rank rows by byte order once, then pick the
    lowest/highest-ranked participating row per segment (cuDF's string minmax
    analog, built from the existing sort + segment machinery)."""
    participating = xp.logical_and(sorted_alive, b.validity)
    order = bk.sort_indices(xp, [(b, True, True)], participating)
    # inverse permutation = rank of each row in sorted order
    rank = bk._stable_argsort(xp, order).astype(np.int64)
    n = rank.shape[0]
    if kind == "min":
        key = xp.where(participating, rank, np.int64(n + 1))
        seg = bk.segment_reduce(xp, key, xp.ones_like(participating), gids,
                                capacity, "min")[0]
        has = seg <= n
    else:
        key = xp.where(participating, rank, np.int64(-1))
        seg = bk.segment_reduce(xp, key, xp.ones_like(participating), gids,
                                capacity, "max")[0]
        has = seg >= 0
    pick = order[xp.clip(seg, 0, n - 1)]
    valid = xp.logical_and(has, b.validity[pick])
    return ColV(b.dtype, b.data[pick], valid, b.lengths[pick])


def _reduce_buffers(xp, fn: AggregateFunction, bufs: Sequence[ColV], gids,
                    capacity: int, sorted_alive) -> List[ColV]:
    reduced: List[ColV] = []
    for spec, b in zip(fn.buffer_specs(), bufs):
        if b.dtype is DType.STRING and spec.kind in ("min", "max"):
            reduced.append(_segment_minmax_string(xp, b, gids, capacity,
                                                  spec.kind, sorted_alive))
        elif spec.kind in ("first", "last"):
            p2, h2 = bk.segment_pick(xp, b.validity, gids, capacity,
                                     spec.kind, alive=sorted_alive,
                                     ignore_nulls=spec.ignore_nulls)
            valid = xp.logical_and(h2, b.validity[p2])
            if b.dtype is DType.STRING:
                reduced.append(ColV(b.dtype, b.data[p2], valid, b.lengths[p2]))
            else:
                reduced.append(ColV(b.dtype, b.data[p2], valid))
        else:
            data, valid = bk.segment_reduce(xp, b.data, b.validity, gids,
                                            capacity, spec.kind)
            reduced.append(ColV(b.dtype, data, valid))
    return reduced


def merge_aggregate(xp, key_cols: Sequence[ColV], buffer_cols: Sequence[ColV],
                    agg_fns: Sequence[AggregateFunction], num_rows, capacity: int):
    """Final mode: merge partially-aggregated buffers (after an exchange or
    all-gather) — group by keys again, combine each buffer with its own
    reduction kind (sum-of-sums, min-of-mins, first-of-firsts...), then run each
    aggregate's evaluate() (aggregate.scala Final/PartialMerge analog).

    buffer_cols: the flattened partial buffers as produced by
    group_aggregate(evaluate=False). Returns (key_cols, result_cols, num_groups).
    """
    alive = bk.alive_mask(xp, capacity, num_rows)
    key_cols = [k.with_validity(xp.logical_and(k.validity, alive))
                for k in key_cols]
    buffer_cols = [b.with_validity(xp.logical_and(b.validity, alive))
                   for b in buffer_cols]

    if key_cols:
        order = bk.sort_indices(xp, [(k, True, True) for k in key_cols], alive)
        starts = bk.rows_equal_adjacent(xp, key_cols, order, alive)
        gids = xp.clip(xp.cumsum(starts.astype(np.int32)) - 1, 0, capacity - 1)
        num_groups = xp.sum(starts).astype(np.int32)
        sorted_alive = alive[order]
        sorted_keys = [_take(xp, k, order) for k in key_cols]
        sorted_bufs = [_take(xp, b, order) for b in buffer_cols]
    else:
        gids = xp.zeros(capacity, dtype=np.int32)
        num_groups = xp.asarray(np.int32(1))
        sorted_alive = alive
        sorted_keys = []
        sorted_bufs = list(buffer_cols)

    pick, has = bk.segment_pick(xp, xp.ones_like(sorted_alive), gids, capacity,
                                "first", alive=sorted_alive)
    out_keys = []
    for k in sorted_keys:
        if k.dtype is DType.STRING:
            out_keys.append(ColV(k.dtype, k.data[pick],
                                 xp.logical_and(has, k.validity[pick]),
                                 k.lengths[pick]))
        else:
            out_keys.append(ColV(k.dtype, k.data[pick],
                                 xp.logical_and(has, k.validity[pick])))

    group_alive = xp.arange(capacity, dtype=np.int32) < num_groups
    result_cols = []
    i = 0
    for fn in agg_fns:
        specs = fn.buffer_specs()
        bufs = sorted_bufs[i:i + len(specs)]
        i += len(specs)
        reduced = _reduce_buffers(xp, fn, bufs, gids, capacity, sorted_alive)
        out = fn.evaluate(xp, reduced)
        result_cols.append(out.with_validity(
            xp.logical_and(out.validity, group_alive)))

    out_keys = [k.with_validity(xp.logical_and(k.validity, group_alive))
                for k in out_keys]
    return out_keys, result_cols, num_groups
