"""Window-function kernels (reference: GpuWindowExec.scala + GpuWindowExpression.scala,
925 LoC — cuDF ``groupBy.aggregateWindows`` for row frames and
``aggregateWindowsOverTimeRanges`` for range frames).

TPU re-design: one sort by (partition keys, order keys) turns every window frame
into an index interval [lo, hi] in the sorted domain, computed with shape-static
vectorized machinery:

- ROWS frames: interval arithmetic on the row index clipped to partition bounds;
- RANGE frames: vectorized binary search over the (direction-normalized) order
  values, restricted to the partition — O(n log n), no data-dependent shapes;
- running / whole-partition frames fall out as special intervals.

Frame aggregation then rides two primitives that XLA fuses well:
- invertible reductions (sum / count / average buffers): exclusive prefix sums,
  frame result = p[hi+1] - p[lo];
- min / max: a sparse-table RMQ (log2(n) doubling levels, two gathers per query);
  strings reduce via a rank-then-RMQ trick reusing the byte-wise sort;
- first / last / lead / lag / ranking functions: plain gathers on the interval
  endpoints and partition/peer boundary indices.

Everything is generic over ``xp`` (numpy eager for the CPU engine, jax.numpy
traced for the TPU exec) and jit-fuses into the enclosing window exec program.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV
from spark_rapids_tpu.ops import batch_kernels as bk


def _cummax(xp, a):
    if xp is np:
        return np.maximum.accumulate(a)
    import jax.lax as lax
    return lax.cummax(a)


def _exclusive_prefix_sum(xp, a):
    """p of length n+1 with p[0]=0, p[i] = sum(a[:i])."""
    c = xp.cumsum(a)
    zero = xp.zeros((1,), dtype=c.dtype)
    return xp.concatenate([zero, c])


def _bsearch(xp, vals, target, lo0, hi0, side: str):
    """Vectorized binary search per row over a shared sorted array.

    Returns the first index j in [lo0, hi0) with vals[j] >= target (side='left')
    or vals[j] > target (side='right'); hi0 when no such j. All of target/lo0/hi0
    are per-row arrays; iteration count is static (log2 capacity).
    """
    cap = vals.shape[0]
    lo = lo0.astype(np.int64)
    hi = hi0.astype(np.int64)
    iters = max(1, int(math.ceil(math.log2(max(cap, 2)))) + 1)
    for _ in range(iters):
        active = lo < hi
        mid = (lo + hi) >> 1
        v = vals[xp.clip(mid, 0, cap - 1)]
        pred = (v < target) if side == "left" else (v <= target)
        go_right = xp.logical_and(active, pred)
        go_left = xp.logical_and(active, xp.logical_not(pred))
        lo = xp.where(go_right, mid + 1, lo)
        hi = xp.where(go_left, mid, hi)
    return lo


# ----------------------------------------------------------------- RMQ tables
def _rmq_table(xp, data, neutral, kind: str):
    """Sparse-table for range min/max: level k covers spans of 2^k rows.

    Returns a (levels, cap) stacked array; queries gather two spans.
    """
    cap = data.shape[0]
    levels = max(1, int(math.ceil(math.log2(max(cap, 2)))) + 1)
    pick = xp.minimum if kind == "min" else xp.maximum
    t = data
    tables = [t]
    for k in range(1, levels):
        w = 1 << (k - 1)
        if w >= cap:
            tables.append(t)
            continue
        pad = xp.full((w,), neutral, dtype=data.dtype)
        shifted = xp.concatenate([t[w:], pad])
        t = pick(t, shifted)
        tables.append(t)
    return xp.stack(tables)


def rmq_reduce(xp, data, neutral, kind: str, lo, hi, empty):
    """Range min/max over inclusive [lo, hi] per row; neutral where empty."""
    table = _rmq_table(xp, data, neutral, kind)
    cap = data.shape[0]
    length = xp.maximum(hi - lo + 1, 1)
    k = xp.floor(xp.log2(length.astype(np.float64))).astype(np.int64)
    k = xp.clip(k, 0, table.shape[0] - 1)
    span = (np.int64(1) << k)
    a = table[k, xp.clip(lo, 0, cap - 1)]
    b = table[k, xp.clip(hi - span + 1, 0, cap - 1)]
    pick = xp.minimum if kind == "min" else xp.maximum
    res = pick(a, b)
    return xp.where(empty, xp.asarray(neutral, dtype=data.dtype), res)


# ----------------------------------------------------------------- frame context
@dataclass
class FrameCtx:
    """Per-row positional context in the sorted domain, shared by every window
    function evaluated under one (partition, order) spec."""
    xp: Any
    capacity: int
    idx: Any          # row index in sorted domain
    salive: Any       # liveness (sorted)
    seg_first: Any    # first row index of the row's partition
    seg_last: Any     # last row index of the row's partition
    seg_size: Any
    peer_first: Any   # first row of the row's peer group (same order values)
    peer_last: Any
    # RANGE-offset support (set when there is exactly one orderable order key):
    order_vals: Optional[Any]  # direction-normalized values in the key's NATIVE
    #                            domain (int64 for integral/date/timestamp — no
    #                            float64 precision loss — float64 for floats)
    special: Optional[Any]     # rows whose key is null or NaN: their frame is
    #                            exactly their peer group (Spark semantics)
    dom_lo: Optional[Any]      # per-row bounds of the searchable (non-special)
    dom_hi: Optional[Any]      # region of the partition
    n_order_keys: int = 0


def build_frame_ctx(xp, part_keys: Sequence[ColV], order_keys, order, alive,
                    capacity: int) -> FrameCtx:
    """order_keys: list of (ColV, ascending) already permuted? NO — raw columns;
    ``order`` is the sort permutation. Everything returned lives in the sorted
    domain."""
    idx = xp.arange(capacity, dtype=np.int64)
    salive = alive[order]

    part_starts = bk.rows_equal_adjacent(xp, part_keys, order, alive)
    gids = xp.clip(xp.cumsum(part_starts.astype(np.int32)) - 1, 0, capacity - 1)
    seg_first = _cummax(xp, xp.where(part_starts, idx, np.int64(0)))
    sizes = _seg_sum(xp, salive.astype(np.int64), gids, capacity)
    seg_size = sizes[gids]
    seg_last = seg_first + xp.maximum(seg_size, 1) - 1

    all_keys = list(part_keys) + [k for k, _, _ in order_keys]
    if order_keys:
        peer_starts = bk.rows_equal_adjacent(xp, all_keys, order, alive)
        peer_first = _cummax(xp, xp.where(peer_starts, idx, np.int64(0)))
        pgids = xp.clip(xp.cumsum(peer_starts.astype(np.int32)) - 1, 0,
                        capacity - 1)
        psizes = _seg_sum(xp, salive.astype(np.int64), pgids, capacity)
        peer_last = peer_first + xp.maximum(psizes[pgids], 1) - 1
    else:
        peer_first, peer_last = seg_first, seg_last

    order_vals = special = dom_lo = dom_hi = None
    if len(order_keys) == 1:
        v, asc, _nf = order_keys[0]
        sv = bk.take_colv(xp, v, order)
        if sv.dtype.is_numeric or sv.dtype in (DType.DATE, DType.TIMESTAMP):
            special = xp.logical_not(sv.validity)
            if sv.dtype.is_floating:
                # NaN rows get peer-group frames (Spark: NaN is its own
                # greatest value; offset arithmetic on it is undefined)
                special = xp.logical_or(special, xp.isnan(sv.data))
                w = sv.data.astype(np.float64)
                if not asc:
                    w = -w
            else:
                # keep the NATIVE int64 domain — float64 would corrupt
                # timestamp-microsecond-scale keys (spacing > 1 above 2^53).
                # Descending uses ~x: monotone decreasing, no INT64_MIN overflow
                w = sv.data.astype(np.int64)
                if not asc:
                    w = ~w
            order_vals = xp.where(special, xp.zeros((), dtype=w.dtype), w)
            # searchable region per row: the contiguous non-special span of the
            # partition (sort puts nulls at the nulls_first end, NaN at the
            # greatest end, so the remainder is contiguous)
            ok = xp.logical_not(special)
            lo_pick, lo_has = bk.segment_pick(xp, ok, gids, capacity, "first",
                                              alive=salive, ignore_nulls=True)
            hi_pick, _ = bk.segment_pick(xp, ok, gids, capacity, "last",
                                         alive=salive, ignore_nulls=True)
            dom_lo = xp.where(lo_has[gids], lo_pick[gids], np.int64(1))
            dom_hi = xp.where(lo_has[gids], hi_pick[gids], np.int64(0))

    return FrameCtx(xp, capacity, idx, salive, seg_first, seg_last, seg_size,
                    peer_first, peer_last, order_vals, special, dom_lo, dom_hi,
                    len(order_keys))


def _seg_sum(xp, data, seg_ids, num_segments: int):
    if xp is np:
        out = np.zeros(num_segments, dtype=data.dtype)
        np.add.at(out, seg_ids, data)
        return out
    import jax
    return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)


def frame_bounds(fr: FrameCtx, frame_type: str, lower, upper):
    """Per-row inclusive [lo, hi] interval + empty mask for one frame spec.

    lower/upper: None = unbounded; for ROWS an int offset (negative preceding,
    0 current row); for RANGE a numeric offset on the single order key, with 0
    meaning CURRENT ROW (peer-inclusive both directions, per SQL semantics).
    """
    xp = fr.xp
    if frame_type == "rows":
        lo = fr.seg_first if lower is None else xp.maximum(
            fr.idx + int(lower), fr.seg_first)
        hi = fr.seg_last if upper is None else xp.minimum(
            fr.idx + int(upper), fr.seg_last)
    else:  # range
        has_offset = any(b is not None and b != 0 for b in (lower, upper))
        if has_offset and fr.order_vals is None:
            # Spark's analyzer restriction (mirrored so the CPU engine gives
            # the same error the TPU tagger predicts)
            raise ValueError(
                "RANGE window frame with offsets requires exactly one "
                "numeric/date/timestamp ORDER BY key")

        def offset_target(off):
            if np.issubdtype(fr.order_vals.dtype, np.integer):
                return fr.order_vals + np.int64(off)
            return fr.order_vals + np.float64(off)

        if lower is None:
            lo = fr.seg_first
        elif lower == 0:
            lo = fr.peer_first
        else:
            found = _bsearch(xp, fr.order_vals, offset_target(lower),
                             fr.dom_lo, fr.dom_hi + 1, "left")
            # null/NaN-keyed rows: frame = their peer group (offset arithmetic
            # is undefined on them — Spark gives them peer-only frames)
            lo = xp.where(fr.special, fr.peer_first, found)
        if upper is None:
            hi = fr.seg_last
        elif upper == 0:
            hi = fr.peer_last
        else:
            found = _bsearch(xp, fr.order_vals, offset_target(upper),
                             fr.dom_lo, fr.dom_hi + 1, "right") - 1
            hi = xp.where(fr.special, fr.peer_last, found)
    empty = xp.logical_or(lo > hi, xp.logical_not(fr.salive))
    return lo, hi, empty


# ----------------------------------------------------------------- frame reduce
def frame_reduce_buffer(fr: FrameCtx, buf: ColV, kind: str, lo, hi, empty,
                        ignore_nulls: bool = False) -> ColV:
    """Reduce one aggregation buffer over each row's frame interval.

    Mirrors the segment reduction kinds of exprs/aggregates.py (sum, min, max,
    first, last) so AggregateFunction.project/evaluate are reused verbatim for
    windowed aggregation.
    """
    xp = fr.xp
    participating = xp.logical_and(buf.validity, fr.salive)
    pcount = _exclusive_prefix_sum(xp, participating.astype(np.int64))
    lo_c = xp.where(empty, np.int64(0), lo)
    hi1 = xp.where(empty, np.int64(0), hi + 1)
    n_valid = pcount[hi1] - pcount[lo_c]
    any_valid = n_valid > 0

    if kind == "sum":
        contrib = xp.where(participating, buf.data,
                           xp.zeros((), dtype=buf.data.dtype))
        p = _exclusive_prefix_sum(xp, contrib)
        return ColV(buf.dtype, p[hi1] - p[lo_c], any_valid)

    if kind in ("min", "max"):
        if buf.dtype is DType.STRING:
            return _frame_minmax_string(fr, buf, kind, lo, hi, empty, any_valid)
        return _frame_minmax(fr, buf, kind, lo, hi, empty, participating,
                             pcount, any_valid)

    if kind in ("first", "last"):
        if not ignore_nulls:
            pos = xp.clip(lo if kind == "first" else hi, 0, fr.capacity - 1)
            valid = xp.logical_and(xp.logical_not(empty),
                                   xp.logical_and(buf.validity[pos],
                                                  fr.salive[pos]))
        else:
            cnt_incl = xp.cumsum(participating.astype(np.int64))
            if kind == "first":
                # first j in frame with cum count exceeding the count before lo
                target = pcount[lo_c]
                pos = _bsearch(xp, cnt_incl, target, lo_c, hi1, "right")
            else:
                # position where cum count first reaches the frame-end count
                target = pcount[hi1]
                pos = _bsearch(xp, cnt_incl, target - 1, lo_c, hi1, "right")
            pos = xp.clip(pos, 0, fr.capacity - 1)
            valid = xp.logical_and(any_valid, xp.logical_not(empty))
        if buf.dtype is DType.STRING:
            return ColV(buf.dtype, buf.data[pos], valid, buf.lengths[pos])
        return ColV(buf.dtype, buf.data[pos], valid)

    raise ValueError(kind)


def _frame_minmax(fr: FrameCtx, buf: ColV, kind: str, lo, hi, empty,
                  participating, pcount, any_valid) -> ColV:
    xp = fr.xp
    from spark_rapids_tpu.exprs.aggregates import _reduce_neutral
    dt = buf.dtype
    if dt is DType.BOOLEAN:
        data = buf.data.astype(np.int8)
        neutral = np.int8(1 if kind == "min" else 0)
        contrib = xp.where(participating, data, neutral)
        res = rmq_reduce(xp, contrib, neutral, kind, lo, hi, empty)
        return ColV(dt, res.astype(np.bool_), any_valid)
    if dt.is_floating:
        d = buf.data
        nan = xp.logical_and(xp.isnan(d), participating)
        dd = xp.where(nan, xp.asarray(np.inf, dtype=d.dtype), d)
        neutral = np.asarray(np.inf if kind == "min" else -np.inf,
                             dtype=np.dtype(d.dtype))
        contrib = xp.where(participating, dd, neutral)
        res = rmq_reduce(xp, contrib, neutral, kind, lo, hi, empty)
        ncount = _exclusive_prefix_sum(xp, nan.astype(np.int64))
        lo_c = xp.where(empty, np.int64(0), lo)
        hi1 = xp.where(empty, np.int64(0), hi + 1)
        n_nan = ncount[hi1] - ncount[lo_c]
        vcount = pcount[hi1] - pcount[lo_c]
        if kind == "max":
            # Spark: NaN is the largest value — any NaN in frame wins the max
            res = xp.where(n_nan > 0, xp.asarray(np.nan, dtype=d.dtype), res)
        else:
            # min is NaN only when every valid value was NaN
            res = xp.where(xp.logical_and(vcount > 0, n_nan == vcount),
                           xp.asarray(np.nan, dtype=d.dtype), res)
        return ColV(dt, res, any_valid)
    neutral = _reduce_neutral(kind, dt)
    contrib = xp.where(participating, buf.data, neutral)
    res = rmq_reduce(xp, contrib, neutral, kind, lo, hi, empty)
    return ColV(dt, res, any_valid)


def _frame_minmax_string(fr: FrameCtx, buf: ColV, kind: str, lo, hi, empty,
                         any_valid) -> ColV:
    """Range min/max on strings: byte-order rank once via the shared sort, then
    integer RMQ over ranks, then gather (the windowed twin of
    ops/aggregate._segment_minmax_string)."""
    xp = fr.xp
    participating = xp.logical_and(buf.validity, fr.salive)
    order2 = bk.sort_indices(xp, [(buf, True, True)], participating)
    rank = bk._stable_argsort(xp, order2).astype(np.int64)
    n = fr.capacity
    if kind == "min":
        key = xp.where(participating, rank, np.int64(n + 1))
        res = rmq_reduce(xp, key, np.int64(n + 1), "min", lo, hi, empty)
        has = res <= n
    else:
        key = xp.where(participating, rank, np.int64(-1))
        res = rmq_reduce(xp, key, np.int64(-1), "max", lo, hi, empty)
        has = res >= 0
    pick = order2[xp.clip(res, 0, n - 1)]
    valid = xp.logical_and(xp.logical_and(has, any_valid), buf.validity[pick])
    return ColV(buf.dtype, buf.data[pick], valid, buf.lengths[pick])
