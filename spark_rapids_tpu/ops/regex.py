"""Device regex engine: compiled DFA over the byte matrix.

The TPU replacement for cuDF's regex kernels (reference:
stringFunctions.scala GpuLike/GpuRegExpReplace/GpuStringSplit delegating to
cudf's regex engine). Patterns are plan-time literals, so compilation is
host-side: a regex subset parses to a Thompson NFA, subset-construction
yields a dense DFA transition table [n_states, 256], and matching is a
fixed-length scan over the byte-matrix columns — W steps of vectorized
table lookups, no data-dependent control flow (lax.scan on device).

Supported syntax (the subset the benchmark suites and LIKE lowering need):
literals, ``.``, classes ``[a-z0-9_]`` with ranges and negation, ``*`` ``+``
``?`` quantifiers, alternation ``|``, grouping ``()``, anchors are implicit
(match() is anchored; search() prepends an any-byte loop). Byte-level
semantics: multibyte UTF-8 is matched byte-wise (``.`` consumes one BYTE) —
ASCII scope, like the engine's Upper/Lower, tagged incompat in the rules.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

_EPS = -1


class RegexError(ValueError):
    pass


class _Nfa:
    def __init__(self):
        self.edges: List[List[Tuple[int, Optional[Set[int]]]]] = []

    def state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1

    def add(self, a: int, b: int, chars: Optional[Set[int]]) -> None:
        self.edges[a].append((b, chars))


def _parse(pattern: str):
    """pattern -> (nfa, start, accept) via recursive descent."""
    nfa = _Nfa()
    pos = [0]
    data = pattern

    def peek():
        return data[pos[0]] if pos[0] < len(data) else None

    def take():
        c = data[pos[0]]
        pos[0] += 1
        return c

    def parse_alt():
        s, e = parse_seq()
        while peek() == "|":
            take()
            s2, e2 = parse_seq()
            ns, ne = nfa.state(), nfa.state()
            nfa.add(ns, s, None)
            nfa.add(ns, s2, None)
            nfa.add(e, ne, None)
            nfa.add(e2, ne, None)
            s, e = ns, ne
        return s, e

    def parse_seq():
        s = nfa.state()
        e = s
        while peek() is not None and peek() not in "|)":
            s2, e2 = parse_piece()
            nfa.add(e, s2, None)
            e = e2
        return s, e

    def parse_piece():
        s, e = parse_atom()
        while peek() in ("*", "+", "?"):
            q = take()
            ns, ne = nfa.state(), nfa.state()
            nfa.add(ns, s, None)
            nfa.add(e, ne, None)
            if q in ("*", "?"):
                nfa.add(ns, ne, None)
            if q in ("*", "+"):
                nfa.add(e, s, None)
            s, e = ns, ne
        return s, e

    def parse_atom():
        c = peek()
        if c is None:
            raise RegexError(f"unexpected end of pattern {data!r}")
        if c == "(":
            take()
            s, e = parse_alt()
            if peek() != ")":
                raise RegexError(f"unbalanced '(' in {data!r}")
            take()
            return s, e
        if c == "[":
            take()
            chars = _parse_class(take, peek)
            return _char_edge(chars)
        if c == ".":
            take()
            return _char_edge(set(range(256)))
        if c == "\\":
            take()
            nxt = take() if peek() is not None else None
            if nxt is None:
                raise RegexError(f"dangling escape in {data!r}")
            cls = _ESCAPES.get(nxt)
            return _char_edge(cls if cls is not None
                              else {ord(nxt) & 0xFF})
        if c in ")|*+?":
            raise RegexError(f"unexpected {c!r} in {data!r}")
        if c in "{}^$":
            # syntax Java regex gives meaning to but this subset does not
            # implement — reject rather than silently matching literally
            raise RegexError(f"unsupported regex syntax {c!r} in {data!r} "
                             f"(escape it to match literally)")
        take()
        bs = c.encode("utf-8")
        # multibyte literal: its bytes match in SEQUENCE (chained edges)
        s = nfa.state()
        e = s
        for b in bs:
            s2, e2 = _char_edge({b})
            nfa.add(e, s2, None)
            e = e2
        return s, e

    def _char_edge(chars: Set[int]):
        s, e = nfa.state(), nfa.state()
        nfa.add(s, e, chars)
        return s, e

    def _parse_class(take, peek):
        neg = False
        if peek() == "^":
            take()
            neg = True
        chars: Set[int] = set()
        prev: Optional[int] = None
        while peek() is not None and peek() != "]":
            c = take()
            if c == "\\" and peek() is not None:
                c2 = take()
                cls = _ESCAPES.get(c2)
                if cls is not None:
                    chars |= cls
                    prev = None
                    continue
                c = c2
            if c == "-" and prev is not None and peek() not in (None, "]"):
                hi = ord(take())
                chars |= set(range(prev, hi + 1))
                prev = None
                continue
            b = ord(c)
            if b > 0xFF:
                raise RegexError("non-ASCII literal in character class")
            chars.add(b)
            prev = b
        if peek() != "]":
            raise RegexError(f"unbalanced '[' in {data!r}")
        take()
        return set(range(256)) - chars if neg else chars

    s, e = parse_alt()
    if pos[0] != len(data):
        raise RegexError(f"trailing input at {pos[0]} in {data!r}")
    return nfa, s, e


_ESCAPES: Dict[str, Set[int]] = {
    "d": set(range(ord("0"), ord("9") + 1)),
    "w": (set(range(ord("a"), ord("z") + 1))
          | set(range(ord("A"), ord("Z") + 1))
          | set(range(ord("0"), ord("9") + 1)) | {ord("_")}),
    "s": {0x20, 0x09, 0x0A, 0x0D, 0x0B, 0x0C},
}
_ESCAPES["D"] = set(range(256)) - _ESCAPES["d"]
_ESCAPES["W"] = set(range(256)) - _ESCAPES["w"]
_ESCAPES["S"] = set(range(256)) - _ESCAPES["s"]


class Dfa:
    """Dense DFA: trans [n_states, 256] int32 (state 0 = dead sink),
    accept [n_states] bool, start state index."""

    def __init__(self, trans: np.ndarray, accept: np.ndarray, start: int):
        self.trans = trans
        self.accept = accept
        self.start = start

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]


def compile_dfa(pattern: str, search: bool = False,
                max_states: int = 512) -> Dfa:
    """Regex subset -> DFA. ``search=True`` allows a match to start anywhere
    (prepends an any-byte loop — RLike semantics); otherwise the match is
    anchored at the start (LIKE lowering adds its own .* where needed)."""
    nfa, start, accept = _parse(pattern)
    if search:
        ns = nfa.state()
        nfa.add(ns, ns, set(range(256)))
        nfa.add(ns, start, None)
        start = ns

    def eps_closure(states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for dst, chars in nfa.edges[s]:
                if chars is None and dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return frozenset(out)

    start_set = eps_closure(frozenset([start]))
    # state 0 is the dead sink
    ids: Dict[FrozenSet[int], int] = {frozenset(): 0, start_set: 1}
    acc: List[bool] = [False, accept in start_set]
    row_of: Dict[int, np.ndarray] = {0: np.zeros(256, np.int32)}
    work = [start_set]
    while work:
        cur = work.pop()
        row = np.zeros(256, np.int32)
        by_byte: Dict[int, Set[int]] = {}
        for s in cur:
            for dst, chars in nfa.edges[s]:
                if chars is None:
                    continue
                for b in chars:
                    by_byte.setdefault(b, set()).add(dst)
        for b, dsts in by_byte.items():
            t = eps_closure(frozenset(dsts))
            if t not in ids:
                if len(ids) >= max_states:
                    raise RegexError(
                        f"pattern {pattern!r} exceeds {max_states} DFA "
                        f"states")
                ids[t] = len(ids)
                acc.append(accept in t)
                work.append(t)
            row[b] = ids[t]
        row_of[ids[cur]] = row
    table = np.stack([row_of[i] for i in range(len(ids))])
    return Dfa(table, np.asarray(acc, bool), 1)


def like_to_regex(pattern: str, escape: str = "\\") -> str:
    """SQL LIKE pattern -> this engine's regex (anchored by construction)."""
    out = []
    i = 0
    special = set(".[]()*+?|\\^${}")
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            nxt = pattern[i + 1]
            out.append("\\" + nxt if nxt in special else nxt)
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        elif c in special:
            out.append("\\" + c)
        else:
            out.append(c)
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# device/np kernels
# ---------------------------------------------------------------------------
def dfa_match(xp, dfa: Dfa, data, lengths, search: bool = False):
    """bool[n]: does each row (its first `length` bytes) match?

    Anchored mode accepts when the state AT the row's length position is
    accepting (full-row match). Search mode accepts when ANY prefix position
    within the row reached an accepting state — pair with
    compile_dfa(search=True) for find-anywhere (RLike), or with an anchored
    DFA for match-at-start-only (a leading ^).
    """
    n, W = data.shape
    trans = xp.asarray(dfa.trans)
    accept = xp.asarray(dfa.accept)
    flat = trans.reshape(-1)
    state = xp.full((n,), dfa.start, dtype=np.int32)
    hit = xp.logical_and(accept[state],
                         xp.asarray(True) if search else lengths == 0)

    def at(jj):
        return (jj + 1 <= lengths) if search else (lengths == jj + 1)

    if xp is np:
        for j in range(W):
            state = flat[state * 256 + data[:, j].astype(np.int32)]
            hit = np.logical_or(hit, np.logical_and(accept[state], at(j)))
        return hit
    import jax

    def step(carry, col):
        state, hit = carry
        byte, jj = col
        state = flat[state * 256 + byte.astype(np.int32)]
        hit = xp.logical_or(hit, xp.logical_and(accept[state], at(jj)))
        return (state, hit), None

    iota = xp.arange(W, dtype=np.int32)
    (state, hit), _ = jax.lax.scan(step, (state, hit), (data.T, iota))
    return hit


def dfa_find_spans(xp, dfa: Dfa, data, lengths):
    """Leftmost-longest match spans for an (anchored) DFA run from every
    starting byte position. Returns match_len [n, W] int32: the LONGEST
    match length starting at each position (-1 = no match). O(W^2 / 8)ish:
    one scan of W steps over a [n, W] state matrix (DFA instance per start).
    """
    n, W = data.shape
    trans = xp.asarray(dfa.trans)
    accept = xp.asarray(dfa.accept)
    flat = trans.reshape(-1)
    pos = np.arange(W, dtype=np.int32)
    valid_start = xp.asarray(pos)[None, :] <= lengths[:, None] - 0
    state = xp.where(valid_start, np.int32(dfa.start), np.int32(0))
    # empty match (zero-length) allowed when start state accepts
    best = xp.where(xp.logical_and(bool(dfa.accept[dfa.start]),
                                   valid_start),
                    np.int32(0), np.int32(-1))

    def body(j, state, best):
        # instance starting at position p consumes byte p + j
        idx = xp.clip(xp.asarray(pos)[None, :] + j, 0, W - 1)
        byte = xp.take_along_axis(data, idx, axis=-1).astype(np.int32)
        in_range = (xp.asarray(pos)[None, :] + j) < lengths[:, None]
        state = xp.where(in_range,
                         flat[state * 256 + byte], np.int32(0))
        best = xp.where(xp.logical_and(accept[state], in_range),
                        (xp.asarray(j) + 1).astype(np.int32)
                        if xp is not np else np.int32(j + 1), best)
        return state, best

    if xp is np:
        for j in range(W):
            state, best = body(j, state, best)
        return best
    import jax

    def step(carry, j):
        state, best = carry
        state, best = body(j, state, best)
        return (state, best), None

    (state, best), _ = jax.lax.scan(
        step, (state, best), xp.arange(W, dtype=np.int32))
    return best


def regex_greedy_spans(xp, match_len, lengths, W: int):
    """Leftmost non-overlapping span selection over per-position match
    lengths (Java Matcher.find() order): sel[n, W] marks span starts,
    span_len[n, W] their lengths (zero-length matches advance by one)."""
    n = match_len.shape[0]
    if xp is np:
        sel = np.zeros((n, W), dtype=bool)
        nxt = np.zeros(n, dtype=np.int32)
        for i in range(W):
            m = match_len[:, i]
            can = np.logical_and(m >= 0, nxt <= i)
            can = np.logical_and(can, i <= lengths - 0)
            sel[:, i] = can
            nxt = np.where(can, np.maximum(i + m, i + 1), nxt)
        return sel
    import jax

    def step(nxt, col):
        m, i = col
        can = xp.logical_and(m >= 0, nxt <= i)
        nxt = xp.where(can, xp.maximum(i + m, i + 1), nxt)
        return nxt, can

    iota = xp.arange(W, dtype=np.int32)
    _, selT = jax.lax.scan(step, xp.zeros(n, dtype=np.int32),
                           (match_len.T, iota))
    return selT.T
