"""Native (C++) runtime components, loaded via ctypes.

The reference delegates its runtime hot paths to native code (RMM pools, cuDF
JNI, UCX); here the host-runtime pieces — the address-space sub-allocator and
the spill-ordering priority queue — are C++ compiled on first import and bound
over a C ABI. Compute stays in XLA; this is the runtime *around* the device.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_BUILD = os.path.join(_DIR, "build")
_LIB_PATH = os.path.join(_BUILD, "libsrtpu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _src_hash() -> str:
    import hashlib
    h = hashlib.sha256()
    for f in sorted(os.listdir(_SRC)):
        if f.endswith(".cpp"):
            with open(os.path.join(_SRC, f), "rb") as fh:
                h.update(f.encode())
                h.update(fh.read())
    return h.hexdigest()


_HASH_PATH = os.path.join(_BUILD, "src.sha256")


def _needs_rebuild() -> bool:
    """Content-hash check (mtimes are unreliable after git checkout)."""
    if not os.path.exists(_LIB_PATH) or not os.path.exists(_HASH_PATH):
        return True
    with open(_HASH_PATH) as f:
        return f.read().strip() != _src_hash()


def _build() -> None:
    os.makedirs(_BUILD, exist_ok=True)
    srcs = [os.path.join(_SRC, f) for f in sorted(os.listdir(_SRC))
            if f.endswith(".cpp")]
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           "-o", _LIB_PATH] + srcs
    subprocess.run(cmd, check=True, capture_output=True)
    with open(_HASH_PATH, "w") as f:
        f.write(_src_hash())


def get_lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            if _needs_rebuild():
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            _configure(lib)
            _lib = lib
    return _lib


def _configure(lib: ctypes.CDLL) -> None:
    u64 = ctypes.c_uint64
    i64 = ctypes.c_int64
    p = ctypes.c_void_p
    lib.srt_allocator_create.restype = p
    lib.srt_allocator_create.argtypes = [u64]
    lib.srt_allocator_destroy.argtypes = [p]
    lib.srt_allocator_allocate.restype = u64
    lib.srt_allocator_allocate.argtypes = [p, u64]
    lib.srt_allocator_free.restype = u64
    lib.srt_allocator_free.argtypes = [p, u64]
    lib.srt_allocator_available.restype = u64
    lib.srt_allocator_available.argtypes = [p]
    lib.srt_allocator_allocated_size.restype = u64
    lib.srt_allocator_allocated_size.argtypes = [p, u64]
    lib.srt_allocator_num_free_blocks.restype = u64
    lib.srt_allocator_num_free_blocks.argtypes = [p]
    lib.srt_allocator_largest_free_block.restype = u64
    lib.srt_allocator_largest_free_block.argtypes = [p]

    lib.srt_pq_create.restype = p
    lib.srt_pq_destroy.argtypes = [p]
    lib.srt_pq_offer.restype = ctypes.c_int
    lib.srt_pq_offer.argtypes = [p, i64, ctypes.c_double]
    lib.srt_pq_contains.restype = ctypes.c_int
    lib.srt_pq_contains.argtypes = [p, i64]
    lib.srt_pq_poll.restype = ctypes.c_int
    lib.srt_pq_poll.argtypes = [p, ctypes.POINTER(i64),
                                ctypes.POINTER(ctypes.c_double)]
    lib.srt_pq_peek.restype = ctypes.c_int
    lib.srt_pq_peek.argtypes = [p, ctypes.POINTER(i64),
                                ctypes.POINTER(ctypes.c_double)]
    lib.srt_pq_remove.restype = ctypes.c_int
    lib.srt_pq_remove.argtypes = [p, i64]
    lib.srt_pq_size.restype = u64
    lib.srt_pq_size.argtypes = [p]


NULL_OFFSET = 2 ** 64 - 1

_build_failed = False


def try_get_lib() -> Optional[ctypes.CDLL]:
    """get_lib that degrades to None when the toolchain is unavailable, so a
    missing g++ costs the native fast path, not the whole engine."""
    global _build_failed
    if _build_failed:
        return None
    try:
        return get_lib()
    except Exception as e:  # noqa: BLE001 - any build/load failure degrades
        _build_failed = True
        import logging
        logging.getLogger(__name__).warning(
            "native runtime unavailable (%s); using Python fallbacks", e)
        return None


def AddressSpaceAllocator(size: int):
    """First-fit sub-allocator over an abstract address space. C++ backed when
    the toolchain is present; pure-Python fallback otherwise."""
    if try_get_lib() is not None:
        return _NativeAddressSpaceAllocator(size)
    return PyAddressSpaceAllocator(size)


def HashedPriorityQueue():
    """Min-heap with O(1) contains and keyed updates (spill ordering). C++
    backed when available; pure-Python fallback otherwise."""
    if try_get_lib() is not None:
        return _NativeHashedPriorityQueue()
    return PyHashedPriorityQueue()


class _NativeAddressSpaceAllocator:
    """First-fit sub-allocator over an abstract address space (C++ backed)."""

    def __init__(self, size: int):
        self._lib = get_lib()
        self._handle = self._lib.srt_allocator_create(size)
        if not self._handle:
            raise MemoryError("failed to create allocator")
        self.size = size

    def allocate(self, length: int) -> Optional[int]:
        off = self._lib.srt_allocator_allocate(self._handle, length)
        return None if off == NULL_OFFSET else off

    def free(self, offset: int) -> int:
        return self._lib.srt_allocator_free(self._handle, offset)

    @property
    def available(self) -> int:
        return self._lib.srt_allocator_available(self._handle)

    def allocated_size(self, offset: int) -> int:
        return self._lib.srt_allocator_allocated_size(self._handle, offset)

    @property
    def num_free_blocks(self) -> int:
        return self._lib.srt_allocator_num_free_blocks(self._handle)

    @property
    def largest_free_block(self) -> int:
        return self._lib.srt_allocator_largest_free_block(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.srt_allocator_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _NativeHashedPriorityQueue:
    """Min-heap with O(1) contains and keyed priority updates (C++ backed).
    Lowest priority polls first — the spill order."""

    def __init__(self):
        self._lib = get_lib()
        self._handle = self._lib.srt_pq_create()
        if not self._handle:
            raise MemoryError("failed to create priority queue")

    def offer(self, key: int, priority: float) -> bool:
        return bool(self._lib.srt_pq_offer(self._handle, key, priority))

    def __contains__(self, key: int) -> bool:
        return bool(self._lib.srt_pq_contains(self._handle, key))

    def poll(self):
        k = ctypes.c_int64()
        pr = ctypes.c_double()
        if not self._lib.srt_pq_poll(self._handle, ctypes.byref(k),
                                     ctypes.byref(pr)):
            return None
        return k.value, pr.value

    def peek(self):
        k = ctypes.c_int64()
        pr = ctypes.c_double()
        if not self._lib.srt_pq_peek(self._handle, ctypes.byref(k),
                                     ctypes.byref(pr)):
            return None
        return k.value, pr.value

    def remove(self, key: int) -> bool:
        return bool(self._lib.srt_pq_remove(self._handle, key))

    def __len__(self) -> int:
        return self._lib.srt_pq_size(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.srt_pq_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------- pure-Python
class PyAddressSpaceAllocator:
    """Fallback first-fit allocator with block coalescing (same semantics as
    the C++ implementation; used when no toolchain is available)."""

    def __init__(self, size: int):
        self.size = size
        self._free = [(0, size)] if size > 0 else []  # sorted (offset, length)
        self._allocated = {}  # offset -> length

    def allocate(self, length: int):
        if length <= 0:
            return None
        for i, (off, flen) in enumerate(self._free):
            if flen >= length:
                if flen == length:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + length, flen - length)
                self._allocated[off] = length
                return off
        return None

    def free(self, offset: int) -> int:
        length = self._allocated.pop(offset, None)
        if length is None:
            return 0
        import bisect
        i = bisect.bisect_left(self._free, (offset, 0))
        self._free.insert(i, (offset, length))
        # coalesce with neighbors
        if i + 1 < len(self._free):
            off, flen = self._free[i]
            noff, nlen = self._free[i + 1]
            if off + flen == noff:
                self._free[i] = (off, flen + nlen)
                self._free.pop(i + 1)
        if i > 0:
            poff, plen = self._free[i - 1]
            off, flen = self._free[i]
            if poff + plen == off:
                self._free[i - 1] = (poff, plen + flen)
                self._free.pop(i)
        return length

    @property
    def available(self) -> int:
        return sum(l for _, l in self._free)

    def allocated_size(self, offset: int) -> int:
        return self._allocated.get(offset, 0)

    @property
    def num_free_blocks(self) -> int:
        return len(self._free)

    @property
    def largest_free_block(self) -> int:
        return max((l for _, l in self._free), default=0)

    def close(self) -> None:
        self._free = []
        self._allocated = {}


class PyHashedPriorityQueue:
    """Fallback keyed min-heap: heapq with lazy deletion + live-entry map."""

    def __init__(self):
        import heapq
        self._heapq = heapq
        self._heap = []  # (priority, seq, key)
        self._live = {}  # key -> (priority, seq)
        self._seq = 0

    def offer(self, key: int, priority: float) -> bool:
        self._seq += 1
        self._live[key] = (priority, self._seq)
        self._heapq.heappush(self._heap, (priority, self._seq, key))
        return True

    def __contains__(self, key: int) -> bool:
        return key in self._live

    def _prune(self):
        while self._heap:
            prio, seq, key = self._heap[0]
            if self._live.get(key) == (prio, seq):
                return self._heap[0]
            self._heapq.heappop(self._heap)
        return None

    def poll(self):
        top = self._prune()
        if top is None:
            return None
        prio, seq, key = self._heapq.heappop(self._heap)
        del self._live[key]
        return key, prio

    def peek(self):
        top = self._prune()
        if top is None:
            return None
        prio, _seq, key = top
        return key, prio

    def remove(self, key: int) -> bool:
        return self._live.pop(key, None) is not None

    def __len__(self) -> int:
        return len(self._live)

    def close(self) -> None:
        self._heap = []
        self._live = {}
