// Hashed priority queue: O(log n) push/pop with O(1) contains and
// O(log n) priority update by key (reference: HashedPriorityQueue.java, used
// for spill ordering in RapidsBufferStore). Min-heap on (priority, seq):
// lowest priority spills first, FIFO among equals via the insertion sequence.
#include <cstddef>
#include <cstdint>
#include <new>
#include <unordered_map>
#include <utility>
#include <vector>

using std::size_t;

namespace {

struct Entry {
  int64_t key;
  double priority;
  uint64_t seq;
};

struct HeapQueue {
  std::vector<Entry> heap;                      // binary min-heap
  std::unordered_map<int64_t, size_t> index;    // key -> heap slot
  uint64_t next_seq = 0;

  bool less(const Entry& a, const Entry& b) const {
    if (a.priority != b.priority) return a.priority < b.priority;
    return a.seq < b.seq;
  }

  void swap_slots(size_t i, size_t j) {
    std::swap(heap[i], heap[j]);
    index[heap[i].key] = i;
    index[heap[j].key] = j;
  }

  void sift_up(size_t i) {
    while (i > 0) {
      size_t parent = (i - 1) / 2;
      if (!less(heap[i], heap[parent])) break;
      swap_slots(i, parent);
      i = parent;
    }
  }

  void sift_down(size_t i) {
    size_t n = heap.size();
    for (;;) {
      size_t l = 2 * i + 1, r = 2 * i + 2, best = i;
      if (l < n && less(heap[l], heap[best])) best = l;
      if (r < n && less(heap[r], heap[best])) best = r;
      if (best == i) break;
      swap_slots(i, best);
      i = best;
    }
  }

  void remove_at(size_t i) {
    index.erase(heap[i].key);
    size_t last = heap.size() - 1;
    if (i != last) {
      heap[i] = heap[last];
      index[heap[i].key] = i;
      heap.pop_back();
      sift_down(i);
      sift_up(i);
    } else {
      heap.pop_back();
    }
  }
};

}  // namespace

extern "C" {

void* srt_pq_create() { return new (std::nothrow) HeapQueue(); }

void srt_pq_destroy(void* handle) { delete static_cast<HeapQueue*>(handle); }

// Insert or update: returns 1 if inserted, 0 if an existing key was updated.
int srt_pq_offer(void* handle, int64_t key, double priority) {
  auto* q = static_cast<HeapQueue*>(handle);
  auto it = q->index.find(key);
  if (it != q->index.end()) {
    size_t i = it->second;
    q->heap[i].priority = priority;
    q->sift_down(i);
    q->sift_up(i);
    return 0;
  }
  q->heap.push_back(Entry{key, priority, q->next_seq++});
  size_t i = q->heap.size() - 1;
  q->index[key] = i;
  q->sift_up(i);
  return 1;
}

int srt_pq_contains(void* handle, int64_t key) {
  auto* q = static_cast<HeapQueue*>(handle);
  return q->index.count(key) ? 1 : 0;
}

// Pop the minimum-priority entry. Returns 0 when empty.
int srt_pq_poll(void* handle, int64_t* key_out, double* priority_out) {
  auto* q = static_cast<HeapQueue*>(handle);
  if (q->heap.empty()) return 0;
  *key_out = q->heap[0].key;
  *priority_out = q->heap[0].priority;
  q->remove_at(0);
  return 1;
}

int srt_pq_peek(void* handle, int64_t* key_out, double* priority_out) {
  auto* q = static_cast<HeapQueue*>(handle);
  if (q->heap.empty()) return 0;
  *key_out = q->heap[0].key;
  *priority_out = q->heap[0].priority;
  return 1;
}

int srt_pq_remove(void* handle, int64_t key) {
  auto* q = static_cast<HeapQueue*>(handle);
  auto it = q->index.find(key);
  if (it == q->index.end()) return 0;
  q->remove_at(it->second);
  return 1;
}

uint64_t srt_pq_size(void* handle) {
  return static_cast<HeapQueue*>(handle)->heap.size();
}

}  // extern "C"
