// Address-space sub-allocator (reference: AddressSpaceAllocator.scala — a
// first-fit allocator over one large pinned buffer used by the host memory
// store). Re-designed in C++ with coalescing free blocks and O(log n) free-list
// lookup by address; exposed to Python over a C ABI via ctypes.
//
// The allocator manages an abstract address space [0, size): callers bind the
// offsets to a host staging arena / pinned region. Thread safety is the
// caller's job (the Python store holds a lock), keeping this layer lock-free.
#include <cstdint>
#include <map>
#include <new>

namespace {

struct Allocator {
  uint64_t size;
  uint64_t available;
  // free blocks keyed by start offset -> length (coalescing neighbors on free)
  std::map<uint64_t, uint64_t> free_blocks;
  // live allocations: start offset -> length
  std::map<uint64_t, uint64_t> allocated;
};

}  // namespace

extern "C" {

void* srt_allocator_create(uint64_t size) {
  auto* a = new (std::nothrow) Allocator();
  if (a == nullptr) return nullptr;
  a->size = size;
  a->available = size;
  if (size > 0) a->free_blocks.emplace(0, size);
  return a;
}

void srt_allocator_destroy(void* handle) {
  delete static_cast<Allocator*>(handle);
}

// Returns the start offset, or UINT64_MAX when no block fits.
uint64_t srt_allocator_allocate(void* handle, uint64_t length) {
  auto* a = static_cast<Allocator*>(handle);
  if (length == 0 || a == nullptr) return UINT64_MAX;
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= length) {  // first fit
      uint64_t start = it->first;
      uint64_t remaining = it->second - length;
      a->free_blocks.erase(it);
      if (remaining > 0) a->free_blocks.emplace(start + length, remaining);
      a->allocated.emplace(start, length);
      a->available -= length;
      return start;
    }
  }
  return UINT64_MAX;
}

// Returns the freed length, or 0 if the offset was not an allocation start.
uint64_t srt_allocator_free(void* handle, uint64_t offset) {
  auto* a = static_cast<Allocator*>(handle);
  auto it = a->allocated.find(offset);
  if (it == a->allocated.end()) return 0;
  uint64_t length = it->second;
  a->allocated.erase(it);
  a->available += length;

  uint64_t start = offset;
  uint64_t end = offset + length;
  // coalesce with the following free block
  auto next = a->free_blocks.lower_bound(start);
  if (next != a->free_blocks.end() && next->first == end) {
    end += next->second;
    a->free_blocks.erase(next);
  }
  // coalesce with the preceding free block
  if (!a->free_blocks.empty()) {
    auto prev = a->free_blocks.lower_bound(start);
    if (prev != a->free_blocks.begin()) {
      --prev;
      if (prev->first + prev->second == start) {
        start = prev->first;
        a->free_blocks.erase(prev);
      }
    }
  }
  a->free_blocks.emplace(start, end - start);
  return length;
}

uint64_t srt_allocator_available(void* handle) {
  return static_cast<Allocator*>(handle)->available;
}

uint64_t srt_allocator_allocated_size(void* handle, uint64_t offset) {
  auto* a = static_cast<Allocator*>(handle);
  auto it = a->allocated.find(offset);
  return it == a->allocated.end() ? 0 : it->second;
}

uint64_t srt_allocator_num_free_blocks(void* handle) {
  return static_cast<Allocator*>(handle)->free_blocks.size();
}

uint64_t srt_allocator_largest_free_block(void* handle) {
  auto* a = static_cast<Allocator*>(handle);
  uint64_t best = 0;
  for (const auto& kv : a->free_blocks)
    if (kv.second > best) best = kv.second;
  return best;
}

}  // extern "C"
