"""PySpark-style Window spec builder.

``Window.partitionBy("a").orderBy("b").rowsBetween(Window.unboundedPreceding,
Window.currentRow)`` — consumed by ``Column.over``.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

from spark_rapids_tpu.api.column import Column
from spark_rapids_tpu.api.dataframe import _to_expr
from spark_rapids_tpu.exprs.core import Expression
from spark_rapids_tpu.exprs.misc import SortOrder
from spark_rapids_tpu.exprs.windows import WindowFrame

_MIN = -(1 << 63)
_MAX = (1 << 63) - 1


def _bound(v: Union[int, float]) -> Optional[Union[int, float]]:
    """Map the unbounded sentinels to None."""
    if v <= _MIN:
        return None
    if v >= _MAX:
        return None
    return v


class WindowSpec:
    def __init__(self, part: Tuple[Expression, ...] = (),
                 orders: Tuple[SortOrder, ...] = (),
                 frame: Optional[WindowFrame] = None):
        self._part = part
        self._orders = orders
        self._frame = frame

    def partitionBy(self, *cols: Union[str, Column]) -> "WindowSpec":
        return WindowSpec(tuple(_to_expr(c) for c in cols), self._orders,
                          self._frame)

    def orderBy(self, *cols: Union[str, Column]) -> "WindowSpec":
        orders = []
        for c in cols:
            e = _to_expr(c)
            orders.append(e if isinstance(e, SortOrder)
                          else SortOrder(e, True, True))
        return WindowSpec(self._part, tuple(orders), self._frame)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._part, self._orders,
                          WindowFrame("rows", _bound(start), _bound(end)))

    def rangeBetween(self, start, end) -> "WindowSpec":
        return WindowSpec(self._part, self._orders,
                          WindowFrame("range", _bound(start), _bound(end)))


class Window:
    unboundedPreceding = _MIN
    unboundedFollowing = _MAX
    currentRow = 0

    @staticmethod
    def partitionBy(*cols: Union[str, Column]) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols: Union[str, Column]) -> WindowSpec:
        return WindowSpec().orderBy(*cols)
