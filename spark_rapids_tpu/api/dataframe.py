"""DataFrame + session frontend.

The user-facing API a Spark user lands on: DataFrames build logical plans; an
action (collect/count/to_pandas) plans the CPU physical plan, runs TpuOverrides
to rewrite supported subtrees onto the TPU, and executes. ``explain()`` surfaces
the will-run/fallback report like spark.rapids.sql.explain.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import pyarrow as pa

from spark_rapids_tpu.api.column import Column, _expr
from spark_rapids_tpu.columnar.dtypes import DType, Schema
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.execs.base import ExecContext, PhysicalExec
from spark_rapids_tpu.exprs import (Alias, Coalesce, SortOrder,
                                    UnresolvedAttribute)
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.overrides import TpuOverrides
from spark_rapids_tpu.plan.planner import plan_physical


def _to_expr(c: Union[str, Column]):
    return UnresolvedAttribute(c) if isinstance(c, str) else c.expr


def _extract_generators(exprs, child: lp.LogicalPlan):
    """Pull an Explode/PosExplode out of a projection list into a Generate node
    beneath it (Catalyst's ExtractGenerator analog). At most one generator per
    select, like Spark."""
    from spark_rapids_tpu.exprs.generators import Explode
    hits = [i for i, e in enumerate(exprs)
            if isinstance(e.c if isinstance(e, Alias) else e, Explode)]
    if not hits:
        return exprs, child
    if len(hits) > 1:
        raise ValueError("only one generator (explode/posexplode) is allowed "
                         "per select")
    i = hits[0]
    e = exprs[i]
    alias = e.name if isinstance(e, Alias) else None
    gen = e.c if isinstance(e, Alias) else e
    col_name = alias or "col"
    node = lp.Generate(gen.child_array.items, gen.with_position, col_name,
                       child)
    refs = [UnresolvedAttribute(col_name)]
    if gen.with_position:
        refs.insert(0, UnresolvedAttribute("pos"))
    out = list(exprs)
    out[i:i + 1] = refs
    return tuple(out), node


def _extract_windows(exprs, child: lp.LogicalPlan):
    """Pull WindowExpressions out of a projection list into Window nodes
    beneath it (Catalyst's ExtractWindowExpressions analog). Expressions
    sharing a (partition, order) spec land in one Window node."""
    from spark_rapids_tpu.exprs.windows import WindowExpression
    pulled = []
    counter = [0]
    taken = {f.name for f in child.schema()}

    def fresh_name() -> str:
        while True:
            name = f"_we{counter[0]}"
            counter[0] += 1
            if name not in taken:
                taken.add(name)
                return name

    def strip(e):
        if isinstance(e, WindowExpression):
            name = fresh_name()
            pulled.append(Alias(e, name))
            return UnresolvedAttribute(name)
        return e.map_children(strip)

    new_exprs = tuple(strip(e) for e in exprs)
    if not pulled:
        return exprs, child
    groups = {}
    for a in pulled:
        groups.setdefault(a.c.sort_spec_key(), []).append(a)
    node = child
    for aliases in groups.values():
        node = lp.Window(tuple(aliases), node)
    return new_exprs, node


class Row(dict):
    """Collected row: dict with attribute access (pyspark Row analog)."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in self.items())
        return f"Row({inner})"


def _show_cell(v, width: int) -> str:
    s = "null" if v is None else str(v)
    if width and len(s) > width:
        # pyspark: plain cut below 4 chars, ellipsis otherwise
        s = s[:width] if width < 4 else s[:width - 3] + "..."
    return s


def _null_safe_set_op(left: "DataFrame", right: "DataFrame",
                      mode: str) -> "DataFrame":
    """SQL set-operation semantics (distinct rows, nulls compare equal,
    positional columns like Spark): tag each side, union, group by every
    column — group keys dedup with null==null natively — and keep groups
    by which sides contributed."""
    from spark_rapids_tpu.api import functions as F
    names = left.schema().names()
    if len(names) != len(right.schema().names()):
        raise ValueError(
            f"set operation column-count mismatch: {names} vs "
            f"{right.schema().names()}")
    la = left.dropDuplicates().withColumn("__setf", F.lit(1))
    rb = (right.toDF(*names).dropDuplicates()
          .withColumn("__setf", F.lit(2)))
    agg = (la.union(rb).groupBy(*names)
           .agg(F.min("__setf").alias("__mn"),
                F.max("__setf").alias("__mx")))
    if mode == "intersect":
        agg = agg.filter((F.col("__mn") == 1) & (F.col("__mx") == 2))
    else:                                   # subtract / EXCEPT
        agg = agg.filter(F.col("__mx") == 1)
    return agg.select(*names)


class DataFrame:
    def __init__(self, logical: lp.LogicalPlan, session: "TpuSession"):
        self._plan = logical
        self.session = session

    # ---- transformations -----------------------------------------------------
    def select(self, *cols: Union[str, Column]) -> "DataFrame":
        exprs = tuple(_to_expr(c) for c in cols)
        exprs, child = _extract_generators(exprs, self._plan)
        exprs, child = _extract_windows(exprs, child)
        return DataFrame(lp.Project(exprs, child), self.session)

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        # a replaced column keeps its position (pyspark semantics)
        exprs = []
        replaced = False
        for f in self._plan.schema():
            if f.name == name:
                exprs.append(Alias(c.expr, name))
                replaced = True
            else:
                exprs.append(UnresolvedAttribute(f.name))
        if not replaced:
            exprs.append(Alias(c.expr, name))
        out, child = _extract_generators(tuple(exprs), self._plan)
        out, child = _extract_windows(out, child)
        return DataFrame(lp.Project(out, child), self.session)

    def filter(self, cond: Column) -> "DataFrame":
        return DataFrame(lp.Filter(cond.expr, self._plan), self.session)

    where = filter

    def groupBy(self, *cols: Union[str, Column]) -> "GroupedData":
        return GroupedData(self, tuple(_to_expr(c) for c in cols))

    def rollup(self, *cols: Union[str, Column]) -> "GroupedData":
        return GroupedData(self, tuple(_to_expr(c) for c in cols), "rollup")

    def cube(self, *cols: Union[str, Column]) -> "GroupedData":
        return GroupedData(self, tuple(_to_expr(c) for c in cols), "cube")

    def agg(self, *cols: Column) -> "DataFrame":
        return GroupedData(self, ()).agg(*cols)

    def sort(self, *cols: Union[str, Column]) -> "DataFrame":
        orders = []
        for c in cols:
            e = _to_expr(c)
            orders.append(e if isinstance(e, SortOrder) else SortOrder(e, True, True))
        return DataFrame(lp.Sort(tuple(orders), self._plan), self.session)

    orderBy = sort

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(lp.Limit(n, self._plan), self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(lp.Union(self._plan, other._plan), self.session)

    unionAll = union

    def join(self, other: "DataFrame", on: Union[str, List],
             how: str = "inner") -> "DataFrame":
        """USING-style join: key columns appear once in the output (from the
        left side, the right side for right joins, coalesced for full).

        ``on`` may also contain ``(left_name, right_name)`` pairs for keys
        named differently on each side; those keep both columns in the output
        (the ``df1.c1 == df2.c2`` pyspark form)."""
        how = {"leftsemi": "left_semi", "semi": "left_semi",
               "leftanti": "left_anti", "anti": "left_anti",
               "leftouter": "left", "rightouter": "right",
               "outer": "full", "fullouter": "full"}.get(how, how)
        if isinstance(on, Column):
            # pyspark's df.join(other, df.a == other.b) equality form:
            # conjunctions of EqualTo over plain column refs become key
            # pairs; anything else needs the explicit pair form (list(on)
            # on a Column would loop forever through getItem)
            on = _column_condition_to_pairs(on.expr)
        raw = [on] if isinstance(on, str) else list(on)
        if any(isinstance(k, tuple) for k in raw):
            if not all(isinstance(k, tuple) for k in raw):
                # a string key promises USING dedup/coalesce, which the
                # pair form does not do — mixing would silently change the
                # shared key's output semantics
                raise ValueError(
                    "join keys must be all strings (USING semantics) or all "
                    "(left, right) pairs; use ('k', 'k') for same-named keys "
                    "in the pair form")
            pairs = raw
            lkeys = tuple(UnresolvedAttribute(a) for a, _ in pairs)
            rkeys = tuple(UnresolvedAttribute(b) for _, b in pairs)
            return DataFrame(
                lp.Join(self._plan, other._plan, how, lkeys, rkeys),
                self.session)
        keys = raw
        lkeys = tuple(UnresolvedAttribute(k) for k in keys)
        rkeys = tuple(UnresolvedAttribute(k) for k in keys)
        joined = lp.Join(self._plan, other._plan, how, lkeys, rkeys)
        if how in ("left_semi", "left_anti"):
            return DataFrame(joined, self.session)
        out = joined.schema()
        left_n = len(self._plan.schema())
        right_schema = other._plan.schema()
        right_key_out = {out[left_n + right_schema.index_of(k)].name: k
                         for k in keys}
        exprs = []
        for i, f in enumerate(out):
            if i < left_n:
                if f.name in keys:
                    from spark_rapids_tpu.exprs import Coalesce
                    if how == "full":
                        rname = out[left_n + right_schema.index_of(f.name)].name
                        exprs.append(Alias(Coalesce(
                            (UnresolvedAttribute(f.name),
                             UnresolvedAttribute(rname))), f.name))
                    elif how == "right":
                        rname = out[left_n + right_schema.index_of(f.name)].name
                        exprs.append(Alias(UnresolvedAttribute(rname), f.name))
                    else:
                        exprs.append(UnresolvedAttribute(f.name))
                else:
                    exprs.append(UnresolvedAttribute(f.name))
            elif f.name not in right_key_out:
                exprs.append(UnresolvedAttribute(f.name))
        return DataFrame(lp.Project(tuple(exprs), joined), self.session)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(lp.Join(self._plan, other._plan, "cross", (), ()),
                         self.session)

    def repartition(self, n: int, *cols: Union[str, Column]) -> "DataFrame":
        return DataFrame(
            lp.Repartition(n, self._plan, tuple(_to_expr(c) for c in cols)),
            self.session)

    def drop(self, *names: str) -> "DataFrame":
        keep = [UnresolvedAttribute(f.name) for f in self._plan.schema()
                if f.name not in names]
        return DataFrame(lp.Project(tuple(keep), self._plan), self.session)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        exprs = tuple(Alias(UnresolvedAttribute(f.name), new)
                      if f.name == old else UnresolvedAttribute(f.name)
                      for f in self._plan.schema())
        return DataFrame(lp.Project(exprs, self._plan), self.session)

    def createOrReplaceTempView(self, name: str) -> None:
        """Register this DataFrame for SQL access via session.sql()."""
        self.session.register_view(name, self)

    def distinct(self) -> "DataFrame":
        return self.dropDuplicates()

    def dropDuplicates(self, subset: Optional[List[str]] = None) -> "DataFrame":
        """Distinct via group-by (Spark plans distinct the same way). With a
        subset, the remaining columns keep one arbitrary row per key (pyspark
        semantics), taken with first()."""
        from spark_rapids_tpu.exprs import First
        all_names = [f.name for f in self._plan.schema()]
        names = subset or all_names
        grouping = tuple(UnresolvedAttribute(n) for n in names)
        rest = tuple(Alias(First(UnresolvedAttribute(n), False), n)
                     for n in all_names if n not in names)
        agg = DataFrame(lp.Aggregate(grouping, rest, self._plan), self.session)
        if not rest:
            return agg
        # restore the original column order
        return agg.select(*all_names)

    # ---- row-level conveniences (pyspark user surface) -----------------------
    def _rows(self) -> List["Row"]:
        table = self.collect()
        cols = [table.column(i).to_pylist() for i in range(table.num_columns)]
        names = table.column_names
        return [Row(zip(names, vals)) for vals in zip(*cols)] if cols else []

    def take(self, n: int) -> List["Row"]:
        return self.limit(n)._rows()

    def head(self, n: Optional[int] = None):
        """head() -> first Row or None; head(n) -> list of Rows (pyspark)."""
        if n is None:
            rows = self.take(1)
            return rows[0] if rows else None
        return self.take(n)

    def first(self):
        return self.head()

    def show(self, n: int = 20, truncate: Union[bool, int] = True) -> None:
        """Print the first n rows formatted as pyspark does."""
        width = 20 if truncate is True else (0 if truncate is False
                                             else int(truncate))
        table = self.limit(n).collect()
        names = table.column_names
        cols = [[_show_cell(v, width) for v in table.column(i).to_pylist()]
                for i in range(table.num_columns)]
        widths = [max([len(nm)] + [len(v) for v in col])
                  for nm, col in zip(names, cols)]
        sep = "+" + "+".join("-" * w for w in widths) + "+"
        print(sep)
        print("|" + "|".join(nm.rjust(w) for nm, w in zip(names, widths))
              + "|")
        print(sep)
        for r in range(table.num_rows):
            print("|" + "|".join(cols[i][r].rjust(widths[i])
                                 for i in range(len(names))) + "|")
        print(sep)

    def printSchema(self) -> None:
        lines = ["root"]
        for f in self.schema():
            lines.append(f" |-- {f.name}: {f.dtype.value} "
                         f"(nullable = {str(f.nullable).lower()})")
        print("\n".join(lines))

    def describe(self, *cols: str) -> "DataFrame":
        """count/mean/stddev/min/max per column, values stringified in a
        'summary' table (pyspark describe). One aggregation pass."""
        from spark_rapids_tpu.api import functions as F
        schema = self.schema()
        names = list(cols) or [f.name for f in schema
                               if f.dtype.is_numeric
                               or f.dtype is DType.STRING]
        stat_fns = {"count": F.count, "mean": F.avg, "stddev": F.stddev,
                    "min": F.min, "max": F.max}
        aggs = []
        for nm in names:
            dt = schema[schema.index_of(nm)].dtype
            for stat, fn in stat_fns.items():
                if stat in ("mean", "stddev") and not dt.is_numeric:
                    continue
                aggs.append(fn(nm).alias(f"{stat}__{nm}"))
        out = self.agg(*aggs).collect()
        vals = {c: out.column(c)[0].as_py() for c in out.column_names}
        stats = []
        for stat in stat_fns:
            row = {"summary": stat}
            for nm in names:
                v = vals.get(f"{stat}__{nm}")
                row[nm] = None if v is None else str(v)
            stats.append(row)
        return self.session.create_dataframe(pa.Table.from_pylist(stats))

    def sample(self, withReplacement=None, fraction=None, seed=None
               ) -> "DataFrame":
        """Bernoulli sample WITHOUT replacement (rand(seed) < fraction).
        Accepts both pyspark call forms: sample(fraction[, seed]) and
        sample(withReplacement, fraction[, seed])."""
        from spark_rapids_tpu.api import functions as F
        if not isinstance(withReplacement, bool) and \
                withReplacement is not None:
            # sample(fraction[, seed]) form: shift arguments, but keep a
            # keyword seed= that was passed alongside a positional fraction
            withReplacement, fraction, seed = (
                None, withReplacement,
                fraction if fraction is not None else seed)
        if withReplacement:
            raise NotImplementedError(
                "sample(withReplacement=True) is not supported")
        if fraction is None:
            raise TypeError("sample() needs a fraction")
        if seed is None:
            # pyspark draws a fresh random seed per unseeded call
            import random
            seed = random.randint(0, 2**31 - 1)
        return self.filter(F.rand(int(seed)) < float(fraction))

    def toDF(self, *names: str) -> "DataFrame":
        cur = self.schema().names()
        if len(names) != len(cur):
            raise ValueError(f"toDF needs {len(cur)} names, got {len(names)}")
        exprs = tuple(Alias(UnresolvedAttribute(o), n)
                      for o, n in zip(cur, names))
        return DataFrame(lp.Project(exprs, self._plan), self.session)

    def withColumnsRenamed(self, mapping: Dict[str, str]) -> "DataFrame":
        exprs = tuple(Alias(UnresolvedAttribute(f.name),
                            mapping.get(f.name, f.name))
                      for f in self.schema())
        return DataFrame(lp.Project(exprs, self._plan), self.session)

    def unionByName(self, other: "DataFrame",
                    allowMissingColumns: bool = False) -> "DataFrame":
        from spark_rapids_tpu.api import functions as F
        mine = self.schema().names()
        theirs = other.schema().names()
        if allowMissingColumns:
            all_names = mine + [n for n in theirs if n not in mine]

            def null_as(schema, n):
                # typed null (Spark casts the null literal to the peer type)
                dt = schema[schema.index_of(n)].dtype
                return F.lit(None).cast(dt.value).alias(n)

            left = self.select(*[F.col(n) if n in mine
                                 else null_as(other.schema(), n)
                                 for n in all_names])
            right = other.select(*[F.col(n) if n in theirs
                                   else null_as(self.schema(), n)
                                   for n in all_names])
            return left.union(right)
        if set(mine) != set(theirs):
            raise ValueError(
                f"unionByName column mismatch: {mine} vs {theirs}")
        return self.union(other.select(*mine))

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows present in both (SQL INTERSECT: nulls compare
        equal, Spark semantics)."""
        return _null_safe_set_op(self, other, "intersect")

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows of self absent from other (SQL EXCEPT)."""
        return _null_safe_set_op(self, other, "subtract")

    def exceptAll(self, other: "DataFrame") -> "DataFrame":
        raise NotImplementedError(
            "exceptAll (bag semantics) is not supported; use subtract() "
            "for SQL EXCEPT (distinct) semantics")

    def dropna(self, how: str = "any", thresh: Optional[int] = None,
               subset: Optional[List[str]] = None) -> "DataFrame":
        """pyspark na.drop: NaN counts as null for float columns
        (AtLeastNNonNulls, the expression Spark plans for dropna)."""
        from spark_rapids_tpu.exprs import AtLeastNNonNulls
        if how not in ("any", "all"):
            raise ValueError(f"how must be 'any' or 'all', got {how!r}")
        names = subset or self.schema().names()
        need = thresh if thresh is not None else (
            len(names) if how == "any" else 1)
        cond = AtLeastNNonNulls(
            need, tuple(UnresolvedAttribute(n) for n in names))
        return DataFrame(lp.Filter(cond, self._plan), self.session)

    def fillna(self, value, subset: Optional[List[str]] = None
               ) -> "DataFrame":
        from spark_rapids_tpu.api import functions as F
        schema = self.schema()
        names = subset or [f.name for f in schema]
        by_col = value if isinstance(value, dict) else {n: value
                                                        for n in names}
        exprs = []
        for f in schema:
            v = by_col.get(f.name)
            compatible = v is not None and (
                (f.dtype.is_numeric and isinstance(v, (int, float))
                 and not isinstance(v, bool))
                or (f.dtype is DType.STRING and isinstance(v, str))
                or (f.dtype is DType.BOOLEAN and isinstance(v, bool)))
            if compatible:
                src: Any = UnresolvedAttribute(f.name)
                if f.dtype.is_floating and isinstance(v, (int, float)):
                    # pyspark na.fill also replaces NaN in float columns
                    from spark_rapids_tpu.exprs import NaNvl
                    src = NaNvl(src, F.lit(float(v)).expr)
                filled = Coalesce((src, F.lit(v).expr))
                if f.dtype.is_numeric and isinstance(v, float):
                    # Spark casts the result BACK to the column type, so a
                    # double fill never widens an integer column
                    from spark_rapids_tpu.exprs.cast import Cast
                    filled = Cast(filled, f.dtype)
                exprs.append(Alias(filled, f.name))
            else:
                exprs.append(UnresolvedAttribute(f.name))
        return DataFrame(lp.Project(tuple(exprs), self._plan), self.session)

    # ---- caching -------------------------------------------------------------
    def cache(self) -> "DataFrame":
        """Mark this DataFrame's plan for caching (lazy, like Spark): the
        first action materializes its batches into the spillable device
        store; later plans containing this subtree scan the cache."""
        return self.persist()

    def persist(self, storage_level: Optional[str] = None) -> "DataFrame":
        # every Spark storage level lands in the same tiered store here:
        # DEVICE first, spilling host->disk under pressure
        self.session.cache_manager.add(self._plan)
        return self

    def unpersist(self, blocking: bool = False) -> "DataFrame":
        self.session.cache_manager.remove(self._plan)
        return self

    @property
    def is_cached(self) -> bool:
        return self.session.cache_manager.lookup(self._plan) is not None

    # ---- actions -------------------------------------------------------------
    def _executed_plan(self, prepared=None) -> PhysicalExec:
        from spark_rapids_tpu import config as _cfg
        logical = (prepared if prepared is not None
                   else self.session.cache_manager.prepare(self._plan))
        cpu_plan = plan_physical(logical, self.session.conf)
        overrides = TpuOverrides(self.session.conf)
        final = overrides.apply(cpu_plan)
        if self.session.conf.get(_cfg.MESH_ENABLED):
            from spark_rapids_tpu.plan.mesh_rewrite import mesh_rewrite
            final = mesh_rewrite(final, self.session.conf)
        self.session.last_explain = overrides.last_explain
        self.session.last_plan = final
        return final

    def _run_partitions(self, final: PhysicalExec,
                        capture_device: bool = False, query=None) -> List:
        """Execute and collect per-partition results as arrow tables. With
        ``capture_device`` (cache materialization), a single-process plan
        whose root is the download transition instead returns the raw
        DeviceBatches — the cache stores them without a device->host->device
        round trip."""
        from spark_rapids_tpu.memory.device_manager import DeviceManager
        from spark_rapids_tpu import config as _cfg
        # cluster + adaptive compose: the stage scheduler coalesces reduce
        # tasks from observed MapStatus sizes (parallel/cluster.py
        # _coalesce_stage_reads — the GpuCustomShuffleReaderExec role)
        if (self.session.conf.get(_cfg.CLUSTER_EXECUTORS) >= 1
                and not self.session.conf.get(_cfg.MESH_ENABLED)):
            from spark_rapids_tpu.parallel.cluster import cluster_scheduler_for
            from spark_rapids_tpu.utils.metrics import (recompute_delta,
                                                        recompute_snapshot)
            # the cluster driver is the only executor of lineage recomputes,
            # and it returns before the single-process metrics block below —
            # snapshot around the run so a query served through the stage
            # scheduler still records its fault-recovery story
            recompute_before = recompute_snapshot()
            tables = cluster_scheduler_for(self.session).run(final)
            if tables is not None:
                if self.session.conf.get(_cfg.METRICS_ENABLED):
                    snap = {"shuffle": recompute_delta(recompute_before)}
                    if query is not None:
                        query.record_exec_metrics(snap)
                    self.session.last_metrics = snap
                if query is not None:
                    for t in tables:
                        query.emit_batch(t)
                return tables
            # plan not stageable (CPU exchanges): single-process fallback
        dm = DeviceManager.initialize(self.session.conf)
        cleanups: List = []
        tables = []
        # spark.rapids.tpu.trace.enabled: structured span tracing for the
        # whole action (utils/tracing.py — per-exec spans, transfer/memory/
        # serving layers, EXPLAIN ANALYZE and the Chrome export) plus the
        # action-level jax.profiler range (NVTX analog); when metrics are
        # on, per-operator counters land in session.last_metrics
        import contextlib
        from spark_rapids_tpu.utils import tracing as _tracing
        from spark_rapids_tpu.utils.metrics import (NamedRange,
                                                    action_depth_scope,
                                                    adaptive_delta,
                                                    adaptive_snapshot,
                                                    memory_delta,
                                                    memory_snapshot,
                                                    recompute_delta,
                                                    recompute_snapshot,
                                                    serving_delta,
                                                    serving_snapshot,
                                                    transfer_delta,
                                                    transfer_snapshot)
        trace = self.session.conf.get(_cfg.TRACE_ENABLED)
        if trace:
            _tracing.TRACER.configure(
                self.session.conf.get(_cfg.TRACE_BUFFER_SPANS))
        trace_scope = (_tracing.TRACER.activate() if trace
                       else contextlib.nullcontext())
        transfer_before = transfer_snapshot()
        memory_before = memory_snapshot()
        serving_before = serving_snapshot()
        recompute_before = recompute_snapshot()
        adaptive_before = adaptive_snapshot()
        import time as _time
        # stable node ordinals: the span/EXPLAIN-ANALYZE key (pre-order,
        # matching the f"{i}:{name}" keys of session.last_metrics)
        for i, nd in enumerate(_iter_execs(final)):
            nd.plan_id = i
        tenant = query.tenant if query is not None else "default"
        cancel = query.check_cancelled if query is not None else None
        # one stack for the action-scoped contexts (depth attribution +
        # tracer activation): entered before the admission wait so the
        # wait is traced, unwound in the finally below even when a
        # cleanup fn raises — a stuck activation would leave the
        # process-wide tracer on for every later query
        scopes = contextlib.ExitStack()
        depth_holder = scopes.enter_context(action_depth_scope())
        scopes.enter_context(trace_scope)
        trace_mark = _tracing.TRACER.mark()
        t_wall = _time.perf_counter()
        t_admit = _time.perf_counter()
        t_admit_ns = _time.perf_counter_ns()
        try:
            # device-admission throttle for the whole task (GpuSemaphore
            # analog), fair-shared by tenant; a cancelled query blocked on
            # admission unwinds here instead of waiting for a permit
            with dm.semaphore.held(tenant=tenant, cancel_check=cancel), \
                    NamedRange("tpu-sql-action", trace=trace):
                _tracing.record("serving.admission_wait", "serving",
                                t_admit_ns,
                                _time.perf_counter_ns() - t_admit_ns,
                                {"tenant": tenant})
                if query is not None:
                    query.note_admission_wait(_time.perf_counter() - t_admit)
                if self.session.conf.get(_cfg.ADAPTIVE_ENABLED) and \
                        not any(getattr(nd, "is_mesh", False)
                                for nd in _iter_execs(final)):
                    # mesh operators adapt inside their execs (observed
                    # sizes precede every exchange program); the host-side
                    # stage rewrite runs whenever the plan actually stayed
                    # on host exchanges (incl. mesh.enabled on one device)
                    from spark_rapids_tpu.plan.adaptive import adaptive_rewrite
                    stage_ctx = ExecContext(self.session.conf, partition_id=0,
                                            num_partitions=1,
                                            device_manager=dm,
                                            cleanups=cleanups, query=query)
                    final = adaptive_rewrite(final, stage_ctx)
                    self.session.last_plan = final
                    for i, nd in enumerate(_iter_execs(final)):
                        nd.plan_id = i      # rewritten plan: fresh ordinals
                from spark_rapids_tpu.execs.tpu_execs import DeviceToHostExec
                if (capture_device and isinstance(final, DeviceToHostExec)
                        and not any(getattr(nd, "is_mesh", False)
                                    for nd in _iter_execs(final))):
                    final = final.children[0]   # keep batches device-resident
                    for p in range(final.num_partitions):
                        ctx = ExecContext(self.session.conf, partition_id=p,
                                          num_partitions=final.num_partitions,
                                          device_manager=dm, cleanups=cleanups,
                                          query=query)
                        for b in final.execute(ctx):
                            ctx.check_cancelled()
                            tables.append(b)
                    return tables
                stream = (
                    isinstance(final, DeviceToHostExec)
                    and self.session.conf.get(_cfg.TRANSFER_STREAMING_COLLECT)
                    and not any(getattr(nd, "is_mesh", False)
                                for nd in _iter_execs(final)))
                if stream:
                    # streaming collect: each result batch's D2H starts the
                    # moment its program is dispatched (copy_to_host_async)
                    # and overlaps the remaining compute; at most
                    # transfer.maxInflight downloads are outstanding, and
                    # batch order is preserved by resolving in FIFO order
                    from spark_rapids_tpu.columnar.transfer import \
                        start_download
                    child = final.children[0]
                    max_inflight = self.session.conf.get(
                        _cfg.TRANSFER_MAX_INFLIGHT)
                    pending: List = []
                    for p in range(final.num_partitions):
                        ctx = ExecContext(self.session.conf, partition_id=p,
                                          num_partitions=final.num_partitions,
                                          device_manager=dm,
                                          cleanups=cleanups, query=query)
                        for db in child.execute(ctx):
                            ctx.check_cancelled()
                            final.count_output(db.num_rows)
                            pending.append(start_download(db))
                            while len(pending) > max_inflight:
                                t = pending.pop(0).result()
                                tables.append(t)
                                # streaming partial results: each batch
                                # reaches the serving stream the moment
                                # its async D2H resolves — before the
                                # final batch exists
                                if query is not None:
                                    query.emit_batch(t)
                    for pd_ in pending:
                        t = pd_.result()
                        tables.append(t)
                        if query is not None:
                            query.emit_batch(t)
                else:
                    for p in range(final.num_partitions):
                        ctx = ExecContext(self.session.conf, partition_id=p,
                                          num_partitions=final.num_partitions,
                                          device_manager=dm,
                                          cleanups=cleanups, query=query)
                        for b in final.execute(ctx):
                            ctx.check_cancelled()
                            t = b.to_arrow()
                            tables.append(t)
                            if query is not None:
                                query.emit_batch(t)
        finally:
            try:
                for fn in cleanups:
                    fn()
            finally:
                self.session.last_action_wall_s = (_time.perf_counter()
                                                   - t_wall)
                scopes.close()
            if self.session.conf.get(_cfg.METRICS_ENABLED):
                # build the whole snapshot FIRST, then publish with ONE
                # attribute store: two interleaved actions used to mutate
                # the shared dict after assignment, so a reader could see
                # the other query's half-written metrics. The per-query
                # handle is the first-class record; the session global
                # stays as a last-action alias for compatibility.
                snap = {f"{i}:{nd.name}": nd.metrics.snapshot()
                        for i, nd in enumerate(_iter_execs(final))}
                # host-link story for the whole action, incl. derived GB/s
                # (process-global counters: under concurrent queries the
                # per-action delta includes overlapping queries' traffic)
                snap["transfer"] = transfer_delta(transfer_before)
                # out-of-core story for the action: pressure events, grace
                # partitions, recursion peak, bytes spilled per tier. The
                # recursion peak is the ACTION-SCOPED maximum (thread/
                # query-bound attribution, not the shared re-armed global
                # whose concurrent-overlap misattribution PR 11 documented)
                snap["memory"] = memory_delta(memory_before,
                                              recursion_peak=(
                                                  depth_holder.peak))
                # serving story: wire bytes/batches streamed, preemptions,
                # footprint-admission rejections over the action's window
                snap["serving"] = serving_delta(serving_before)
                # fault-recovery story for the action: lineage-scoped stage
                # recomputes the cluster driver ran (and escalations to the
                # failover path) while this action was collecting
                snap["shuffle"] = recompute_delta(recompute_before)
                # adaptive story: runtime rewrites this action's AQE pass
                # applied (skew splits, coalesced partitions, broadcast
                # switches, re-fused stages)
                snap["adaptive"] = adaptive_delta(adaptive_before)
                if query is not None:
                    query.record_exec_metrics(snap)
                self.session.last_metrics = snap
            if trace:
                # the action's span window: kept on the session for
                # introspection and exported per trace.export.path (the
                # file is rewritten per action — last-action semantics)
                records = _tracing.TRACER.since(trace_mark)
                self.session.last_trace = records
                export = self.session.conf.get(_cfg.TRACE_EXPORT_PATH)
                if export:
                    _tracing.export_chrome(
                        records, export,
                        metadata={"action_wall_s": round(
                            self.session.last_action_wall_s, 6)})
        return tables

    def collect(self) -> pa.Table:
        return self._collect()

    def _collect(self, query=None, final: Optional[PhysicalExec] = None
                 ) -> pa.Table:
        """collect() with serving context: ``query`` is the QueryHandle a
        scheduler worker is driving (cancellation checkpoints, fair-share
        tenant, per-query metric snapshot); ``final`` reuses an already-
        planned physical tree."""
        if final is None:
            final = self._executed_plan()
        tables = self._run_partitions(final, query=query)
        schema = self._plan.schema().to_pa()
        if not tables:
            return schema.empty_table()
        return pa.concat_tables(tables)

    def to_pandas(self):
        return self.collect().to_pandas()

    toPandas = to_pandas

    def count(self) -> int:
        from spark_rapids_tpu.api.functions import count
        return self.agg(count().alias("count")).collect().column(0)[0].as_py()

    def schema(self) -> Schema:
        return self._plan.schema()

    @property
    def columns(self) -> List[str]:
        return self._plan.schema().names()

    def explain(self, print_out: bool = True) -> str:
        # substitute cached subtrees (no materialization: explain is free)
        logical = self.session.cache_manager.substitute(self._plan)
        cpu_plan = plan_physical(logical, self.session.conf)
        overrides = TpuOverrides(self.session.conf)
        final = overrides.apply(cpu_plan)
        text = overrides.last_explain + "\n\nPhysical plan:\n" + final.tree_string()
        if print_out:
            print(text)
        return text

    def write_parquet(self, path: str, compression: str = "snappy") -> None:
        from spark_rapids_tpu.io.parquet import write_parquet
        write_parquet(self.collect(), path, compression)

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)


class DataFrameWriter:
    """df.write API (DataFrameWriter analog) driving the columnar write path
    (GpuDataWritingCommandExec / GpuFileFormatWriter)."""

    def __init__(self, df: DataFrame):
        self._df = df
        self._mode = "error"
        self._partition_by: List[str] = []
        self._options: Dict[str, str] = {}

    def mode(self, m: str) -> "DataFrameWriter":
        m = {"errorifexists": "error", "default": "error"}.get(m.lower(),
                                                               m.lower())
        if m not in ("error", "overwrite", "append", "ignore"):
            raise ValueError(f"unknown save mode {m!r}")
        self._mode = m
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partition_by = partitionBy

    def option(self, k: str, v) -> "DataFrameWriter":
        self._options[k] = str(v)
        return self

    def _save(self, fmt: str, path: str):
        from spark_rapids_tpu.io.write_exec import WriteSpec
        from spark_rapids_tpu.io.write_exec import CpuWriteFilesExec
        max_records = int(self._options.get("maxRecordsPerFile", "0"))
        opts = tuple((k, v) for k, v in self._options.items()
                     if k != "maxRecordsPerFile")
        spec = WriteSpec(fmt, path, self._mode, tuple(self._partition_by),
                         opts, max_records)
        df = DataFrame(lp.WriteFiles(spec, self._df._plan), self._df.session)
        final = df._executed_plan()
        df._run_partitions(final)
        # surface write stats from whichever engine ran the command
        from spark_rapids_tpu.execs.mesh_execs import MeshWriteFilesExec
        for node in _iter_execs(final):
            if isinstance(node, (CpuWriteFilesExec, MeshWriteFilesExec)):
                return node.stats
        return None

    def parquet(self, path: str):
        return self._save("parquet", path)

    def orc(self, path: str):
        return self._save("orc", path)

    def csv(self, path: str):
        return self._save("csv", path)


def _column_condition_to_pairs(e) -> List[tuple]:
    """EqualTo conjunctions over column refs -> [(left_name, right_name)...];
    raises a clear TypeError for anything richer."""
    from spark_rapids_tpu.exprs.predicates import And, EqualTo
    from spark_rapids_tpu.exprs.core import BoundReference

    def name_of(x):
        if isinstance(x, UnresolvedAttribute):
            return x.name
        if isinstance(x, BoundReference) and x.ref_name:
            return x.ref_name
        return None

    if isinstance(e, And):
        return (_column_condition_to_pairs(e.l)
                + _column_condition_to_pairs(e.r))
    if isinstance(e, EqualTo):
        a, b = name_of(e.l), name_of(e.r)
        if a and b:
            return [(a, b)]
    raise TypeError(
        "join(on=Column) supports only equality conjunctions of plain "
        "columns (df.a == other.b [& ...]); use string keys or "
        "(left, right) pairs otherwise")


def _iter_execs(plan: PhysicalExec):
    yield plan
    for c in plan.children:
        yield from _iter_execs(c)


def _tree_has(e, cls) -> bool:
    if isinstance(e, cls):
        return True
    return any(_tree_has(c, cls) for c in e.children)


def _null_safe_zero(dt):
    """A valid stand-in value of the key's type for coalescing null keys; rows
    are disambiguated by the paired isnull flag, so the value itself is
    arbitrary."""
    import datetime
    from spark_rapids_tpu.columnar.dtypes import DType
    if dt is DType.STRING:
        return ""
    if dt is DType.BOOLEAN:
        return False
    if dt is DType.DATE:
        return datetime.date(1970, 1, 1)
    if dt is DType.TIMESTAMP:
        return datetime.datetime(1970, 1, 1)
    if dt.is_floating:
        return 0.0
    return 0


def _null_safe_key_join(left: "DataFrame", right: "DataFrame",
                        keynames: List[str]) -> "DataFrame":
    """Inner join on keys where null keys match each other (eqNullSafe): each
    key joins as the pair (coalesce(k, zero), isnull(k)). The right side's key
    and helper columns are dropped afterwards."""
    from spark_rapids_tpu.api import functions as F
    lschema = left.schema()
    pairs = []
    drop_after = []
    for j, kn in enumerate(keynames):
        dt = lschema[lschema.index_of(kn)].dtype
        zero = F.lit(_null_safe_zero(dt))
        lv, ln = f"__jl{j}_v", f"__jl{j}_n"
        rv, rn = f"__jr{j}_v", f"__jr{j}_n"
        rk = f"__jr{j}_k"
        left = (left.withColumn(lv, F.coalesce(F.col(kn), zero))
                .withColumn(ln, F.col(kn).isNull()))
        right = (right.withColumnRenamed(kn, rk)
                 .withColumn(rv, F.coalesce(F.col(rk), zero))
                 .withColumn(rn, F.col(rk).isNull()))
        pairs += [(lv, rv), (ln, rn)]
        drop_after += [lv, ln, rv, rn, rk]
    return left.join(right, pairs).drop(*drop_after)


class GroupedData:
    def __init__(self, df: DataFrame, grouping, mode: str = "groupby"):
        self._df = df
        self._grouping = grouping
        self._mode = mode
        self._pivot: Optional[tuple] = None

    def pivot(self, col_name: str, values: Optional[List] = None
              ) -> "GroupedData":
        """Spark pivot: one output column per pivot value. With no values
        list, the distinct pivot values are queried first (exactly what
        Spark does, which is why it recommends passing them)."""
        if self._mode != "groupby":
            raise NotImplementedError("pivot with rollup/cube")
        if values is None:
            vals = (self._df.select(col_name).distinct().collect()
                    .column(0).to_pylist())
            values = sorted([v for v in vals if v is not None],
                            key=lambda v: (str(type(v)), v))
            if any(v is None for v in vals):
                values.insert(0, None)      # Spark's 'null' pivot column
        g = GroupedData(self._df, self._grouping)
        g._pivot = (col_name, list(values))
        return g

    def agg(self, *cols: Column) -> DataFrame:
        if self._pivot is not None:
            return self._pivot_agg(cols)
        return self._agg_impl(cols)

    def _pivot_agg(self, cols) -> DataFrame:
        """Pivot lowering (Catalyst's single-aggregation pivot shape):
        each aggregate becomes one conditional aggregate per pivot value —
        agg(when(p == v, child)) AS <v>[_<aggname>]."""
        from spark_rapids_tpu.api import functions as F
        from spark_rapids_tpu.exprs.core import Expression
        pcol, values = self._pivot
        from spark_rapids_tpu.exprs.aggregates import (AggregateFunction,
                                                       DistinctAgg)
        aggs = []
        for v in values:
            for c in cols:
                e = c.expr
                name_suffix = None
                if isinstance(e, Alias):
                    name_suffix = e.name
                    e = e.c
                if not isinstance(e, AggregateFunction):
                    raise NotImplementedError(
                        "pivot aggregates must be plain aggregate "
                        "functions (optionally aliased), e.g. sum(col)")

                # rewrite the aggregate's input to when(p == v, input);
                # a null pivot value matches with isNull (Spark's 'null'
                # pivot column)
                def gate(child: Expression, v=v) -> Expression:
                    match = (F.col(pcol).isNull() if v is None
                             else F.col(pcol) == F.lit(v))
                    return (F.when(match, Column(child))
                            .otherwise(F.lit(None))).expr

                if isinstance(e, DistinctAgg):
                    # gate INSIDE the distinct wrapper so the rewrite in
                    # _distinct_agg still sees an aggregate at the top
                    gated = DistinctAgg(e.inner.map_children(gate))
                else:
                    gated = e.map_children(gate)
                base = "null" if v is None else str(v)
                name = (base if len(cols) == 1 and name_suffix is None
                        else f"{base}_{name_suffix or e.name_hint}")
                aggs.append(Column(Alias(gated, name)))
        return GroupedData(self._df, self._grouping).agg(*aggs)

    def _agg_impl(self, cols) -> DataFrame:
        from spark_rapids_tpu.exprs import DistinctAgg
        aggs = []
        for i, c in enumerate(cols):
            e = c.expr
            if not isinstance(e, Alias):
                e = Alias(e, e.name_hint)
            aggs.append(e)
        if any(isinstance(a.c, DistinctAgg) for a in aggs):
            if self._mode != "groupby":
                raise NotImplementedError(
                    "distinct aggregates are not supported with rollup/cube")
            return self._distinct_agg(aggs)
        for a in aggs:
            if _tree_has(a.c, DistinctAgg):
                raise NotImplementedError(
                    "distinct aggregate must be a top-level aggregate "
                    "expression (optionally aliased)")
        if self._mode != "groupby":
            return self._grouping_sets_agg(tuple(aggs))
        return DataFrame(
            lp.Aggregate(self._grouping, tuple(aggs), self._df._plan),
            self._df.session)

    def _distinct_agg(self, aggs) -> DataFrame:
        """Rewrite an aggregation containing DISTINCT aggregates into
        dedup-then-aggregate subplans recombined on the grouping keys — the
        join-based form of Spark's RewriteDistinctAggregates (the reference GPU
        plugin falls back to CPU for these; here both engines run the rewrite).

        Each distinct agg becomes: select(keys, child) -> dropDuplicates ->
        groupBy(keys).agg(inner). Group sets are identical across subplans (every
        subplan sees every input row), so an inner join on the keys recombines
        them; keys are joined null-safely (coalesce + isnull flag pairs, the
        standard eqNullSafe lowering) because a group key may be null."""
        from spark_rapids_tpu.exprs import DistinctAgg
        df = self._df
        keys = list(self._grouping)
        keynames = [k.name_hint for k in keys]
        out_names = [a.name_hint for a in aggs]
        if len(set(keynames + out_names)) != len(keynames) + len(out_names):
            raise ValueError(
                "duplicate output names in a DISTINCT aggregation: "
                f"{keynames + out_names!r} — alias the colliding columns")

        # Subplans are recombined BY NAME, so keys and agg outputs get
        # generated unique names (__gk{i}/__da{i}); user-facing names come
        # back only in the final select.
        gk = [f"__gk{i}" for i in range(len(keys))]
        da = [f"__da{i}" for i in range(len(aggs))]
        key_aliases = tuple(Alias(k, g) for k, g in zip(keys, gk))

        regular = [(i, a) for i, a in enumerate(aggs)
                   if not isinstance(a.c, DistinctAgg)]
        for _, a in regular:
            if _tree_has(a.c, DistinctAgg):
                raise NotImplementedError(
                    "distinct aggregate must be a top-level aggregate "
                    "expression (optionally aliased)")
        parts: List[DataFrame] = []
        if regular:
            parts.append(GroupedData(df, key_aliases).agg(
                *[Column(Alias(a.c, da[i])) for i, a in regular]))
        for i, a in enumerate(aggs):
            if not isinstance(a.c, DistinctAgg):
                continue
            inner = a.c.inner
            vname = f"__dv{i}"
            sel = [Column(ka) for ka in key_aliases]
            sel.append(Column(Alias(inner.child, vname)))
            dd = df.select(*sel).dropDuplicates()
            rebuilt = inner.map_children(
                lambda _e: UnresolvedAttribute(vname))
            grouping = tuple(UnresolvedAttribute(g) for g in gk)
            parts.append(GroupedData(dd, grouping).agg(
                Column(Alias(rebuilt, da[i]))))

        result = parts[0]
        for p in parts[1:]:
            result = (_null_safe_key_join(result, p, gk) if gk
                      else result.crossJoin(p))
        final = [Column(Alias(UnresolvedAttribute(g), kn))
                 for g, kn in zip(gk, keynames)]
        final += [Column(Alias(UnresolvedAttribute(d), on))
                  for d, on in zip(da, out_names)]
        return result.select(*final)

    def _grouping_sets_agg(self, aggs) -> DataFrame:
        """rollup/cube via Expand (Spark's Expand + grouping-id plan shape):
        each row replicates once per grouping set with rolled-up keys nulled;
        grouping by (expanded keys, grouping id) keeps real nulls distinct
        from rolled-up nulls; a final projection drops the internal columns."""
        from spark_rapids_tpu.columnar.dtypes import DType
        from spark_rapids_tpu.exprs import Literal
        keys = list(self._grouping)
        n = len(keys)
        if self._mode == "rollup":
            # (all keys), (all but last), ..., (none)
            masks = [[j < n - i for j in range(n)] for i in range(n + 1)]
        else:  # cube: every subset
            masks = [[not ((i >> (n - 1 - j)) & 1) for j in range(n)]
                     for i in range(2 ** n)]
        cs = self._df._plan.schema()
        kn = [f"_gset{i}" for i in range(n)]
        names = tuple(f.name for f in cs) + tuple(kn) + ("_gid",)
        projections = []
        for mask in masks:
            gid = 0
            row = [UnresolvedAttribute(f.name) for f in cs]
            for j, (e, inc) in enumerate(zip(keys, mask)):
                row.append(e if inc else Literal(None, DType.NULL))
                if not inc:
                    gid |= 1 << (n - 1 - j)
            row.append(Literal(gid, DType.INT))
            projections.append(tuple(row))
        expand = lp.Expand(tuple(projections), names, self._df._plan)
        grouping = tuple(UnresolvedAttribute(k) for k in kn) + (
            UnresolvedAttribute("_gid"),)
        agg = lp.Aggregate(grouping, aggs, expand)
        final = tuple(
            Alias(UnresolvedAttribute(k), keys[i].name_hint)
            for i, k in enumerate(kn)
        ) + tuple(UnresolvedAttribute(a.name_hint) for a in aggs)
        return DataFrame(lp.Project(final, agg), self._df.session)

    def count(self) -> DataFrame:
        from spark_rapids_tpu.api.functions import count
        return self.agg(count().alias("count"))

    def _simple(self, fname, *cols) -> DataFrame:
        from spark_rapids_tpu.api import functions as F
        fn = getattr(F, fname)
        names = cols or [f.name for f in self._df._plan.schema()
                         if f.dtype.is_numeric]
        return self.agg(*[fn(n).alias(f"{fname}({n})") for n in names])

    def sum(self, *cols) -> DataFrame:
        return self._simple("sum", *cols)

    def avg(self, *cols) -> DataFrame:
        return self._simple("avg", *cols)

    def min(self, *cols) -> DataFrame:
        return self._simple("min", *cols)

    def max(self, *cols) -> DataFrame:
        return self._simple("max", *cols)


class DataFrameReader:
    def __init__(self, session: "TpuSession"):
        self.session = session
        self._options: Dict[str, str] = {}

    def option(self, k: str, v) -> "DataFrameReader":
        self._options[k] = str(v)
        return self

    def _scan(self, fmt: str, paths, infer_schema) -> DataFrame:
        """Discover hive partitions, then full read schema = data schema from
        the first file ++ partition columns."""
        from spark_rapids_tpu.columnar.dtypes import Field as SField
        from spark_rapids_tpu.io.datasource import discover_partitioned_files
        files, pschema = discover_partitioned_files(paths, fmt)
        if not files:
            raise FileNotFoundError(f"no {fmt} files under {paths}")
        data_schema = infer_schema(files[0].path)
        full = Schema(list(data_schema.fields)
                      + [SField(f.name, f.dtype, f.nullable) for f in pschema])
        return DataFrame(lp.FileScan(fmt, tuple(paths), full,
                                     tuple(self._options.items()),
                                     files=files, partition_schema=pschema),
                         self.session)

    def parquet(self, *paths: str) -> DataFrame:
        import pyarrow.parquet as pq
        return self._scan("parquet", paths,
                          lambda p: Schema.from_pa(pq.read_schema(p)))

    def csv(self, *paths: str, schema: Optional[Schema] = None) -> DataFrame:
        from spark_rapids_tpu.io.csv import infer_csv_schema
        return self._scan(
            "csv", paths,
            lambda p: schema or infer_csv_schema(p, self._options))

    def orc(self, *paths: str) -> DataFrame:
        import pyarrow.orc as po
        return self._scan("orc", paths,
                          lambda p: Schema.from_pa(po.ORCFile(p).schema))


class TpuSession:
    """SparkSession analog wired to the TPU accelerator (SQLPlugin +
    RapidsDriverPlugin role: holds the conf, applies the overrides rule)."""

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        from spark_rapids_tpu.memory.df_cache import CacheManager
        self.conf = TpuConf(conf or {})
        self.last_explain: str = ""
        self.last_plan: Optional[PhysicalExec] = None
        #: per-operator metric snapshots of the LAST action, filled when
        #: spark.rapids.tpu.metrics.enabled (SQLMetrics reporting analog).
        #: Under concurrent serving this is a last-writer-wins alias —
        #: read QueryHandle.exec_metrics for a specific query's snapshot.
        self.last_metrics: Dict[str, Dict[str, int]] = {}
        #: wall-clock seconds of the last action (EXPLAIN ANALYZE header)
        self.last_action_wall_s: float = 0.0
        #: span window of the last TRACED action (trace.enabled) — the
        #: records export_chrome() writes; last-writer-wins like
        #: last_metrics (per-query spans live on the QueryHandle)
        self.last_trace: list = []
        self._views: Dict[str, DataFrame] = {}
        #: guards the view table: concurrent serve.register handlers (the
        #: transport worker pool) register views while SQL planning reads
        #: them (R012)
        self._views_lock = threading.Lock()
        self.cache_manager = CacheManager(self)
        self._scheduler = None
        self._scheduler_lock = threading.Lock()

    def clear_cache(self) -> None:
        """Drop every cached DataFrame (spark.catalog.clearCache analog)."""
        self.cache_manager.clear()

    clearCache = clear_cache

    def explain_analyze(self, print_out: bool = False) -> str:
        """EXPLAIN ANALYZE of the LAST action: the physical plan annotated
        with each node's OBSERVED rows / batches / wall / self time / spill
        (Spark-UI style). Requires the action to have run with
        ``trace.enabled`` — without it the tree renders without stats.
        Per-node self times sum (within driver slack) to the action wall."""
        if self.last_plan is None:
            raise RuntimeError("no action has run yet")
        text = (f"== Physical plan with observed stats "
                f"(action wall {self.last_action_wall_s:.3f}s) ==\n"
                + self.last_plan.tree_string(analyze=True))
        if print_out:
            print(text)
        return text

    # ---- concurrent serving -----------------------------------------------
    @property
    def scheduler(self):
        """The session's query scheduler (serving/scheduler.py), created on
        first use with the session's serving.* conf."""
        with self._scheduler_lock:
            if self._scheduler is None:
                from spark_rapids_tpu.serving.scheduler import \
                    SessionScheduler
                self._scheduler = SessionScheduler(self)
            return self._scheduler

    def submit(self, query, tenant: str = "default",
               timeout: Optional[float] = None, label: Optional[str] = None):
        """Submit a DataFrame or SQL string for concurrent execution;
        returns a QueryHandle immediately (state QUEUED). ``handle.
        result()`` blocks for the collected table; ``handle.cancel()``
        requests cooperative cancellation; per-query metrics live in
        ``handle.snapshot()`` / ``handle.exec_metrics``."""
        return self.scheduler.submit(query, tenant=tenant, timeout=timeout,
                                     label=label)

    # ---- SQL frontend -----------------------------------------------------
    def table(self, name: str) -> "DataFrame":
        with self._views_lock:
            try:
                return self._views[name.lower()]
            except KeyError:
                raise KeyError(
                    f"table or view not found: {name}") from None

    def register_view(self, name: str, df: "DataFrame") -> None:
        with self._views_lock:
            self._views[name.lower()] = df

    def sql(self, query: str) -> "DataFrame":
        """Run a SQL query over registered temp views (the role Catalyst's
        parser/analyzer plays for the reference — its benchmark suites feed
        raw SQL, TpcdsLikeSpark.scala:30)."""
        from spark_rapids_tpu.sql.parser import parse_sql
        from spark_rapids_tpu.sql.planner import SqlPlanner
        stmt = parse_sql(query)
        df, _names = SqlPlanner(self).plan(stmt)
        return df

    @staticmethod
    def builder() -> "TpuSessionBuilder":
        return TpuSessionBuilder()

    def create_dataframe(self, data, schema: Optional[Sequence[str]] = None
                         ) -> DataFrame:
        if isinstance(data, pa.Table):
            table = data
        elif hasattr(data, "to_dict") and hasattr(data, "columns"):  # pandas
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, dict):
            table = pa.table(data)
        else:  # rows
            import pandas as pd
            table = pa.Table.from_pandas(pd.DataFrame(data, columns=schema),
                                         preserve_index=False)
        return DataFrame(lp.LocalRelation(table), self)

    createDataFrame = create_dataframe

    def range(self, start: int, end: Optional[int] = None, step: int = 1
              ) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(lp.Range(start, end, step), self)

    @property
    def read(self) -> DataFrameReader:
        return DataFrameReader(self)

    def set_conf(self, key: str, value) -> None:
        self.conf = self.conf.with_overrides({key: value})


class TpuSessionBuilder:
    def __init__(self):
        self._conf: Dict[str, Any] = {}

    def config(self, key: str, value) -> "TpuSessionBuilder":
        self._conf[key] = value
        return self

    def getOrCreate(self) -> TpuSession:
        return TpuSession(self._conf)
