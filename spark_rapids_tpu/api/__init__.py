from spark_rapids_tpu.api.column import Column
from spark_rapids_tpu.api.dataframe import DataFrame, GroupedData, TpuSession
from spark_rapids_tpu.api import functions
from spark_rapids_tpu.api.window import Window, WindowSpec
