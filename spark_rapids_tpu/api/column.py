"""Column wrapper: the user-facing expression builder (pyspark-Column-style API
over the expression layer)."""
from __future__ import annotations

from typing import Any, Union

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs import (Add, Alias, And, BitwiseAnd, BitwiseOr,
                                    BitwiseXor, Cast, Contains, Divide, EndsWith,
                                    EqualNullSafe, EqualTo, Expression, GreaterThan,
                                    GreaterThanOrEqual, In, IsNan, IsNotNull, IsNull,
                                    LessThan, LessThanOrEqual, Like, Literal,
                                    Multiply, Not, NotEqual, Or, Pmod, Remainder,
                                    SortOrder, StartsWith, Subtract, UnaryMinus,
                                    UnresolvedAttribute)


def _expr(v: Any) -> Expression:
    if isinstance(v, Column):
        return v.expr
    if isinstance(v, Expression):
        return v
    return Literal.of(v)


class Column:
    def __init__(self, expr: Expression):
        self.expr = expr

    # arithmetic ------------------------------------------------------------
    def __add__(self, o): return Column(Add(self.expr, _expr(o)))
    def __radd__(self, o): return Column(Add(_expr(o), self.expr))
    def __sub__(self, o): return Column(Subtract(self.expr, _expr(o)))
    def __rsub__(self, o): return Column(Subtract(_expr(o), self.expr))
    def __mul__(self, o): return Column(Multiply(self.expr, _expr(o)))
    def __rmul__(self, o): return Column(Multiply(_expr(o), self.expr))
    def __truediv__(self, o): return Column(Divide(self.expr, _expr(o)))
    def __rtruediv__(self, o): return Column(Divide(_expr(o), self.expr))
    def __mod__(self, o): return Column(Remainder(self.expr, _expr(o)))
    def __neg__(self): return Column(UnaryMinus(self.expr))

    # comparisons -----------------------------------------------------------
    def __eq__(self, o): return Column(EqualTo(self.expr, _expr(o)))  # type: ignore[override]
    def __ne__(self, o): return Column(NotEqual(self.expr, _expr(o)))  # type: ignore[override]
    def __lt__(self, o): return Column(LessThan(self.expr, _expr(o)))
    def __le__(self, o): return Column(LessThanOrEqual(self.expr, _expr(o)))
    def __gt__(self, o): return Column(GreaterThan(self.expr, _expr(o)))
    def __ge__(self, o): return Column(GreaterThanOrEqual(self.expr, _expr(o)))
    def eqNullSafe(self, o): return Column(EqualNullSafe(self.expr, _expr(o)))

    # boolean ---------------------------------------------------------------
    def __and__(self, o): return Column(And(self.expr, _expr(o)))
    def __or__(self, o): return Column(Or(self.expr, _expr(o)))
    def __invert__(self): return Column(Not(self.expr))

    # bitwise ---------------------------------------------------------------
    def bitwiseAND(self, o): return Column(BitwiseAnd(self.expr, _expr(o)))
    def bitwiseOR(self, o): return Column(BitwiseOr(self.expr, _expr(o)))
    def bitwiseXOR(self, o): return Column(BitwiseXor(self.expr, _expr(o)))

    # null / misc -----------------------------------------------------------
    def isNull(self): return Column(IsNull(self.expr))
    def isNotNull(self): return Column(IsNotNull(self.expr))
    def isNaN(self): return Column(IsNan(self.expr))
    def isin(self, *vals):
        # large numeric literal sets take the InSet fast path (GpuInSet
        # analog: one sorted-membership probe instead of per-item equality)
        non_null = [v for v in vals if v is not None]
        if len(non_null) > 16 and all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in non_null):
            from spark_rapids_tpu.exprs.predicates import InSet
            return Column(InSet(self.expr, tuple(sorted(non_null)),
                                has_null=len(non_null) < len(vals)))
        return Column(In(self.expr, tuple(Literal.of(v) for v in vals)))

    # strings ---------------------------------------------------------------
    def startswith(self, p): return Column(StartsWith(self.expr, _expr(p)))
    def endswith(self, p): return Column(EndsWith(self.expr, _expr(p)))
    def contains(self, p): return Column(Contains(self.expr, _expr(p)))
    def like(self, p): return Column(Like(self.expr, _expr(p)))

    def rlike(self, p):
        from spark_rapids_tpu.exprs.strings import RLike
        return Column(RLike(self.expr, _expr(p)))

    def getItem(self, i: int):
        from spark_rapids_tpu.exprs.strings import GetArrayItem
        return Column(GetArrayItem(self.expr, int(i)))

    def __getitem__(self, i: int):
        return self.getItem(i)

    # naming / casting ------------------------------------------------------
    def alias(self, name: str) -> "Column":
        return Column(Alias(self.expr, name))

    def cast(self, to: Union[str, DType]) -> "Column":
        dt = DType(to) if isinstance(to, str) else to
        return Column(Cast(self.expr, dt))

    # windowing -------------------------------------------------------------
    def over(self, spec) -> "Column":
        from spark_rapids_tpu.exprs.windows import WindowExpression
        return Column(WindowExpression(self.expr, spec._part, spec._orders,
                                       spec._frame))

    # ordering --------------------------------------------------------------
    def asc(self): return Column(SortOrder(self.expr, True, True))
    def asc_nulls_last(self): return Column(SortOrder(self.expr, True, False))
    def desc(self): return Column(SortOrder(self.expr, False, False))
    def desc_nulls_first(self): return Column(SortOrder(self.expr, False, True))

    def __repr__(self):
        return f"Column<{self.expr}>"

    __hash__ = None  # type: ignore[assignment]
