"""User-facing function namespace (pyspark.sql.functions analog)."""
from __future__ import annotations

from typing import Any, Optional, Union

from spark_rapids_tpu.api.column import Column, _expr
from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs import (Abs, Acos, Asin, Atan, Atan2,
                                    AtLeastNNonNulls, Average, CaseWhen, Cbrt, Ceil,
                                    Coalesce, Concat, Corr, Cos, Cosh, Count,
                                    CovarPop, CovarSamp, DateAdd,
                                    DateDiff, DateSub, DayOfMonth, DayOfWeek,
                                    DayOfYear, DistinctAgg, Exp, Expm1, First,
                                    Floor, Greatest,
                                    Hour, If, InitCap, Last, LastDay, Least,
                                    Length, Literal,
                                    Log, Log1p, Log2, Log10, Lower, Max, Min, Minute,
                                    Month, MonotonicallyIncreasingID, NaNvl, Pmod,
                                    Pow, Quarter, Rand, Rint, Round, Second, Signum,
                                    Sin, Sinh, SparkPartitionID, Sqrt, StddevPop,
                                    StddevSamp, StringLocate, StringLPad,
                                    StringReplace, StringRPad, StringTrim,
                                    StringTrimLeft, StringTrimRight,
                                    Substring, SubstringIndex, Sum, Tan, Tanh,
                                    ToDegrees, ToRadians,
                                    UnresolvedAttribute, Upper, VariancePop,
                                    VarianceSamp, Year)


def col(name: str) -> Column:
    return Column(UnresolvedAttribute(name))


def udf(f=None, returnType: Union[str, DType] = DType.DOUBLE):
    """Row UDF wrapper (pyspark.sql.functions.udf analog). The returned
    callable produces a PythonUDF expression: row-at-a-time on the CPU engine
    by default; with spark.rapids.tpu.sql.udfCompiler.enabled the planner
    compiles the function's bytecode into a columnar expression tree that runs
    on the TPU (the udf-compiler module's two-stage strategy)."""
    from spark_rapids_tpu.udf import PythonUDF
    if isinstance(f, (str, DType)):
        # the @udf("int") positional form pyspark supports
        f, returnType = None, f
    ret = DType(returnType) if isinstance(returnType, str) else returnType

    def make(fn):
        def wrapper(*cols: Union[str, Column]) -> Column:
            return Column(PythonUDF(fn, ret, tuple(_c(c) for c in cols)))
        wrapper.__name__ = getattr(fn, "__name__", "udf")
        return wrapper
    return make if f is None else make(f)


def array(*cols: Union[str, Column]) -> Column:
    """Per-row array from scalar columns; only consumable by explode/posexplode
    (the reference's v0 Generate scope, GpuGenerateExec.scala:45-78)."""
    from spark_rapids_tpu.exprs.generators import CreateArray
    return Column(CreateArray(tuple(_c(c) for c in cols)))


def _as_created_array(c):
    from spark_rapids_tpu.exprs.generators import CreateArray
    if isinstance(c, (list, tuple)):
        return CreateArray(tuple(Literal.of(v) for v in c))
    e = c.expr if isinstance(c, Column) else None
    if not isinstance(e, CreateArray):
        raise ValueError(
            "explode/posexplode requires array(...) or a Python list literal "
            "(ARRAY columns are not a columnar type on this engine, matching "
            "the reference's explode-of-created-array scope)")
    return e


def explode(c) -> Column:
    from spark_rapids_tpu.exprs.generators import Explode
    return Column(Explode(_as_created_array(c)))


def posexplode(c) -> Column:
    from spark_rapids_tpu.exprs.generators import Explode
    return Column(Explode(_as_created_array(c), with_position=True))


def lit(value: Any) -> Column:
    return Column(Literal.of(value))


# aggregates ---------------------------------------------------------------
def count(c: Union[str, Column] = "*") -> Column:
    # note: `c == "*"` would be wrong here — Column.__eq__ builds an expression
    if isinstance(c, str):
        if c == "*":
            return Column(Count(Literal.of(1)))
        return Column(Count(col(c).expr))
    if isinstance(c.expr, Literal):
        return Column(Count(Literal.of(1)))
    return Column(Count(c.expr))


def sum(c: Union[str, Column]) -> Column:  # noqa: A001 - mirrors pyspark
    return Column(Sum(_c(c)))


def avg(c: Union[str, Column]) -> Column:
    return Column(Average(_c(c)))


mean = avg


def min(c: Union[str, Column]) -> Column:  # noqa: A001
    return Column(Min(_c(c)))


def max(c: Union[str, Column]) -> Column:  # noqa: A001
    return Column(Max(_c(c)))


def first(c: Union[str, Column], ignorenulls: bool = False) -> Column:
    return Column(First(_c(c), ignorenulls))


def last(c: Union[str, Column], ignorenulls: bool = False) -> Column:
    return Column(Last(_c(c), ignorenulls))


def stddev(c: Union[str, Column]) -> Column:
    return Column(StddevSamp(_c(c)))


stddev_samp = stddev


def stddev_pop(c: Union[str, Column]) -> Column:
    return Column(StddevPop(_c(c)))


def variance(c: Union[str, Column]) -> Column:
    return Column(VarianceSamp(_c(c)))


var_samp = variance


def var_pop(c: Union[str, Column]) -> Column:
    return Column(VariancePop(_c(c)))


def corr(a: Union[str, Column], b: Union[str, Column]) -> Column:
    return Column(Corr(_c(a), _c(b)))


def covar_samp(a: Union[str, Column], b: Union[str, Column]) -> Column:
    return Column(CovarSamp(_c(a), _c(b)))


def covar_pop(a: Union[str, Column], b: Union[str, Column]) -> Column:
    return Column(CovarPop(_c(a), _c(b)))


def countDistinct(c: Union[str, Column]) -> Column:
    return Column(DistinctAgg(count(c).expr))


count_distinct = countDistinct


def sumDistinct(c: Union[str, Column]) -> Column:
    return Column(DistinctAgg(Sum(_c(c))))


sum_distinct = sumDistinct


def _c(c: Union[str, Column]):
    return col(c).expr if isinstance(c, str) else c.expr


# conditionals -------------------------------------------------------------
class _WhenColumn(Column):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(CaseWhen(tuple(branches), None))

    def when(self, cond: Column, value: Any) -> "_WhenColumn":
        return _WhenColumn(self._branches + [(cond.expr, _expr(value))])

    def otherwise(self, value: Any) -> Column:
        return Column(CaseWhen(tuple(self._branches), _expr(value)))


def when(cond: Column, value: Any) -> _WhenColumn:
    return _WhenColumn([(cond.expr, _expr(value))])


def coalesce(*cols: Column) -> Column:
    return Column(Coalesce(tuple(_expr(c) for c in cols)))


def nanvl(a: Column, b: Column) -> Column:
    return Column(NaNvl(_expr(a), _expr(b)))


def greatest(*cols) -> Column:
    return Column(Greatest(tuple(_expr(c) for c in cols)))


def least(*cols) -> Column:
    return Column(Least(tuple(_expr(c) for c in cols)))


# math ---------------------------------------------------------------------
def _unary(cls):
    def f(c: Union[str, Column]) -> Column:
        return Column(cls(_c(c)))
    return f


abs = _unary(Abs)  # noqa: A001
sqrt = _unary(Sqrt)
cbrt = _unary(Cbrt)
exp = _unary(Exp)
expm1 = _unary(Expm1)
log = _unary(Log)
log2 = _unary(Log2)
log10 = _unary(Log10)
log1p = _unary(Log1p)
sin = _unary(Sin)
cos = _unary(Cos)
tan = _unary(Tan)
asin = _unary(Asin)
acos = _unary(Acos)
atan = _unary(Atan)
sinh = _unary(Sinh)
cosh = _unary(Cosh)
tanh = _unary(Tanh)
degrees = _unary(ToDegrees)
radians = _unary(ToRadians)
signum = _unary(Signum)
floor = _unary(Floor)
ceil = _unary(Ceil)
rint = _unary(Rint)


def pow(a, b) -> Column:  # noqa: A001
    return Column(Pow(_expr(a), _expr(b)))


def atan2(a, b) -> Column:
    return Column(Atan2(_expr(a), _expr(b)))


def pmod(a, b) -> Column:
    return Column(Pmod(_expr(a), _expr(b)))


def round(c: Union[str, Column], scale: int = 0) -> Column:  # noqa: A001
    return Column(Round(_c(c), scale))


# strings ------------------------------------------------------------------
upper = _unary(Upper)
lower = _unary(Lower)
length = _unary(Length)
trim = _unary(StringTrim)


def substring(c: Union[str, Column], pos: int, length_: int) -> Column:
    return Column(Substring(_c(c), Literal.of(pos), Literal.of(length_)))


def concat(*cols) -> Column:
    return Column(Concat(tuple(_c(c) if isinstance(c, str) else c.expr
                               for c in cols)))


initcap = _unary(InitCap)


def ltrim(c: Union[str, Column], trim_chars: Optional[str] = None) -> Column:
    t = None if trim_chars is None else Literal.of(trim_chars)
    return Column(StringTrimLeft(_c(c), t))


def rtrim(c: Union[str, Column], trim_chars: Optional[str] = None) -> Column:
    t = None if trim_chars is None else Literal.of(trim_chars)
    return Column(StringTrimRight(_c(c), t))


def locate(substr: str, c: Union[str, Column], pos: int = 1) -> Column:
    return Column(StringLocate(Literal.of(substr), _c(c), Literal.of(pos)))


def instr(c: Union[str, Column], substr: str) -> Column:
    return Column(StringLocate(Literal.of(substr), _c(c), Literal.of(1)))


def lpad(c: Union[str, Column], length_: int, pad: str) -> Column:
    return Column(StringLPad(_c(c), Literal.of(length_), Literal.of(pad)))


def rpad(c: Union[str, Column], length_: int, pad: str) -> Column:
    return Column(StringRPad(_c(c), Literal.of(length_), Literal.of(pad)))


def replace(c: Union[str, Column], search: str, replacement: str = "") -> Column:
    return Column(StringReplace(_c(c), Literal.of(search),
                                Literal.of(replacement)))


def substring_index(c: Union[str, Column], delim: str, count_: int) -> Column:
    return Column(SubstringIndex(_c(c), Literal.of(delim),
                                 Literal.of(count_)))


# datetime -----------------------------------------------------------------
year = _unary(Year)
month = _unary(Month)
dayofmonth = _unary(DayOfMonth)
dayofweek = _unary(DayOfWeek)
dayofyear = _unary(DayOfYear)
quarter = _unary(Quarter)
hour = _unary(Hour)
minute = _unary(Minute)
second = _unary(Second)
last_day = _unary(LastDay)


def date_add(c, days) -> Column:
    return Column(DateAdd(_c(c) if isinstance(c, str) else c.expr, _expr(days)))


def date_sub(c, days) -> Column:
    return Column(DateSub(_c(c) if isinstance(c, str) else c.expr, _expr(days)))


def datediff(end, start) -> Column:
    return Column(DateDiff(_expr(end), _expr(start)))


# ids / random -------------------------------------------------------------
def spark_partition_id() -> Column:
    return Column(SparkPartitionID())


def monotonically_increasing_id() -> Column:
    return Column(MonotonicallyIncreasingID())


def rand(seed: int = 0) -> Column:
    return Column(Rand(seed))


# window functions ---------------------------------------------------------
def row_number() -> Column:
    from spark_rapids_tpu.exprs.windows import RowNumber
    return Column(RowNumber())


def rank() -> Column:
    from spark_rapids_tpu.exprs.windows import Rank
    return Column(Rank())


def dense_rank() -> Column:
    from spark_rapids_tpu.exprs.windows import DenseRank
    return Column(DenseRank())


def percent_rank() -> Column:
    from spark_rapids_tpu.exprs.windows import PercentRank
    return Column(PercentRank())


def cume_dist() -> Column:
    from spark_rapids_tpu.exprs.windows import CumeDist
    return Column(CumeDist())


def ntile(n: int) -> Column:
    from spark_rapids_tpu.exprs.windows import NTile
    return Column(NTile(n))


def lead(c: Union[str, Column], offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.exprs.windows import Lead
    d = None if default is None else Literal.of(default)
    return Column(Lead(_c(c) if isinstance(c, str) else c.expr, offset, d))


def lag(c: Union[str, Column], offset: int = 1, default=None) -> Column:
    from spark_rapids_tpu.exprs.windows import Lag
    d = None if default is None else Literal.of(default)
    return Column(Lag(_c(c) if isinstance(c, str) else c.expr, offset, d))


def regexp_replace(c: Union[str, Column], pattern: str,
                   replacement: str = "") -> Column:
    from spark_rapids_tpu.exprs.strings import RegExpReplace
    return Column(RegExpReplace(_c(c), Literal.of(pattern),
                                Literal.of(replacement)))


def split(c: Union[str, Column], pattern: str, limit: int = -1) -> Column:
    """split(str, regex): index the result with [i]/getItem(i) (arrays are
    not a columnar type; the item access fuses into one split-part kernel).
    Only limit=-1 (split at every match) is supported."""
    if limit != -1:
        raise NotImplementedError(
            "split() with a positive limit is not supported (only -1)")
    from spark_rapids_tpu.exprs.strings import StringSplit
    return Column(StringSplit(_c(c), Literal.of(pattern), limit))


def unix_timestamp(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.datetime import UnixTimestamp
    return Column(UnixTimestamp(_c(c)))


def to_unix_timestamp(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.datetime import ToUnixTimestamp
    return Column(ToUnixTimestamp(_c(c)))


def from_unixtime(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.datetime import FromUnixTime
    return Column(FromUnixTime(_c(c)))


def weekday(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.datetime import WeekDay
    return Column(WeekDay(_c(c)))


def cot(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.math import Cot
    return Column(Cot(_c(c)))


def asinh(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.math import Asinh
    return Column(Asinh(_c(c)))


def acosh(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.math import Acosh
    return Column(Acosh(_c(c)))


def atanh(c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.math import Atanh
    return Column(Atanh(_c(c)))


def log_base(base: float, c: Union[str, Column]) -> Column:
    from spark_rapids_tpu.exprs.math import Logarithm
    return Column(Logarithm(Literal.of(float(base)), _c(c)))


def input_file_name() -> Column:
    """Path of the file the row was read from (GpuInputFileBlock analog);
    hidden scan metadata columns carry the value per batch."""
    from spark_rapids_tpu.exprs.misc import InputFileName
    return Column(InputFileName())


def input_file_block_start() -> Column:
    from spark_rapids_tpu.exprs.misc import InputFileBlockStart
    return Column(InputFileBlockStart())


def input_file_block_length() -> Column:
    from spark_rapids_tpu.exprs.misc import InputFileBlockLength
    return Column(InputFileBlockLength())
