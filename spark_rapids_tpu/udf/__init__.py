"""UDF support: row-function wrapper + bytecode compiler.

Reference analog: the udf-compiler module (udf-compiler/.../Plugin.scala:28 —
a resolution rule replacing ScalaUDF with compiled Catalyst expressions, gated
by spark.rapids.sql.udfCompiler.enabled) and GpuScalaUDF.scala (the fallback
wrapper). ``compile_plan_udfs`` is the resolution-rule analog, run by the
planner before physical planning when the conf is on."""
from __future__ import annotations

import dataclasses

from spark_rapids_tpu.exprs.cast import Cast
from spark_rapids_tpu.exprs.core import Expression, bind_expression
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.udf.compiler import UdfCompileError, compile_udf
from spark_rapids_tpu.udf.expression import PythonUDF

__all__ = ["PythonUDF", "UdfCompileError", "compile_udf", "compile_plan_udfs"]


def _compile_expr(e: Expression, schema) -> Expression:
    e = e.map_children(lambda c: _compile_expr(c, schema))
    if isinstance(e, PythonUDF):
        try:
            # bind the argument expressions so the compiler can reason about
            # types (If-branch harmonization); BoundReference survives the
            # planner's later bind pass untouched
            bound = tuple(bind_expression(a, schema) for a in e.args)
            compiled = compile_udf(e.fn, bound)
        except (UdfCompileError, KeyError, TypeError):
            return e
        # pin the declared return type regardless of what the body inferred
        return Cast(compiled, e.ret_dtype)
    return e


def _walk_field(v, schema):
    if isinstance(v, Expression):
        return _compile_expr(v, schema)
    if isinstance(v, tuple):
        return tuple(_walk_field(x, schema) for x in v)
    return v


def compile_plan_udfs(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """Replace compilable PythonUDF nodes across a logical plan (the
    LogicalPlanRules resolution-rule role, udf-compiler Plugin.scala:36-48).
    Expressions compile against the node's child schema; nodes without a
    single input schema (joins) keep their UDFs on the fallback path."""
    if not dataclasses.is_dataclass(plan):
        return plan
    changes = {}
    children = plan.children
    schema = children[0].schema() if len(children) == 1 else None
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, lp.LogicalPlan):
            nv = compile_plan_udfs(v)
        elif schema is not None:
            nv = _walk_field(v, schema)
        else:
            nv = v
        if nv is not v:
            changes[f.name] = nv
    return dataclasses.replace(plan, **changes) if changes else plan
