"""Python-bytecode -> expression-tree UDF compiler.

Reference analog: the udf-compiler module — LambdaReflection.scala (bytecode
access), CFG.scala:44 (basic blocks), Instruction.scala:83 (symbolic stack
interpreter over ~100 JVM opcodes), CatalystExpressionBuilder.scala:45 (drives
traversal, emits Catalyst). Same two-stage strategy here: the compiled output
is one of OUR expressions, which then rides the normal plan-rewrite path onto
the TPU — the compiler never generates device code itself.

This interpreter walks CPython 3.10–3.12 bytecode symbolically: the operand
stack holds Expression nodes; a conditional jump forks interpretation down
both successors and joins them as an If over the two reachable RETURNs
(loops and anything else unsupported raise UdfCompileError, leaving the UDF
on the row-wise fallback path — the reference falls back identically when
its opcode coverage runs out). Pre-3.11 spellings (BINARY_ADD et al.,
CALL_FUNCTION/CALL_METHOD, JUMP_IF_*_OR_POP, unflagged LOAD_GLOBAL) are
handled alongside the 3.11+ forms, the same version-drift posture as
shims/ takes for jax.
"""
from __future__ import annotations

import dis
import math
import sys
from typing import Any, Dict, List, Tuple

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs import arithmetic as ar
from spark_rapids_tpu.exprs import bitwise as bw
from spark_rapids_tpu.exprs import conditional as cond
from spark_rapids_tpu.exprs import math as ma
from spark_rapids_tpu.exprs import nulls as nu
from spark_rapids_tpu.exprs import predicates as pr
from spark_rapids_tpu.exprs import strings as st
from spark_rapids_tpu.exprs.cast import Cast
from spark_rapids_tpu.exprs.core import Expression
from spark_rapids_tpu.exprs.literals import Literal


class UdfCompileError(Exception):
    """Raised when the UDF body uses something outside the supported subset;
    the caller leaves the row-wise PythonUDF in place."""


class _Null:
    """Stack sentinel for PUSH_NULL / the NULL slot of LOAD_GLOBAL/LOAD_ATTR."""


class _Callable:
    """A resolved function/method the CALL handler knows how to map."""

    def __init__(self, name: str):
        self.name = name


class _Module:
    def __init__(self, name: str):
        self.name = name


class _TupleConst:
    """A tuple literal; only consumable by CONTAINS_OP (x in (...))."""

    def __init__(self, items: tuple):
        self.items = items


_BINOPS = {
    "+": ar.Add, "-": ar.Subtract, "*": ar.Multiply, "/": ar.Divide,
    "//": ar.IntegralDivide, "%": ar.Remainder, "**": ma.Pow,
    "&": bw.BitwiseAnd, "|": bw.BitwiseOr, "^": bw.BitwiseXor,
    "<<": bw.ShiftLeft, ">>": bw.ShiftRight,
}
_CMPOPS = {
    "==": pr.EqualTo, "!=": pr.NotEqual, "<": pr.LessThan,
    "<=": pr.LessThanOrEqual, ">": pr.GreaterThan, ">=": pr.GreaterThanOrEqual,
}
#: CPython <= 3.10 spellings of what 3.11 folded into BINARY_OP
_LEGACY_BINOPS = {
    "BINARY_ADD": "+", "BINARY_SUBTRACT": "-", "BINARY_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "BINARY_FLOOR_DIVIDE": "//",
    "BINARY_MODULO": "%", "BINARY_POWER": "**", "BINARY_AND": "&",
    "BINARY_OR": "|", "BINARY_XOR": "^", "BINARY_LSHIFT": "<<",
    "BINARY_RSHIFT": ">>",
    "INPLACE_ADD": "+", "INPLACE_SUBTRACT": "-", "INPLACE_MULTIPLY": "*",
    "INPLACE_TRUE_DIVIDE": "/", "INPLACE_FLOOR_DIVIDE": "//",
    "INPLACE_MODULO": "%", "INPLACE_POWER": "**", "INPLACE_AND": "&",
    "INPLACE_OR": "|", "INPLACE_XOR": "^", "INPLACE_LSHIFT": "<<",
    "INPLACE_RSHIFT": ">>",
}
_PY311 = sys.version_info >= (3, 11)
_PY312 = sys.version_info >= (3, 12)
#: global functions: name -> (expr class, arity) — arity None = variadic>=2
_FUNCTIONS = {
    "abs": (ar.Abs, 1), "len": (st.Length, 1), "round": (ma.Rint, None),
    "min": (ar.Least, None), "max": (ar.Greatest, None),
    "math.sqrt": (ma.Sqrt, 1), "math.exp": (ma.Exp, 1),
    "math.expm1": (ma.Expm1, 1), "math.log": (ma.Log, 1),
    "math.log2": (ma.Log2, 1), "math.log10": (ma.Log10, 1),
    "math.log1p": (ma.Log1p, 1), "math.sin": (ma.Sin, 1),
    "math.cos": (ma.Cos, 1), "math.tan": (ma.Tan, 1),
    "math.asin": (ma.Asin, 1), "math.acos": (ma.Acos, 1),
    "math.atan": (ma.Atan, 1), "math.atan2": (ma.Atan2, 2),
    "math.sinh": (ma.Sinh, 1), "math.cosh": (ma.Cosh, 1),
    "math.tanh": (ma.Tanh, 1), "math.floor": (ma.Floor, 1),
    "math.ceil": (ma.Ceil, 1), "math.pow": (ma.Pow, 2),
    "math.degrees": (ma.ToDegrees, 1), "math.radians": (ma.ToRadians, 1),
    "math.isnan": (nu.IsNan, 1),
}
#: str methods: name -> builder(self, *args)
_METHODS = {
    "upper": lambda s: st.Upper(s),
    "lower": lambda s: st.Lower(s),
    "strip": lambda s: st.StringTrim(s),
    "startswith": lambda s, p: st.StartsWith(s, p),
    "endswith": lambda s, p: st.EndsWith(s, p),
}

_MAX_FORKS = 64


def compile_udf(fn, args: Tuple[Expression, ...]) -> Expression:
    """Compile ``fn``'s bytecode into an expression over ``args`` or raise
    UdfCompileError."""
    code = fn.__code__
    if (code.co_flags & 0x0C) or code.co_kwonlyargcount:  # *args/**kwargs
        raise UdfCompileError("varargs/kwargs are not supported")
    if fn.__defaults__ or code.co_freevars or code.co_cellvars:
        raise UdfCompileError("defaults and closures are not supported")
    if code.co_argcount != len(args):
        raise UdfCompileError(
            f"{getattr(fn, '__name__', 'udf')} takes {code.co_argcount} args, "
            f"{len(args)} columns given")
    instrs = list(dis.get_instructions(fn))
    by_offset = {ins.offset: i for i, ins in enumerate(instrs)}
    locals_: Dict[int, Any] = {i: a for i, a in enumerate(args)}
    state = _State(fn, instrs, by_offset)
    return state.run(0, [], dict(locals_))


class _State:
    def __init__(self, fn, instrs, by_offset):
        self.fn = fn
        self.instrs = instrs
        self.by_offset = by_offset
        self.forks = 0

    def run(self, i: int, stack: List[Any], locals_: Dict[int, Any]) -> Expression:
        """Symbolically execute from instruction index ``i`` to a RETURN."""
        instrs = self.instrs
        while i < len(instrs):
            ins = instrs[i]
            op = ins.opname
            if op in ("RESUME", "NOP", "CACHE", "PRECALL"):
                i += 1
            elif op == "PUSH_NULL":
                stack.append(_Null())
                i += 1
            elif op == "POP_TOP":
                stack.pop()
                i += 1
            elif op == "COPY":
                stack.append(stack[-ins.arg])
                i += 1
            elif op == "SWAP":
                stack[-ins.arg], stack[-1] = stack[-1], stack[-ins.arg]
                i += 1
            elif op == "LOAD_FAST":
                if ins.arg not in locals_:
                    raise UdfCompileError(f"local {ins.argrepr} read before "
                                          f"assignment")
                stack.append(locals_[ins.arg])
                i += 1
            elif op == "STORE_FAST":
                locals_[ins.arg] = stack.pop()
                i += 1
            elif op == "LOAD_CONST":
                stack.append(self._const(ins.argval))
                i += 1
            elif op == "RETURN_CONST":
                return self._expr(self._const(ins.argval))
            elif op == "RETURN_VALUE":
                return self._expr(stack.pop())
            elif op == "LOAD_GLOBAL":
                # the low "push NULL" flag bit exists only on 3.11+; on 3.10
                # ins.arg is a plain co_names index
                if _PY311 and ins.arg & 1:
                    stack.append(_Null())
                stack.append(self._global(ins.argval))
                i += 1
            elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                obj = stack.pop()
                name = ins.argval
                # 3.12 folded LOAD_METHOD into LOAD_ATTR behind arg's low bit
                methodish = (op == "LOAD_METHOD"
                             or (_PY312 and bool(ins.arg & 1)))
                if isinstance(obj, _Module):
                    target = _Callable(f"{obj.name}.{name}")
                    stack.append(target)
                    if methodish:
                        stack.append(_Null())
                elif isinstance(obj, Expression) and name in _METHODS:
                    stack.append(_Callable(name))
                    stack.append(obj)
                else:
                    raise UdfCompileError(f"attribute {name!r} is not "
                                          f"supported")
                i += 1
            elif op == "BINARY_OP" or op in _LEGACY_BINOPS:
                sym = (_LEGACY_BINOPS[op] if op in _LEGACY_BINOPS
                       else ins.argrepr.rstrip("="))
                cls = _BINOPS.get(sym)
                if cls is None:
                    raise UdfCompileError(f"operator {ins.argrepr!r} is not "
                                          f"supported")
                r, l = self._expr(stack.pop()), self._expr(stack.pop())
                stack.append(cls(l, r))
                i += 1
            elif op == "DUP_TOP":
                stack.append(stack[-1])
                i += 1
            elif op == "ROT_TWO":
                stack[-1], stack[-2] = stack[-2], stack[-1]
                i += 1
            elif op == "COMPARE_OP":
                sym = ins.argrepr.replace("bool(", "").rstrip(")")
                cls = _CMPOPS.get(sym)
                if cls is None:
                    raise UdfCompileError(f"comparison {ins.argrepr!r} is not "
                                          f"supported")
                r, l = self._expr(stack.pop()), self._expr(stack.pop())
                stack.append(cls(l, r))
                i += 1
            elif op == "CONTAINS_OP":
                container = stack.pop()
                value = self._expr(stack.pop())
                if isinstance(container, _TupleConst):
                    items = tuple(Literal.of(v) for v in container.items)
                    e: Expression = pr.In(value, items)
                elif isinstance(container, Expression):
                    e = st.Contains(container, value)
                else:
                    raise UdfCompileError("unsupported `in` container")
                stack.append(pr.Not(e) if ins.arg else e)
                i += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(ar.UnaryMinus(self._expr(stack.pop())))
                i += 1
            elif op in ("UNARY_NOT", "TO_BOOL"):
                if op == "UNARY_NOT":
                    stack.append(pr.Not(self._expr(stack.pop())))
                i += 1
            elif op == "UNARY_INVERT":
                stack.append(bw.BitwiseNot(self._expr(stack.pop())))
                i += 1
            elif op == "IS_OP":
                # `x is None` / `x is not None`
                r = stack.pop()
                l = self._expr(stack.pop())
                if not (isinstance(r, Literal) and r.value is None):
                    raise UdfCompileError("`is` only supports None")
                e = nu.IsNull(l)
                stack.append(pr.Not(e) if ins.arg else e)
                i += 1
            elif op.startswith("POP_JUMP_BACKWARD_IF_"):
                # 3.11 spelling of a loop back-edge
                raise UdfCompileError("loops are not supported")
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE",
                        # 3.11 spellings; 3.10/3.12 drop the direction
                        "POP_JUMP_FORWARD_IF_FALSE",
                        "POP_JUMP_FORWARD_IF_TRUE",
                        "POP_JUMP_FORWARD_IF_NONE",
                        "POP_JUMP_FORWARD_IF_NOT_NONE"):
                kind = op.replace("_FORWARD", "")
                v = self._expr(stack.pop())
                if kind == "POP_JUMP_IF_NONE":
                    pred = pr.Not(nu.IsNull(v))       # jump when None
                elif kind == "POP_JUMP_IF_NOT_NONE":
                    pred = nu.IsNull(v)               # jump when not None
                elif kind == "POP_JUMP_IF_TRUE":
                    pred = pr.Not(_as_bool(v))
                else:
                    pred = _as_bool(v)
                self.forks += 1
                if self.forks > _MAX_FORKS:
                    raise UdfCompileError("too many branches")
                then_e = self.run(i + 1, list(stack), dict(locals_))
                else_e = self.run(self.by_offset[ins.argval], list(stack),
                                  dict(locals_))
                return _merge_if(pred, then_e, else_e)
            elif op in ("JUMP_IF_FALSE_OR_POP", "JUMP_IF_TRUE_OR_POP"):
                # 3.10 spelling of `and`/`or` chains: the short-circuit
                # branch keeps the tested value on the stack
                v = self._expr(stack.pop())
                self.forks += 1
                if self.forks > _MAX_FORKS:
                    raise UdfCompileError("too many branches")
                fall = self.run(i + 1, list(stack), dict(locals_))
                jump = self.run(self.by_offset[ins.argval],
                                list(stack) + [v], dict(locals_))
                if op == "JUMP_IF_FALSE_OR_POP":
                    return _merge_if(_as_bool(v), fall, jump)
                return _merge_if(_as_bool(v), jump, fall)
            elif op == "JUMP_FORWARD":
                i = self.by_offset[ins.argval]
            elif op == "JUMP_ABSOLUTE":
                target = self.by_offset[ins.argval]
                if target <= i:
                    raise UdfCompileError("loops are not supported")
                i = target
            elif op == "JUMP_BACKWARD":
                raise UdfCompileError("loops are not supported")
            elif op == "CALL":
                argc = ins.arg
                call_args = [self._expr(stack.pop()) for _ in range(argc)][::-1]
                a = stack.pop()
                b = stack.pop() if stack else _Null()
                marker, self_obj = None, None
                for item in (a, b):
                    if isinstance(item, _Callable):
                        marker = item
                    elif isinstance(item, Expression):
                        self_obj = item
                if marker is None:
                    raise UdfCompileError("call target is not a supported "
                                          "function")
                stack.append(self._call(marker.name, self_obj, call_args))
                i += 1
            elif op == "CALL_FUNCTION":
                # 3.10: stack is [func, arg0..argN-1]; no NULL slot
                call_args = [self._expr(stack.pop())
                             for _ in range(ins.arg)][::-1]
                target = stack.pop()
                if not isinstance(target, _Callable):
                    raise UdfCompileError("call target is not a supported "
                                          "function")
                stack.append(self._call(target.name, None, call_args))
                i += 1
            elif op == "CALL_METHOD":
                # 3.10: stack is [method, self_or_null, arg0..argN-1]
                call_args = [self._expr(stack.pop())
                             for _ in range(ins.arg)][::-1]
                a = stack.pop()
                b = stack.pop()
                marker, self_obj = None, None
                for item in (a, b):
                    if isinstance(item, _Callable):
                        marker = item
                    elif isinstance(item, Expression):
                        self_obj = item
                if marker is None:
                    raise UdfCompileError("call target is not a supported "
                                          "function")
                stack.append(self._call(marker.name, self_obj, call_args))
                i += 1
            else:
                raise UdfCompileError(f"opcode {op} is not supported")
        raise UdfCompileError("fell off the end of the bytecode")

    # ---- helpers --------------------------------------------------------------
    def _const(self, v):
        if isinstance(v, tuple):
            return _TupleConst(v)
        try:
            return Literal.of(v)
        except TypeError:
            raise UdfCompileError(f"constant {v!r} is not supported")

    def _global(self, name: str):
        import builtins
        missing = object()
        v = self.fn.__globals__.get(name, missing)
        if v is missing:
            v = getattr(builtins, name, missing)
        if v is math:
            return _Module("math")
        # a shadowed builtin (def abs(x): ...) must NOT compile to the real one
        if name in _FUNCTIONS and v is getattr(builtins, name, None):
            return _Callable(name)
        raise UdfCompileError(f"global {name!r} is not supported")

    def _call(self, name: str, self_obj, args: List[Expression]) -> Expression:
        if self_obj is not None and name in _METHODS:
            try:
                return _METHODS[name](self_obj, *args)
            except TypeError:
                raise UdfCompileError(f"bad arity for method {name!r}")
        spec = _FUNCTIONS.get(name)
        if spec is None:
            raise UdfCompileError(f"function {name!r} is not supported")
        cls, arity = spec
        if arity is None:
            if name == "round":
                # python round() is half-even -> Rint, not Spark's HALF_UP
                if len(args) != 1:
                    raise UdfCompileError("only 1-arg round() is supported")
                return ma.Rint(args[0])
            if len(args) < 2:
                raise UdfCompileError(f"{name} needs at least 2 args")
            return cls(tuple(args))
        if len(args) != arity:
            raise UdfCompileError(f"bad arity for {name!r}")
        return cls(*args)

    def _expr(self, v) -> Expression:
        if isinstance(v, Expression):
            return v
        raise UdfCompileError(f"unsupported stack value {type(v).__name__}")


def _as_bool(e: Expression) -> Expression:
    """Python truthiness of the branch value. Types whose truthiness we cannot
    reproduce exactly raise, leaving the UDF on the row-wise path."""
    dt = e.dtype()
    if dt is DType.BOOLEAN:
        return e
    if dt is DType.STRING:
        return pr.GreaterThan(st.Length(e), Literal.of(0))
    if dt.is_numeric:
        return pr.NotEqual(e, Cast(Literal.of(0), dt))
    raise UdfCompileError(f"truthiness of {dt.value} is not supported")


def _merge_if(pred: Expression, t: Expression, f: Expression) -> Expression:
    """Join two return expressions under a condition, reconciling types."""
    td, fd = t.dtype(), f.dtype()
    if td is DType.NULL and isinstance(t, Literal):
        t = Literal(None, fd)
    elif fd is DType.NULL and isinstance(f, Literal):
        f = Literal(None, td)
    else:
        ct = DType.common_type(td, fd)
        if td is not ct:
            t = Cast(t, ct)
        if fd is not ct:
            f = Cast(f, ct)
    return cond.If(pred, t, f)
