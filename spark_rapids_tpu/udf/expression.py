"""Python row-UDF expression (reference analog: GpuScalaUDF.scala — the
uncompiled wrapper that keeps the query correct on the fallback path).

A ``PythonUDF`` has no device implementation (there is no EXPR rule for it),
so any exec containing one is tagged NOT_ON_TPU and runs on the CPU engine,
where ``eval`` applies the function row-at-a-time — exactly the reference's
behavior for an uncompiled ScalaUDF (the JVM evaluates it row-wise and the
plan around it falls back). The udf compiler (udf/compiler.py) replaces these
nodes with real expression trees when it can.
"""
from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, Callable, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression

_EPOCH_DATE = datetime.date(1970, 1, 1)
_EPOCH_TS = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def _to_python(v: ColV, i: int) -> Any:
    if not bool(v.validity[i]):
        return None
    if v.dtype is DType.STRING:
        n = int(v.lengths[i])
        return bytes(np.asarray(v.data[i][:n], dtype=np.uint8)).decode(
            "utf-8", errors="replace")
    raw = v.data[i]
    if v.dtype is DType.DATE:
        return _EPOCH_DATE + datetime.timedelta(days=int(raw))
    if v.dtype is DType.TIMESTAMP:
        return _EPOCH_TS + datetime.timedelta(microseconds=int(raw))
    if v.dtype is DType.BOOLEAN:
        return bool(raw)
    if v.dtype.is_floating:
        return float(raw)
    return int(raw)


@dataclass(frozen=True)
class PythonUDF(Expression):
    fn: Callable
    ret_dtype: DType
    args: Tuple[Expression, ...]

    def dtype(self) -> DType:
        return self.ret_dtype

    def nullable(self) -> bool:
        return True

    @property
    def name_hint(self) -> str:
        return getattr(self.fn, "__name__", "udf")

    def eval(self, ctx: EvalCtx) -> ColV:
        if ctx.xp is not np:
            raise TypeError("Python UDFs cannot run on device; enable "
                            "spark.rapids.tpu.sql.udfCompiler.enabled or keep "
                            "this exec on the CPU engine")
        cols = [a.eval(ctx) for a in self.args]
        n = ctx.capacity
        out = []
        valid = np.zeros(n, dtype=bool)
        for i in range(n):
            res = self.fn(*[_to_python(c, i) for c in cols])
            valid[i] = res is not None
            out.append(res)
        dt = self.ret_dtype
        if dt is DType.STRING:
            data = np.zeros((n, ctx.string_max_bytes), dtype=np.uint8)
            lengths = np.zeros(n, dtype=np.int32)
            for i, res in enumerate(out):
                if res is None:
                    continue
                raw = str(res).encode("utf-8")[:ctx.string_max_bytes]
                data[i, :len(raw)] = bytearray(raw)
                lengths[i] = len(raw)
            return ColV(dt, data, valid, lengths)
        phys = np.zeros(n, dtype=_np_dtype(dt))
        for i, res in enumerate(out):
            if res is None:
                continue
            if dt is DType.DATE and isinstance(res, datetime.date):
                res = (res - _EPOCH_DATE).days
            elif dt is DType.TIMESTAMP and isinstance(res, datetime.datetime):
                if res.tzinfo is None:
                    res = res.replace(tzinfo=datetime.timezone.utc)
                res = int(res.timestamp() * 1_000_000)
            phys[i] = res
        return ColV(dt, phys, valid)


def _np_dtype(dt: DType):
    return {DType.BOOLEAN: np.bool_, DType.BYTE: np.int8, DType.SHORT: np.int16,
            DType.INT: np.int32, DType.LONG: np.int64, DType.FLOAT: np.float32,
            DType.DOUBLE: np.float64, DType.DATE: np.int32,
            DType.TIMESTAMP: np.int64}[dt]
