"""Cast expression (reference: GpuCast.scala:79,181 — 877 LoC cast matrix).

Spark non-ANSI cast semantics implemented:
- integral -> narrower integral wraps (Java narrowing conversion);
- float/double -> integral goes through Scala's .toInt/.toLong: NaN -> 0,
  saturate at the *int/long* bounds, truncate toward zero; narrower targets then
  wrap from the saturated int (Java (byte)(int)x);
- numeric -> boolean is `!= 0`; boolean -> numeric is 1/0;
- date -> timestamp multiplies by 86_400_000_000 us (UTC, matching Spark's
  UTC-only TPU/GPU gating); timestamp -> date floor-divides;
- timestamp -> long is floor seconds; long -> timestamp multiplies to micros;
- integral/boolean -> string uses the vectorized device itos kernel;
- float -> string and string -> numeric/timestamp are CPU-fallback paths gated by
  confs (castFloatToString.enabled etc.), like the reference's incompat casts.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression
from spark_rapids_tpu.ops import strings as sk

MICROS_PER_DAY = 86_400_000_000
MICROS_PER_SECOND = 1_000_000

_INT_BOUNDS = {
    DType.BYTE: (-(2 ** 7), 2 ** 7 - 1),
    DType.SHORT: (-(2 ** 15), 2 ** 15 - 1),
    DType.INT: (-(2 ** 31), 2 ** 31 - 1),
    DType.LONG: (-(2 ** 63), 2 ** 63 - 1),
}


def can_cast_on_device(src: DType, to: DType) -> bool:
    """Which cast pairs have a device kernel (rest fall back / are conf-gated)."""
    if src == to:
        return True
    numericish = src.is_numeric or src is DType.BOOLEAN
    if numericish and (to.is_numeric or to is DType.BOOLEAN):
        return True
    if src in (DType.DATE, DType.TIMESTAMP) and to in (DType.DATE, DType.TIMESTAMP):
        return True
    if src is DType.TIMESTAMP and to in (DType.LONG,):
        return True
    if src.is_integral and to is DType.TIMESTAMP:
        return True
    if src is DType.DATE and to.is_integral:
        return True
    if (src.is_integral or src is DType.BOOLEAN) and to is DType.STRING:
        return True
    return False


@dataclass(frozen=True)
class Cast(Expression):
    c: Expression
    to: DType
    ansi: bool = False

    def dtype(self) -> DType:
        return self.to

    def nullable(self) -> bool:
        return self.c.nullable()

    def sql_name(self) -> str:
        return "Cast"

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        src, to = v.dtype, self.to
        if src == to:
            return v
        if src is DType.NULL:
            from spark_rapids_tpu.exprs.literals import Literal
            return Literal(None, to).eval(ctx)

        if to is DType.STRING:
            if src.is_integral:
                kernel = sk.int_to_string
            elif src is DType.BOOLEAN:
                kernel = sk.bool_to_string
            else:
                raise NotImplementedError(f"cast {src} -> string has no device kernel")
            if v.data.ndim == 0:
                d2, l2 = kernel(xp, v.data[None], ctx.string_max_bytes)
                return ColV(to, d2[0], v.validity, l2[0], is_scalar=True)
            data, lengths = kernel(xp, v.data, ctx.string_max_bytes)
            return ColV(to, data, v.validity, lengths)

        if to is DType.BOOLEAN:
            return ColV(to, v.data != 0, v.validity, is_scalar=v.is_scalar)

        if src is DType.BOOLEAN:
            return ColV(to, v.data.astype(to.np_dtype()), v.validity,
                        is_scalar=v.is_scalar)

        if src is DType.DATE and to is DType.TIMESTAMP:
            data = v.data.astype(np.int64) * MICROS_PER_DAY
            return ColV(to, data, v.validity, is_scalar=v.is_scalar)
        if src is DType.TIMESTAMP and to is DType.DATE:
            data = (v.data // MICROS_PER_DAY).astype(np.int32)
            return ColV(to, data, v.validity, is_scalar=v.is_scalar)
        if src is DType.TIMESTAMP and to is DType.LONG:
            data = v.data // MICROS_PER_SECOND
            return ColV(to, data, v.validity, is_scalar=v.is_scalar)
        if src.is_integral and to is DType.TIMESTAMP:
            data = v.data.astype(np.int64) * MICROS_PER_SECOND
            return ColV(to, data, v.validity, is_scalar=v.is_scalar)
        if src is DType.DATE and to.is_integral:
            return ColV(to, v.data.astype(to.np_dtype()), v.validity,
                        is_scalar=v.is_scalar)

        if src.is_floating and to.is_integral:
            return ColV(to, _float_to_integral(xp, v.data, to), v.validity,
                        is_scalar=v.is_scalar)
        if src.is_numeric and to.is_numeric:
            # integral->integral narrowing wraps; ->float is standard widening
            return ColV(to, v.data.astype(to.np_dtype()), v.validity,
                        is_scalar=v.is_scalar)

        raise NotImplementedError(f"cast {src} -> {to} has no device kernel")


def _float_to_integral(xp, d, to: DType):
    """Scala .toInt/.toLong then Java narrowing: NaN->0, saturate to int/long,
    then wrap to byte/short."""
    wide = DType.LONG if to is DType.LONG else DType.INT
    lo, hi = _INT_BOUNDS[wide]
    nan = xp.isnan(d)
    clipped = xp.clip(d, float(lo), float(hi))
    as_wide = xp.where(nan, 0, clipped).astype(wide.np_dtype())
    # edge: clip to float(hi) can round up past hi for int64; re-clamp exactly
    as_wide = xp.where(d >= float(hi), np.asarray(hi, dtype=wide.np_dtype()), as_wide)
    as_wide = xp.where(d <= float(lo), np.asarray(lo, dtype=wide.np_dtype()), as_wide)
    return as_wide.astype(to.np_dtype())
