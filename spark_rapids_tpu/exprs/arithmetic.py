"""Arithmetic expressions (reference: arithmetic.scala, 227 LoC).

Spark (non-ANSI) semantics encoded here:
- integral add/sub/mul wrap like Java two's complement;
- Divide always produces DOUBLE and returns NULL when the divisor is 0 (Spark's
  Divide nulls out division by zero even for doubles — it never emits Inf from /0);
- IntegralDivide (`div`) produces LONG, NULL on /0, truncating toward zero like Java;
- Remainder/Pmod are NULL on /0; Remainder sign follows the dividend (Java %).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import (BinaryExpression, ColV, EvalCtx, Expression,
                                         UnaryExpression, and_validity, cast_operands)


@dataclass(frozen=True)
class Add(BinaryExpression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return _wrapping(ctx, lambda: l.data + r.data)


@dataclass(frozen=True)
class Subtract(BinaryExpression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return _wrapping(ctx, lambda: l.data - r.data)


@dataclass(frozen=True)
class Multiply(BinaryExpression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return _wrapping(ctx, lambda: l.data * r.data)


def _wrapping(ctx: EvalCtx, fn):
    """Java ints wrap on overflow; numpy warns, jnp just wraps."""
    if ctx.is_tracing:
        return fn()
    with np.errstate(over="ignore"):
        return fn()


class _DivisorNullingBinary(BinaryExpression):
    """Base for ops that are NULL when the divisor is zero."""

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        l, r = cast_operands(ctx, l, r, self.operand_dtype())
        zero = r.data == 0
        validity = xp.logical_and(and_validity(xp, l, r), xp.logical_not(zero))
        data = self.do_div(ctx, l, r, zero)
        return ColV(self.dtype(), data, validity,
                    is_scalar=l.is_scalar and r.is_scalar)

    def do_div(self, ctx: EvalCtx, l: ColV, r: ColV, zero):
        raise NotImplementedError


@dataclass(frozen=True)
class Divide(_DivisorNullingBinary):
    l: Expression
    r: Expression

    def operand_dtype(self) -> DType:
        return DType.DOUBLE

    def dtype(self) -> DType:
        return DType.DOUBLE

    def do_div(self, ctx: EvalCtx, l: ColV, r: ColV, zero):
        xp = ctx.xp
        safe = xp.where(zero, xp.asarray(1.0, dtype=r.data.dtype), r.data)
        return l.data / safe


@dataclass(frozen=True)
class IntegralDivide(_DivisorNullingBinary):
    l: Expression
    r: Expression

    def operand_dtype(self) -> DType:
        return DType.LONG

    def dtype(self) -> DType:
        return DType.LONG

    def do_div(self, ctx: EvalCtx, l: ColV, r: ColV, zero):
        xp = ctx.xp
        safe = xp.where(zero, xp.asarray(1, dtype=r.data.dtype), r.data)
        # Java integer division truncates toward zero; // floors. Fix up.
        q = l.data // safe
        rem = l.data - q * safe
        trunc_fix = xp.logical_and(rem != 0, (l.data < 0) != (safe < 0))
        return (q + trunc_fix.astype(q.dtype)).astype(np.int64)


@dataclass(frozen=True)
class Remainder(_DivisorNullingBinary):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_div(self, ctx: EvalCtx, l: ColV, r: ColV, zero):
        xp = ctx.xp
        one = xp.asarray(1, dtype=r.data.dtype)
        safe = xp.where(zero, one, r.data)
        if self.operand_dtype().is_floating:
            # Java % (fmod): sign follows dividend
            return _wrapping(ctx, lambda: xp.fmod(l.data, safe))
        m = _wrapping(ctx, lambda: xp.mod(l.data, safe))
        # numpy mod floors (sign follows divisor); Java % truncates. Fix up.
        fix = xp.logical_and(m != 0, (l.data < 0) != (safe < 0))
        return m - xp.where(fix, safe, xp.asarray(0, dtype=safe.dtype))


@dataclass(frozen=True)
class Pmod(_DivisorNullingBinary):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_div(self, ctx: EvalCtx, l: ColV, r: ColV, zero):
        xp = ctx.xp
        one = xp.asarray(1, dtype=r.data.dtype)
        safe = xp.where(zero, one, r.data)
        if self.operand_dtype().is_floating:
            m = xp.fmod(l.data, safe)
            return xp.where(m < 0, xp.fmod(m + safe, safe), m)
        m = xp.mod(xp.mod(l.data, safe) + safe, safe)
        return m


@dataclass(frozen=True)
class UnaryMinus(UnaryExpression):
    c: Expression

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        return _wrapping(ctx, lambda: -child.data)


@dataclass(frozen=True)
class UnaryPositive(UnaryExpression):
    c: Expression

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        return child.data


@dataclass(frozen=True)
class Abs(UnaryExpression):
    c: Expression

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        return _wrapping(ctx, lambda: ctx.xp.abs(child.data))


@dataclass(frozen=True)
class Least(Expression):
    exprs: tuple

    def dtype(self) -> DType:
        return DType.common_type_all([e.dtype() for e in self.exprs])

    def eval(self, ctx: EvalCtx) -> ColV:
        return _least_greatest(self, ctx, greatest=False)


@dataclass(frozen=True)
class Greatest(Expression):
    exprs: tuple

    def dtype(self) -> DType:
        return DType.common_type_all([e.dtype() for e in self.exprs])

    def eval(self, ctx: EvalCtx) -> ColV:
        return _least_greatest(self, ctx, greatest=True)


def _least_greatest(node, ctx: EvalCtx, greatest: bool) -> ColV:
    """Spark least/greatest skip nulls; NaN is greater than any other value."""
    from spark_rapids_tpu.exprs.core import widen
    xp = ctx.xp
    dt = node.dtype()
    vals = [widen(ctx, e.eval(ctx), dt) for e in node.exprs]
    out_data = None
    out_valid = None
    for v in vals:
        if out_data is None:
            out_data, out_valid = v.data, v.validity
            continue
        if greatest:
            better = xp.logical_or(_nan_gt(xp, v.data, out_data),
                                   xp.logical_not(out_valid))
        else:
            better = xp.logical_or(_nan_gt(xp, out_data, v.data),
                                   xp.logical_not(out_valid))
        take = xp.logical_and(v.validity, better)
        out_data = xp.where(take, v.data, out_data)
        out_valid = xp.logical_or(out_valid, v.validity)
    return ColV(dt, out_data, out_valid,
                is_scalar=all(v.is_scalar for v in vals))


def _nan_gt(xp, a, b):
    """a > b with NaN treated as greater than everything (Spark ordering)."""
    if np.issubdtype(np.asarray(a).dtype if xp is np else a.dtype, np.floating):
        a_nan = xp.isnan(a)
        b_nan = xp.isnan(b)
        return xp.logical_or(xp.logical_and(a_nan, xp.logical_not(b_nan)),
                             xp.logical_and(xp.logical_not(b_nan), a > b))
    return a > b
