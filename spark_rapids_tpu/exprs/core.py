"""Expression tree core: typing, binding, and columnar evaluation.

Reference analogs:
- ``GpuExpressions.scala:367`` — Gpu{Unary,Binary,Ternary}Expression base traits with
  null-propagation conventions;
- ``GpuBoundAttribute.scala:97`` — binding attribute references to column ordinals;
- the ``columnarEval(batch)`` evaluation model returning a column or a scalar.

TPU re-design: instead of issuing one device kernel per node (cuDF JNI style), a bound
expression tree *emits* a jax computation over column arrays. The enclosing exec jits
the whole tree (plus any surrounding filter/aggregate logic) into ONE fused XLA
program per (expression tree, schema, capacity bucket) — eliminating per-op kernel
launch and intermediate HBM traffic, which is where XLA beats a cuDF-call-per-op
design on TPU.

Evaluation is generic over the array namespace ``xp``: ``jax.numpy`` when tracing the
device program, plain ``numpy`` for the eager CPU engine — one semantics definition,
two execution paths.
"""
from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType, Schema


@dataclass(frozen=True)
class ColV:
    """A columnar value during evaluation: data + validity (+ lengths for strings).

    ``data``/``validity``/``lengths`` are xp arrays (jnp tracers on device, numpy on
    CPU). A scalar result is represented as 0-d arrays with ``is_scalar=True``.
    """
    dtype: DType
    data: Any
    validity: Any
    lengths: Optional[Any] = None
    is_scalar: bool = False

    def with_validity(self, validity: Any) -> "ColV":
        return ColV(self.dtype, self.data, validity, self.lengths, self.is_scalar)


class EvalCtx:
    """Evaluation context handed to Expression.eval.

    ``xp``: array namespace (numpy or jax.numpy).
    ``columns``: input columns of the child batch as ColVs.
    ``capacity``: row capacity of the arrays (static under jit).
    ``string_max_bytes``: device string width for newly materialized strings.
    """

    def __init__(self, xp, columns: Sequence[ColV], capacity: int,
                 string_max_bytes: int = 256):
        self.xp = xp
        self.columns = list(columns)
        self.capacity = capacity
        self.string_max_bytes = string_max_bytes
        #: ordinal -> columnar.encoding.EncView for input columns whose
        #: dictionary encoding survived upload; encoded-domain expressions
        #: (exprs/encoded.py) evaluate against these instead of the decoded
        #: columns. Populated only by execs that flatten encodings through
        #: their jit boundary.
        self.encodings = {}

    @property
    def is_tracing(self) -> bool:
        return self.xp is not np


class Expression:
    """Immutable expression node. Subclasses are frozen dataclasses."""

    # ---- static structure --------------------------------------------------------
    @property
    def children(self) -> Tuple["Expression", ...]:
        out = []
        for f in fields(self):  # type: ignore[arg-type]
            v = getattr(self, f.name)
            if isinstance(v, Expression):
                out.append(v)
            elif isinstance(v, tuple):
                out.extend(c for c in v if isinstance(c, Expression))
        return tuple(out)

    def map_children(self, fn) -> "Expression":
        """Rebuild this node with fn applied to each child expression."""
        kwargs = {}
        changed = False
        for f in fields(self):  # type: ignore[arg-type]
            v = getattr(self, f.name)
            if isinstance(v, Expression):
                nv = fn(v)
                changed |= nv is not v
                kwargs[f.name] = nv
            elif isinstance(v, tuple) and any(isinstance(c, Expression) for c in v):
                nv = tuple(fn(c) if isinstance(c, Expression) else c for c in v)
                changed |= nv != v
                kwargs[f.name] = nv
            else:
                kwargs[f.name] = v
        return type(self)(**kwargs) if changed else self

    # ---- typing ------------------------------------------------------------------
    def dtype(self) -> DType:
        raise NotImplementedError(type(self).__name__)

    def nullable(self) -> bool:
        return True

    @property
    def name_hint(self) -> str:
        return type(self).__name__.lower()

    def sql_name(self) -> str:
        return type(self).__name__

    # ---- evaluation --------------------------------------------------------------
    def eval(self, ctx: EvalCtx) -> ColV:
        raise NotImplementedError(type(self).__name__)

    def __str__(self) -> str:
        args = ", ".join(str(c) for c in self.children)
        return f"{type(self).__name__}({args})"


# --------------------------------------------------------------------------------------
# References and binding (GpuBoundAttribute.scala analog)
# --------------------------------------------------------------------------------------
@dataclass(frozen=True)
class UnresolvedAttribute(Expression):
    """Column reference by name; must be bound before evaluation."""
    name: str

    def dtype(self) -> DType:
        raise TypeError(f"unresolved attribute {self.name!r} has no type; bind first")

    def eval(self, ctx: EvalCtx) -> ColV:
        raise TypeError(f"cannot evaluate unresolved attribute {self.name!r}")

    @property
    def name_hint(self) -> str:
        return self.name

    def __str__(self) -> str:
        return f"'{self.name}"


@dataclass(frozen=True)
class BoundReference(Expression):
    """Column reference by ordinal, resolved against a schema."""
    ordinal: int
    ref_dtype: DType
    ref_nullable: bool = True
    ref_name: str = ""

    def dtype(self) -> DType:
        return self.ref_dtype

    def nullable(self) -> bool:
        return self.ref_nullable

    @property
    def name_hint(self) -> str:
        return self.ref_name or f"c{self.ordinal}"

    def eval(self, ctx: EvalCtx) -> ColV:
        return ctx.columns[self.ordinal]

    def __str__(self) -> str:
        return f"input[{self.ordinal}, {self.ref_dtype.value}]"


def bind_expression(expr: Expression, schema: Schema) -> Expression:
    """Replace UnresolvedAttribute with BoundReference (GpuBindReferences analog)."""
    from spark_rapids_tpu.exprs.misc import _InputFileMeta

    def rec(e: Expression) -> Expression:
        if isinstance(e, UnresolvedAttribute):
            i = schema.index_of(e.name)
            f = schema[i]
            return BoundReference(i, f.dtype, f.nullable, f.name)
        if isinstance(e, _InputFileMeta) and e._col in schema.names():
            # input-file metadata marker -> the scan's hidden column
            i = schema.index_of(e._col)
            f = schema[i]
            return BoundReference(i, f.dtype, f.nullable, f.name)
        return e.map_children(rec)
    return rec(expr)


# --------------------------------------------------------------------------------------
# Null-propagation helper bases (GpuExpressions.scala:367 analog)
# --------------------------------------------------------------------------------------
def and_validity(xp, *vals: ColV):
    """Validity of a null-intolerant op: all inputs valid."""
    out = None
    for v in vals:
        out = v.validity if out is None else xp.logical_and(out, v.validity)
    return out


class UnaryExpression(Expression):
    """Null-intolerant unary op: output null where input null."""

    @property
    def child(self) -> Expression:
        return self.children[0]

    def dtype(self) -> DType:
        return self.child.dtype()

    def nullable(self) -> bool:
        return self.child.nullable()

    def eval(self, ctx: EvalCtx) -> ColV:
        c = self.child.eval(ctx)
        data = self.do_columnar(ctx, c)
        return ColV(self.dtype(), data, c.validity, is_scalar=c.is_scalar)

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        raise NotImplementedError


class BinaryExpression(Expression):
    """Null-intolerant binary op with numeric widening of operands."""

    @property
    def left(self) -> Expression:
        return self.children[0]

    @property
    def right(self) -> Expression:
        return self.children[1]

    def nullable(self) -> bool:
        return self.left.nullable() or self.right.nullable()

    def operand_dtype(self) -> DType:
        lt, rt = self.left.dtype(), self.right.dtype()
        if lt == rt:
            return lt
        return DType.common_numeric(lt, rt)

    def eval(self, ctx: EvalCtx) -> ColV:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        l, r = cast_operands(ctx, l, r, self.operand_dtype())
        validity = and_validity(ctx.xp, l, r)
        data = self.do_columnar(ctx, l, r)
        return ColV(self.dtype(), data, validity,
                    is_scalar=l.is_scalar and r.is_scalar)

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        raise NotImplementedError


# ---- flat columnar layout (shared by jit boundaries everywhere) ---------------
def flat_len(schema) -> int:
    """Number of flat array slots for a schema: strings use 3 (data, validity,
    lengths), everything else 2."""
    return sum(3 if f.dtype is DType.STRING else 2 for f in schema)


def flatten_colvs(colvs: Sequence[ColV]) -> list:
    flat = []
    for v in colvs:
        flat.append(v.data)
        flat.append(v.validity)
        if v.dtype is DType.STRING:
            flat.append(v.lengths)
    return flat


def unflatten_colvs(schema, flat) -> list:
    cols, i = [], 0
    for f in schema:
        if f.dtype is DType.STRING:
            cols.append(ColV(f.dtype, flat[i], flat[i + 1], flat[i + 2]))
            i += 3
        else:
            cols.append(ColV(f.dtype, flat[i], flat[i + 1]))
            i += 2
    return cols


def widen(ctx: EvalCtx, v: ColV, to: DType) -> ColV:
    """Convert a branch/operand value to the resolved common type.

    Handles the NULL-typed literal case (produces a properly shaped all-null value
    of the target type, including string lengths) and numeric widening. Shared by
    Coalesce/If/CaseWhen/Least/Greatest so multi-branch type resolution cannot
    diverge between nodes.
    """
    if v.dtype == to:
        return v
    xp = ctx.xp
    if v.dtype is DType.NULL:
        false = xp.zeros_like(v.validity, dtype=bool)
        if to is DType.STRING:
            shape = ((ctx.string_max_bytes,) if v.is_scalar
                     else (ctx.capacity, ctx.string_max_bytes))
            data = xp.zeros(shape, dtype=np.uint8)
            lengths = xp.zeros(shape[:-1], dtype=np.int32)
            return ColV(to, data, false, lengths, is_scalar=v.is_scalar)
        return ColV(to, v.data.astype(to.np_dtype()), false, is_scalar=v.is_scalar)
    if v.dtype.is_numeric and to.is_numeric:
        return ColV(to, v.data.astype(to.np_dtype()), v.validity,
                    is_scalar=v.is_scalar)
    raise TypeError(f"cannot widen {v.dtype} to {to}")


def cast_operands(ctx: EvalCtx, l: ColV, r: ColV, to: DType) -> Tuple[ColV, ColV]:
    """Widen both operands to the common numeric type (no-op for matching types)."""
    def w(v: ColV) -> ColV:
        if v.dtype == to or v.dtype is DType.STRING:
            return v
        return ColV(to, v.data.astype(to.np_dtype()), v.validity,
                    is_scalar=v.is_scalar)
    return w(l), w(r)
