"""Date/time expressions (reference: datetimeExpressions.scala, 533 LoC).

All timestamps are UTC microseconds (the reference likewise gates GPU datetime ops
to UTC/corrected-rebase). Calendar decomposition uses Howard Hinnant's
civil-from-days algorithm — pure integer vector math, no lookup tables, ideal for
the VPU.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression

MICROS_PER_DAY = 86_400_000_000


def civil_from_days(xp, z):
    """days since 1970-01-01 -> (year, month [1,12], day [1,31]); vectorized."""
    z = z.astype(np.int64) + 719468
    # Hinnant's C++ adjusts for truncating division; // already floors, so the
    # plain floor quotient is the correct era for negative days too.
    era = z // 146097
    doe = z - era * 146097                                    # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)           # [0, 365]
    mp = (5 * doy + 2) // 153                                 # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                         # [1, 31]
    m = mp + xp.where(mp < 10, 3, -9)                         # [1, 12]
    y = y + (m <= 2)
    return y.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


def _days_of(v: ColV, xp):
    """DATE column -> days; TIMESTAMP column -> days (floor, UTC)."""
    if v.dtype is DType.DATE:
        return v.data.astype(np.int64)
    return v.data // MICROS_PER_DAY


class _DatePart(Expression):
    part: str = ""

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        days = _days_of(v, xp)
        y, m, d = civil_from_days(xp, days)
        data = {"year": y, "month": m, "day": d}[self.part]
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Year(_DatePart):
    c: Expression
    part = "year"


@dataclass(frozen=True)
class Month(_DatePart):
    c: Expression
    part = "month"


@dataclass(frozen=True)
class DayOfMonth(_DatePart):
    c: Expression
    part = "day"


@dataclass(frozen=True)
class DayOfWeek(Expression):
    """1 = Sunday ... 7 = Saturday (Spark)."""
    c: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        days = _days_of(v, xp)
        # 1970-01-01 was a Thursday; Sunday-based index:
        data = ((days + 4) % 7 + 1).astype(np.int32)
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class DayOfYear(Expression):
    c: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        days = _days_of(v, xp)
        y, _, _ = civil_from_days(xp, days)
        jan1 = days_from_civil(xp, y, xp.ones_like(y), xp.ones_like(y))
        data = (days - jan1 + 1).astype(np.int32)
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


def days_from_civil(xp, y, m, d):
    """(year, month, day) -> days since epoch; inverse of civil_from_days."""
    y = y.astype(np.int64) - (m <= 2)
    era = y // 400  # floor division; see civil_from_days note
    yoe = y - era * 400
    mp = (m.astype(np.int64) + xp.where(m > 2, -3, 9))
    doy = (153 * mp + 2) // 5 + d.astype(np.int64) - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class _TimePart(Expression):
    divisor: int = 1
    modulus: int = 1

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        tod = v.data - (v.data // MICROS_PER_DAY) * MICROS_PER_DAY
        data = ((tod // self.divisor) % self.modulus).astype(np.int32)
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Hour(_TimePart):
    c: Expression
    divisor = 3_600_000_000
    modulus = 24


@dataclass(frozen=True)
class Minute(_TimePart):
    c: Expression
    divisor = 60_000_000
    modulus = 60


@dataclass(frozen=True)
class Second(_TimePart):
    c: Expression
    divisor = 1_000_000
    modulus = 60


@dataclass(frozen=True)
class DateAdd(Expression):
    """date_add(date, n days)."""
    c: Expression
    n: Expression

    def dtype(self) -> DType:
        return DType.DATE

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        n = self.n.eval(ctx)
        data = (v.data + n.data.astype(np.int32)).astype(np.int32)
        valid = xp.logical_and(v.validity, n.validity)
        return ColV(DType.DATE, data, valid, is_scalar=v.is_scalar and n.is_scalar)


@dataclass(frozen=True)
class DateSub(Expression):
    c: Expression
    n: Expression

    def dtype(self) -> DType:
        return DType.DATE

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        n = self.n.eval(ctx)
        data = (v.data - n.data.astype(np.int32)).astype(np.int32)
        valid = xp.logical_and(v.validity, n.validity)
        return ColV(DType.DATE, data, valid, is_scalar=v.is_scalar and n.is_scalar)


@dataclass(frozen=True)
class DateDiff(Expression):
    """datediff(end, start) in days."""
    end: Expression
    start: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        e = self.end.eval(ctx)
        s = self.start.eval(ctx)
        data = (e.data.astype(np.int32) - s.data.astype(np.int32))
        valid = xp.logical_and(e.validity, s.validity)
        return ColV(DType.INT, data, valid, is_scalar=e.is_scalar and s.is_scalar)


@dataclass(frozen=True)
class LastDay(Expression):
    c: Expression

    def dtype(self) -> DType:
        return DType.DATE

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        days = _days_of(v, xp)
        y, m, _ = civil_from_days(xp, days)
        ny = xp.where(m == 12, y + 1, y)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(xp, ny, nm, xp.ones_like(nm))
        return ColV(DType.DATE, (first_next - 1).astype(np.int32), v.validity,
                    is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Quarter(Expression):
    c: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        _, m, _ = civil_from_days(xp, _days_of(v, xp))
        return ColV(DType.INT, ((m - 1) // 3 + 1).astype(np.int32), v.validity,
                    is_scalar=v.is_scalar)


@dataclass(frozen=True)
class WeekDay(Expression):
    """0 = Monday ... 6 = Sunday (Spark WeekDay; datetimeExpressions.scala)."""
    c: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        days = _days_of(v, xp)
        # 1970-01-01 was a Thursday (weekday 3)
        data = ((days + 3) % 7).astype(np.int32)
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


MICROS_PER_SECOND = 1_000_000


@dataclass(frozen=True)
class ToUnixTimestamp(Expression):
    """to_unix_timestamp(ts_or_date): UTC epoch seconds (the default
    yyyy-MM-dd HH:mm:ss format path of datetimeExpressions.scala
    GpuToUnixTimestamp — non-default formats stay on CPU, same gate as the
    reference's incompatible-format tagging)."""
    c: Expression

    def dtype(self) -> DType:
        return DType.LONG

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        if v.dtype is DType.DATE:
            secs = v.data.astype(np.int64) * 86_400
        else:
            secs = v.data.astype(np.int64) // MICROS_PER_SECOND
        return ColV(DType.LONG, secs, v.validity, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class UnixTimestamp(ToUnixTimestamp):
    """unix_timestamp(col) — same kernel as ToUnixTimestamp (Spark's two
    names for the epoch-seconds conversion)."""
    c: Expression


@dataclass(frozen=True)
class FromUnixTime(Expression):
    """from_unixtime(seconds): epoch seconds -> 'yyyy-MM-dd HH:mm:ss' string
    (default format only; UTC — GpuFromUnixTime analog)."""
    c: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        secs = v.data.astype(np.int64)
        days = secs // 86_400
        tod = secs - days * 86_400
        y, m, d = civil_from_days(xp, days)
        hh = (tod // 3600).astype(np.int64)
        mm = ((tod % 3600) // 60).astype(np.int64)
        ss = (tod % 60).astype(np.int64)
        W = 19

        def dig(x, p10):
            return ((x // p10) % 10 + 48).astype(np.uint8)

        cols = [dig(y, 1000), dig(y, 100), dig(y, 10), dig(y, 1),
                xp.full_like(ss, 45).astype(np.uint8),
                dig(m, 10), dig(m, 1),
                xp.full_like(ss, 45).astype(np.uint8),
                dig(d, 10), dig(d, 1),
                xp.full_like(ss, 32).astype(np.uint8),
                dig(hh, 10), dig(hh, 1),
                xp.full_like(ss, 58).astype(np.uint8),
                dig(mm, 10), dig(mm, 1),
                xp.full_like(ss, 58).astype(np.uint8),
                dig(ss, 10), dig(ss, 1)]
        if getattr(v.data, "ndim", 0) == 0:
            data = xp.stack(cols).astype(np.uint8)
            lengths = xp.asarray(np.int32(W))
        else:
            data = xp.stack(cols, axis=-1).astype(np.uint8)
            lengths = xp.full(v.data.shape, W, dtype=np.int32)
        return ColV(DType.STRING, data, v.validity, lengths,
                    is_scalar=v.is_scalar)
