"""Encoded-domain expressions: evaluate on dictionary INDICES, not values.

The compressed columnar path (columnar/encoding.py) keeps each uploaded
column's dictionary encoding on device. These expression nodes exploit it —
the late-materialization piece of ROADMAP item 1, following "GPU
Acceleration of SQL Analytics on Compressed Data" (PAPERS.md): operators
that only need value EQUALITY or a per-distinct-value verdict run over the
k dictionary slots (or the int32 index vector) instead of the n decoded
rows, and the decoded values materialize only where an operator truly needs
them.

- ``DictDomainGather``: a row-wise boolean predicate over ONE encoded
  column evaluates once per dictionary slot (k rows), then a single gather
  broadcasts the verdict to all n rows. For string predicates this replaces
  n x width byte comparisons with k x width plus an int gather.
- ``EncodedKeyRef``: group-by / join keys read the index vector as an int32
  column. Distinct indices <=> distinct values (dictionary uniqueness is
  checked at upload), so grouping and equi-join semantics are preserved —
  and int keys unlock the sort-free one-hot aggregation path that string
  keys cannot take.
- ``materialize_key``: after aggregation, the surviving group keys (one row
  per GROUP, not per input row) gather their decoded values back — the
  deferred materialization.

Planner/exec wiring lives in plan/encoded.py and execs/tpu_execs.py /
execs/join_execs.py; tpu-lint's R001/R002 apply to these code paths like
any other (EncSpec is part of every jit cache key, and nothing here syncs).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Set, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.columnar.encoding import EncSpec, EncView
from spark_rapids_tpu.exprs.core import (BoundReference, ColV, EvalCtx,
                                         Expression)


@dataclass(frozen=True)
class DictDomainGather(Expression):
    """Evaluate ``pred`` (bound to ordinal 0 of a one-column dictionary
    schema) over the k dictionary values of input column ``ordinal``, then
    gather the per-slot verdict through the index vector. ``k`` is static
    (part of the jit cache key via this node's equality)."""

    pred: Expression
    ordinal: int
    k: int

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def nullable(self) -> bool:
        return True

    def sql_name(self) -> str:
        return f"DictDomain({self.pred.sql_name()})"

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        enc: EncView = ctx.encodings[self.ordinal]
        sub = EvalCtx(xp, [enc.values], self.k, ctx.string_max_bytes)
        # scalar context attrs (partition_id etc.) carry over
        for a in ("partition_id",):
            if hasattr(ctx, a):
                setattr(sub, a, getattr(ctx, a))
        pv = self.pred.eval(sub)
        data = xp.broadcast_to(pv.data, (self.k,))
        valid = xp.broadcast_to(pv.validity, (self.k,))
        col_valid = ctx.columns[self.ordinal].validity
        return ColV(DType.BOOLEAN, xp.take(data, enc.indices, axis=0),
                    xp.logical_and(xp.take(valid, enc.indices, axis=0),
                                   col_valid))


@dataclass(frozen=True)
class EncodedKeyRef(Expression):
    """The dictionary-index vector of input column ``ordinal`` as an int32
    key column. Validity is the column's own (null rows stay null keys)."""

    ordinal: int
    k: int
    ref_dtype: DType                 # the ORIGINAL value dtype (for explain)
    ref_name: str = ""

    def dtype(self) -> DType:
        return DType.INT

    def nullable(self) -> bool:
        return True

    @property
    def name_hint(self) -> str:
        return self.ref_name or f"c{self.ordinal}"

    def eval(self, ctx: EvalCtx) -> ColV:
        enc: EncView = ctx.encodings[self.ordinal]
        return ColV(DType.INT, enc.indices,
                    ctx.columns[self.ordinal].validity)


def materialize_key(ctx: EvalCtx, spec: EncSpec, key: ColV) -> ColV:
    """Late materialization: turn a reduced index-key column (one row per
    group) back into its decoded values with a k-bounded gather."""
    xp = ctx.xp
    enc: EncView = ctx.encodings[spec.ordinal]
    idx = xp.clip(key.data.astype(np.int32), 0, spec.k - 1)
    data = xp.take(enc.values.data, idx, axis=0)
    lengths = (xp.take(enc.values.lengths, idx, axis=0)
               if enc.values.lengths is not None else None)
    return ColV(spec.dtype, data, key.validity, lengths)


@dataclass(frozen=True)
class EncJoinKey:
    """One equi-join key pair that matches on the index domain. With
    ``same_token`` the two sides share a prefix-compatible dictionary and
    indices compare directly; otherwise the right dictionary remaps into
    the left one on device (k_l x k_r work — tiny next to n)."""
    pos: int
    left: EncSpec
    right: EncSpec
    same_token: bool


def dict_remap(xp, lvals: ColV, rvals: ColV, k_left: int,
               l_k_real, r_k_real):
    """int32[k_right] mapping each right-dictionary slot to the left slot
    holding the same value, or the sentinel ``k_left`` when the value does
    not occur on the left (the sentinel equals no left index, so those rows
    simply never match — exactly the decoded join's behavior).

    One k_l x k_r equality matrix (tiny next to n; callers cap the cell
    count). ``l_k_real``/``r_k_real`` are traced live counts masking the
    PADDING slots of the bucketed dictionaries — a pad zero must never
    claim a real value's match."""
    kl, kr = lvals.data.shape[0], rvals.data.shape[0]
    live = xp.logical_and(
        (xp.arange(kl, dtype=np.int32) < l_k_real)[:, None],
        (xp.arange(kr, dtype=np.int32) < r_k_real)[None, :])
    if lvals.lengths is None:
        eq = lvals.data[:, None] == rvals.data[None, :]
    else:
        from spark_rapids_tpu.ops.strings import pad_width
        L, R = lvals.data, rvals.data
        W = max(L.shape[1], R.shape[1])
        L, R = pad_width(xp, L, W), pad_width(xp, R, W)
        eq = xp.logical_and(
            (L[:, None, :] == R[None, :, :]).all(axis=-1),
            lvals.lengths[:, None] == rvals.lengths[None, :])
    eq = xp.logical_and(eq, live)
    found = eq.any(axis=0)
    return xp.where(found, xp.argmax(eq, axis=0),
                    k_left).astype(np.int32)


# ---------------------------------------------------------------- rewriting
def _refs(e: Expression, out: Set[int]) -> None:
    if isinstance(e, BoundReference):
        out.add(e.ordinal)
    for c in e.children:
        _refs(c, out)


def _domain_safe(e: Expression) -> bool:
    """True when evaluating ``e`` once per DISTINCT dictionary value and
    gathering the verdict is equivalent to per-row evaluation.

    The gather sees only VALID dictionary values and then forces null rows
    to a null verdict (validity AND), so the rewrite is sound exactly for
    expressions with ``f(NULL) is NULL`` null propagation and no positional
    state. That is enforced by WHITELIST, not blacklist:

    - Literal / BoundReference leaves;
    - nodes that inherit the Unary/BinaryExpression base ``eval`` (those
      bases ARE the null-intolerant convention — a subclass overriding
      eval, like EqualNullSafe's null-safe equality or NaNvl, is excluded
      automatically);
    - And / Or / Not / In / InSet, whose explicit three-valued logic still
      yields a null verdict for a null input within a single-column
      subtree (verified case by case — e.g. Kleene AND of two verdicts of
      the SAME null row is null on both paths).

    Everything else (IsNull/Coalesce/If/CaseWhen produce non-null results
    from null inputs; Rand and ids have positional state; aggregates and
    windows are not row-wise) stays on the decoded path."""
    from spark_rapids_tpu.exprs import predicates as pr
    from spark_rapids_tpu.exprs.core import (BinaryExpression,
                                             UnaryExpression)
    from spark_rapids_tpu.exprs.literals import Literal
    if isinstance(e, (Literal, BoundReference)):
        return True
    ok = False
    if isinstance(e, (pr.And, pr.Or, pr.Not, pr.In, pr.InSet)):
        ok = True
    elif isinstance(e, (UnaryExpression, BinaryExpression)):
        ok = type(e).eval in (UnaryExpression.eval, BinaryExpression.eval)
    return ok and all(_domain_safe(c) for c in e.children)


def _rebind_to_slot0(e: Expression, ordinal: int) -> Expression:
    if isinstance(e, BoundReference):
        assert e.ordinal == ordinal
        return BoundReference(0, e.ref_dtype, e.ref_nullable, e.ref_name)
    return e.map_children(lambda c: _rebind_to_slot0(c, ordinal))


def rewrite_predicate(cond: Expression, specs: Sequence[EncSpec]
                      ) -> Tuple[Expression, Tuple[EncSpec, ...]]:
    """Rewrite every maximal boolean subtree of ``cond`` that references
    exactly one encoded column into a DictDomainGather over that column's
    dictionary. Returns (rewritten condition, the EncSpecs actually used).
    A condition with no eligible subtree comes back unchanged."""
    by_ord: Dict[int, EncSpec] = {s.ordinal: s for s in specs}
    used: Dict[int, EncSpec] = {}

    def rec(e: Expression) -> Expression:
        refs: Set[int] = set()
        _refs(e, refs)
        if (len(refs) == 1 and not isinstance(e, BoundReference)
                and e.children):
            (o,) = tuple(refs)
            spec = by_ord.get(o)
            if spec is not None and _domain_safe(e):
                try:
                    is_bool = e.dtype() is DType.BOOLEAN
                except TypeError:
                    is_bool = False
                if is_bool:
                    used[o] = spec
                    return DictDomainGather(_rebind_to_slot0(e, o), o,
                                            spec.k)
        return e.map_children(rec)

    out = rec(cond)
    return out, tuple(sorted(used.values(), key=lambda s: s.ordinal))


def rewrite_grouping(grouping: Sequence[Expression],
                     specs: Sequence[EncSpec]
                     ) -> Tuple[Tuple[Expression, ...],
                                Dict[int, EncSpec],
                                Tuple[EncSpec, ...]]:
    """Substitute grouping keys that are plain references to encoded columns
    with their index vectors. Returns (new grouping, {key position ->
    EncSpec} for later materialization, EncSpecs used)."""
    by_ord: Dict[int, EncSpec] = {s.ordinal: s for s in specs}
    out = []
    subs: Dict[int, EncSpec] = {}
    used: Dict[int, EncSpec] = {}
    for j, g in enumerate(grouping):
        spec = (by_ord.get(g.ordinal)
                if isinstance(g, BoundReference) else None)
        if spec is not None and spec.dtype.is_floating:
            # index identity is FINER than float equality (-0.0 vs 0.0 are
            # distinct dictionary slots but equal keys): floats stay decoded
            spec = None
        if spec is not None:
            out.append(EncodedKeyRef(g.ordinal, spec.k, g.ref_dtype,
                                     g.ref_name))
            subs[j] = spec
            used[spec.ordinal] = spec
        else:
            out.append(g)
    return (tuple(out), subs,
            tuple(sorted(used.values(), key=lambda s: s.ordinal)))
