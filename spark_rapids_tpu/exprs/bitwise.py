"""Bitwise expressions (reference: bitwise.scala, 145 LoC). Java semantics:
shifts mask the shift amount by the width (x << 33 == x << 1 for int)."""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import (BinaryExpression, ColV, EvalCtx, Expression,
                                         UnaryExpression)


@dataclass(frozen=True)
class BitwiseAnd(BinaryExpression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return l.data & r.data


@dataclass(frozen=True)
class BitwiseOr(BinaryExpression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return l.data | r.data


@dataclass(frozen=True)
class BitwiseXor(BinaryExpression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return self.operand_dtype()

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return l.data ^ r.data


@dataclass(frozen=True)
class BitwiseNot(UnaryExpression):
    c: Expression

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        return ~child.data


class _Shift(Expression):
    def dtype(self) -> DType:
        return self.children[0].dtype()

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        width = v.dtype.element_size() * 8
        amount = (s.data & (width - 1)).astype(v.data.dtype)
        data = self.do_shift(xp, v.data, amount, width)
        valid = xp.logical_and(v.validity, s.validity)
        return ColV(v.dtype, data, valid, is_scalar=v.is_scalar and s.is_scalar)

    def do_shift(self, xp, d, amount, width):
        raise NotImplementedError


@dataclass(frozen=True)
class ShiftLeft(_Shift):
    l: Expression
    r: Expression

    def do_shift(self, xp, d, amount, width):
        return xp.left_shift(d, amount)


@dataclass(frozen=True)
class ShiftRight(_Shift):
    """Arithmetic (sign-extending) right shift."""
    l: Expression
    r: Expression

    def do_shift(self, xp, d, amount, width):
        return xp.right_shift(d, amount)


@dataclass(frozen=True)
class ShiftRightUnsigned(_Shift):
    """Logical right shift (>>> in Java): zero-fill."""
    l: Expression
    r: Expression

    def do_shift(self, xp, d, amount, width):
        unsigned = {32: np.uint32, 64: np.uint64}[width]
        shifted = xp.right_shift(d.astype(unsigned), amount.astype(unsigned))
        return shifted.astype(d.dtype)
