"""String function expressions (reference: stringFunctions.scala, 862 LoC).

All operate on the fixed-width byte-matrix layout via ops/strings kernels.
Upper/Lower are ASCII-only on device (non-ASCII bytes pass through unchanged);
full-unicode case mapping falls back to CPU, mirroring the reference's
incompat gating of cuDF's case ops.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression, UnaryExpression
from spark_rapids_tpu.exprs.literals import Literal
from spark_rapids_tpu.ops import strings as sk


def _as_column(xp, v: ColV, capacity: int) -> ColV:
    """Broadcast a scalar string ColV to a column of the given capacity."""
    if not v.is_scalar:
        return v
    W = v.data.shape[-1]
    data = xp.broadcast_to(v.data[None, :], (capacity, W))
    lengths = xp.broadcast_to(xp.reshape(v.lengths, (1,)), (capacity,))
    validity = xp.broadcast_to(xp.reshape(v.validity, (1,)), (capacity,))
    return ColV(DType.STRING, data, lengths=lengths, validity=validity)


@dataclass(frozen=True)
class Upper(UnaryExpression):
    c: Expression

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.STRING, sk.upper_ascii(ctx.xp, v.data), v.validity,
                    v.lengths, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Lower(UnaryExpression):
    c: Expression

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.STRING, sk.lower_ascii(ctx.xp, v.data), v.validity,
                    v.lengths, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Length(Expression):
    """Character (not byte) length, like Spark's length()."""
    c: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        data = sk.char_lengths(ctx.xp, v.data, v.lengths)
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


class _ConstPatternPredicate(Expression):
    """Base for StartsWith/EndsWith/Contains with a literal pattern (the reference
    also requires literal patterns for these — GpuOverrides string rules)."""

    def dtype(self) -> DType:
        return DType.BOOLEAN

    @property
    def pattern(self) -> bytes:
        lit = self.children[1]
        if not isinstance(lit, Literal) or lit.value is None:
            raise TypeError(f"{type(self).__name__} requires a non-null literal pattern")
        return str(lit.value).encode("utf-8")

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        col = _as_column(xp, v, ctx.capacity)
        W = col.data.shape[-1]
        data = self.do_match(xp, col, W)
        return ColV(DType.BOOLEAN, data, col.validity)

    def do_match(self, xp, col: ColV, W: int):
        raise NotImplementedError


@dataclass(frozen=True)
class StartsWith(_ConstPatternPredicate):
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        return sk.starts_with(xp, col.data, col.lengths, self.pattern, W)


@dataclass(frozen=True)
class EndsWith(_ConstPatternPredicate):
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        return sk.ends_with(xp, col.data, col.lengths, self.pattern, W)


@dataclass(frozen=True)
class Contains(_ConstPatternPredicate):
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        return sk.contains(xp, col.data, col.lengths, self.pattern, W)


@dataclass(frozen=True)
class Like(_ConstPatternPredicate):
    r"""SQL LIKE with literal pattern. Device path supports patterns that reduce to
    anchored/substring matches: 'abc', 'abc%', '%abc', '%abc%' (no '_', no inner
    '%'); everything else is tagged for CPU fallback by the plan layer."""
    c: Expression
    p: Expression
    escape: str = "\\"

    @staticmethod
    def classify(pattern: str) -> Optional[Tuple[str, str]]:
        """Return (kind, needle) where kind in {exact, prefix, suffix, contains},
        or None if the pattern needs a real regex engine."""
        if "_" in pattern:
            return None
        body = pattern.strip("%")
        if "%" in body or "\\" in body:
            return None
        starts = pattern.startswith("%")
        ends = pattern.endswith("%")
        if starts and ends:
            return ("contains", body)
        if ends:
            return ("prefix", body)
        if starts:
            return ("suffix", body)
        return ("exact", body)

    @staticmethod
    def to_regex(pattern: str) -> str:
        """SQL LIKE pattern -> anchored python regex (escape char '\\')."""
        import re as _re
        out = []
        i = 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == "\\" and i + 1 < len(pattern):
                out.append(_re.escape(pattern[i + 1]))
                i += 2
                continue
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(_re.escape(ch))
            i += 1
        # \Z not $: $ would also match before a trailing newline
        return "^" + "".join(out) + r"\Z"

    def do_match(self, xp, col, W):
        pat = self.pattern.decode("utf-8")
        kind_needle = Like.classify(pat)
        if kind_needle is None:
            if xp is np:
                # eager CPU engine: full regex semantics (the fallback path the
                # plan layer routes unsupported patterns to)
                import re as _re
                rx = _re.compile(Like.to_regex(pat), _re.DOTALL)
                n = col.data.shape[0]
                res = np.zeros(n, dtype=bool)
                for i in range(n):
                    s = bytes(col.data[i, :col.lengths[i]]).decode(
                        "utf-8", errors="replace")
                    res[i] = rx.match(s) is not None
                return res
            # device: compiled DFA over the byte matrix (anchored; byte-level
            # semantics — '_' consumes one BYTE, so multibyte chars under '_'
            # diverge from Spark; ASCII scope like Upper/Lower)
            from spark_rapids_tpu.ops import regex as rk
            dfa = rk.compile_dfa(rk.like_to_regex(pat, self.escape))
            return rk.dfa_match(xp, dfa, col.data, col.lengths)
        kind, needle = kind_needle
        nb = needle.encode("utf-8")
        if kind == "contains":
            return sk.contains(xp, col.data, col.lengths, nb, W)
        if kind == "prefix":
            return sk.starts_with(xp, col.data, col.lengths, nb, W)
        if kind == "suffix":
            return sk.ends_with(xp, col.data, col.lengths, nb, W)
        eq_len = col.lengths == len(nb)
        return xp.logical_and(
            sk.starts_with(xp, col.data, col.lengths, nb, W), eq_len)


@dataclass(frozen=True)
class Substring(Expression):
    """substring(str, pos, len) with Spark 1-based/negative-pos semantics, on
    *character* positions (byte positions only when the column is pure ASCII is
    not assumed: we compute byte offsets from char offsets vectorized)."""
    c: Expression
    pos: Expression
    length: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        pos = self.pos.eval(ctx)
        ln = self.length.eval(ctx)
        W = v.data.shape[-1]
        nchars = sk.char_lengths(xp, v.data, v.lengths)
        p = pos.data.astype(np.int32)
        l = xp.maximum(ln.data.astype(np.int32), 0)
        # Spark: pos 1-based; 0 behaves like 1; negative counts from the end.
        start_char = xp.where(p > 0, p - 1,
                              xp.where(p == 0, 0, xp.maximum(nchars + p, 0)))
        start_char = xp.minimum(start_char, nchars)
        end_char = xp.minimum(start_char + l, nchars)
        start_b = sk.char_to_byte_offset(xp, v.data, v.lengths, start_char, W)
        end_b = sk.char_to_byte_offset(xp, v.data, v.lengths, end_char, W)
        data, lengths = sk.substring(xp, v.data, v.lengths, start_b,
                                     end_b - start_b, W)
        validity = xp.logical_and(v.validity,
                                  xp.logical_and(pos.validity, ln.validity))
        return ColV(DType.STRING, data, validity, lengths)


@dataclass(frozen=True)
class Concat(Expression):
    """concat(...): null if any input is null (Spark semantics)."""
    exprs: Tuple

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        vals = [_as_column(xp, e.eval(ctx), ctx.capacity) for e in self.exprs]
        out = vals[0]
        W = ctx.string_max_bytes
        for v in vals[1:]:
            data, lengths = sk.concat2(xp, out.data, out.lengths, v.data, v.lengths, W)
            validity = xp.logical_and(out.validity, v.validity)
            out = ColV(DType.STRING, data, validity, lengths)
        return out


class _TrimBase(Expression):
    """Shared trim machinery (reference: GpuStringTrim/Left/Right,
    stringFunctions.scala:211-266 — cudf strip with an optional literal
    trim-character set)."""
    left = True
    right = True

    def dtype(self) -> DType:
        return DType.STRING

    def _trim_chars(self) -> bytes:
        if self.trim is None:
            return b" "
        if not isinstance(self.trim, Literal) or self.trim.value is None:
            raise TypeError(f"{type(self).__name__} requires a literal "
                            f"trim-character set")
        chars = str(self.trim.value).encode("utf-8")
        if any(b > 127 for b in chars):
            # per-byte membership would strip partial UTF-8 sequences
            raise TypeError(f"{type(self).__name__} trim-character set must "
                            f"be ASCII (got {self.trim.value!r})")
        return chars

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        W = v.data.shape[-1]
        start, new_len = sk.trim_bounds(xp, v.data, v.lengths, W,
                                        self.left, self.right,
                                        self._trim_chars())
        data, lengths = sk.substring(xp, v.data, v.lengths, start, new_len, W)
        return ColV(DType.STRING, data, v.validity, lengths)


@dataclass(frozen=True)
class StringTrim(_TrimBase):
    """trim(str): strip the trim chars (default ASCII space) from both ends."""
    c: Expression
    trim: Optional[Expression] = None


@dataclass(frozen=True)
class StringTrimLeft(_TrimBase):
    c: Expression
    trim: Optional[Expression] = None
    right = False


@dataclass(frozen=True)
class StringTrimRight(_TrimBase):
    c: Expression
    trim: Optional[Expression] = None
    left = False


@dataclass(frozen=True)
class InitCap(UnaryExpression):
    """initcap: capitalize the letter after each space, lowercase the rest
    (Spark toLowerCase().toTitleCase(); ASCII scope like Upper/Lower)."""
    c: Expression

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.STRING, sk.initcap(ctx.xp, v.data, v.lengths),
                    v.validity, v.lengths, is_scalar=v.is_scalar)


def _literal_utf8(e: Expression, what: str) -> Optional[bytes]:
    """Constant string operand; None when the literal is null (callers emit a
    null column, matching the reference's scalar-operand handling)."""
    if not isinstance(e, Literal):
        raise TypeError(f"{what} must be a literal string")
    return None if e.value is None else str(e.value).encode("utf-8")


def _all_null(xp, dtype: DType, capacity: int, W: int = 0) -> ColV:
    if dtype is DType.STRING:
        return ColV(DType.STRING, xp.zeros((capacity, W), dtype=np.uint8),
                    xp.zeros(capacity, dtype=bool),
                    xp.zeros(capacity, dtype=np.int32))
    return ColV(dtype, xp.zeros(capacity, dtype=dtype.np_dtype()),
                xp.zeros(capacity, dtype=bool))


@dataclass(frozen=True)
class StringLocate(Expression):
    """locate(substr, str, start): 1-based character position of the first
    occurrence at or after character position start; 0 when absent. Literal
    substr/start like the reference (GpuStringLocate supports only the
    scalar-scalar-column form); the kernel converts char positions to/from
    byte offsets for multibyte UTF-8 data.

    Null/edge semantics mirror GpuStringLocate: null start -> 0; null substr
    -> null; start < 1 -> 0; empty substr -> 1 (for non-null rows)."""
    sub: Expression
    c: Expression
    start: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        W = v.data.shape[-1]
        if not isinstance(self.start, Literal):
            raise TypeError("locate requires a literal start position")
        needle = _literal_utf8(self.sub, "locate substring")
        if self.start.value is None:
            return ColV(DType.INT, xp.zeros(ctx.capacity, dtype=np.int32),
                        xp.ones(ctx.capacity, dtype=bool))
        if needle is None:
            return _all_null(xp, DType.INT, ctx.capacity)
        start1 = int(self.start.value)
        if start1 < 1:
            data = xp.zeros(ctx.capacity, dtype=np.int32)
        elif len(needle) == 0:
            data = xp.ones(ctx.capacity, dtype=np.int32)
        else:
            data = sk.locate(xp, v.data, v.lengths, needle, start1, W)
        return ColV(DType.INT, data, v.validity)


@dataclass(frozen=True)
class StringReplace(Expression):
    """replace(str, search, replace) with literal search/replace (the
    reference's GpuStringReplace supports only scalar operands). Null search
    or replace -> all-null result; empty search -> unchanged input."""
    c: Expression
    search: Expression
    replace: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        search = _literal_utf8(self.search, "replace search")
        repl = _literal_utf8(self.replace, "replace replacement")
        if search is None or repl is None:
            return _all_null(xp, DType.STRING, ctx.capacity,
                             v.data.shape[-1])
        if len(search) == 0:
            return v
        W_out = ctx.string_max_bytes
        data, lengths = sk.replace_const(xp, v.data, v.lengths, search, repl,
                                         W_out)
        return ColV(DType.STRING, data, v.validity, lengths)


class _PadBase(Expression):
    side = ""

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        if not isinstance(self.length, Literal):
            raise TypeError("pad length must be a literal")
        pad_bytes = _literal_utf8(self.pad, "pad string")
        if self.length.value is None or pad_bytes is None:
            return _all_null(xp, DType.STRING, ctx.capacity,
                             v.data.shape[-1])
        target = max(int(self.length.value), 0)
        # width bound: surviving prefix (≤ min(input W, 4 bytes/char · target))
        # plus the worst-case cyclic fill in BYTES of `target` pad CHARS
        bound = (min(v.data.shape[-1], 4 * target)
                 + sk.pad_fill_total_bytes(pad_bytes, target))
        W_out = max(v.data.shape[-1], min(bound, ctx.string_max_bytes))
        data, lengths = sk.pad(xp, v.data, v.lengths, target,
                               pad_bytes, self.side, W_out)
        return ColV(DType.STRING, data, v.validity, lengths)


@dataclass(frozen=True)
class StringLPad(_PadBase):
    """lpad(str, len, pad): literal len/pad (GpuStringLPad scalar operands)."""
    c: Expression
    length: Expression
    pad: Expression
    side = "left"


@dataclass(frozen=True)
class StringRPad(_PadBase):
    c: Expression
    length: Expression
    pad: Expression
    side = "right"


@dataclass(frozen=True)
class SubstringIndex(Expression):
    """substring_index(str, delim, count) with literal delim/count
    (GpuSubstringIndex scalar operands)."""
    c: Expression
    delim: Expression
    count: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        if not isinstance(self.count, Literal):
            raise TypeError("substring_index count must be a literal")
        delim = _literal_utf8(self.delim, "substring_index delimiter")
        if self.count.value is None or delim is None:
            return _all_null(xp, DType.STRING, ctx.capacity,
                             v.data.shape[-1])
        cnt = int(self.count.value)
        if len(delim) == 0 or cnt == 0:
            return ColV(DType.STRING, xp.zeros_like(v.data), v.validity,
                        xp.zeros(ctx.capacity, dtype=np.int32))
        data, lengths = sk.substring_index(xp, v.data, v.lengths, delim, cnt,
                                           v.data.shape[-1])
        return ColV(DType.STRING, data, v.validity, lengths)


@dataclass(frozen=True)
class RLike(_ConstPatternPredicate):
    """str RLIKE pattern (Java Pattern.find semantics: unanchored search).
    Device path: compiled DFA with a leading any-byte loop
    (stringFunctions.scala GpuRLike analog; byte-level '.', ASCII scope)."""
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        from spark_rapids_tpu.ops import regex as rk
        pat = self.pattern.decode("utf-8")
        if xp is np:
            import re as _re
            rx = _re.compile(pat)
            n = col.data.shape[0]
            res = np.zeros(n, dtype=bool)
            for i in range(n):
                s = bytes(col.data[i, :col.lengths[i]]).decode(
                    "utf-8", errors="replace")
                res[i] = rx.search(s) is not None
            return res
        # '^' anchors are rejected at tag time (Java's '^a|b' anchors only
        # the first branch — subtle semantics the DFA does not implement)
        dfa = rk.compile_dfa(pat, search=True)
        return rk.dfa_match(xp, dfa, col.data, col.lengths, search=True)


def _regex_spans(xp, pat: str, data, lengths, W: int):
    """Leftmost non-overlapping regex match spans: (sel, span_len)."""
    from spark_rapids_tpu.ops import regex as rk
    dfa = rk.compile_dfa(pat)
    if dfa.accept[dfa.start]:
        raise TypeError(f"pattern {pat!r} can match the empty string; "
                        f"zero-length matches are not supported on device")
    match_len = rk.dfa_find_spans(xp, dfa, data, lengths)
    sel = rk.regex_greedy_spans(xp, match_len, lengths, W)
    span_len = xp.where(sel, xp.maximum(match_len, 0), 0).astype(np.int32)
    return sel, span_len


@dataclass(frozen=True)
class RegExpReplace(Expression):
    """regexp_replace(str, pattern, replacement) with literal pattern and
    replacement (no group backreferences — the reference's GpuRegExpReplace
    has the same restriction). Leftmost non-overlapping matches, DFA-longest
    per start (POSIX-style; Java's backtracking-greedy agrees on the
    supported subset's common patterns)."""
    c: Expression
    pattern_e: Expression
    replacement: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        pat = _literal_utf8(self.pattern_e, "regexp pattern")
        repl = _literal_utf8(self.replacement, "regexp replacement")
        if pat is None or repl is None:
            return _all_null(xp, DType.STRING, ctx.capacity,
                             v.data.shape[-1])
        W = v.data.shape[-1]
        W_out = ctx.string_max_bytes
        if xp is np:
            import re as _re
            rx = _re.compile(pat.decode())
            n = v.data.shape[0]
            out = np.zeros((n, W_out), dtype=np.uint8)
            lens = np.zeros(n, dtype=np.int32)
            for i in range(n):
                s = bytes(v.data[i, :v.lengths[i]]).decode(
                    "utf-8", errors="replace")
                rb = rx.sub(repl.decode(), s).encode()[:W_out]
                out[i, :len(rb)] = bytearray(rb)
                lens[i] = len(rb)
            return ColV(DType.STRING, out, v.validity, lens)
        sel, span_len = _regex_spans(xp, pat.decode(), v.data, v.lengths, W)
        inside = sk.spans_inside(xp, sel, span_len, W)
        pos = np.arange(W, dtype=np.int32)[None, :]
        plain = xp.logical_and(
            pos < v.lengths[:, None],
            xp.logical_not(xp.logical_or(sel, inside))).astype(np.int32)
        data, lengths = sk.reassemble_spans(xp, v.data, sel, plain, repl,
                                            W_out)
        return ColV(DType.STRING, data, v.validity, lengths)


@dataclass(frozen=True)
class StringSplit(Expression):
    """split(str, regex): array-valued; only consumable through
    GetArrayItem (split(x, d)[i]) or size() on this engine — ARRAY is not a
    columnar type (same gate as CreateArray)."""
    c: Expression
    pattern_e: Expression
    limit: int = -1

    def dtype(self) -> DType:
        raise TypeError("split() produces an array; index it with [i] / "
                        "getItem(i) (ARRAY is not a columnar type here)")

    def element_type(self) -> DType:
        return DType.STRING


@dataclass(frozen=True)
class GetArrayItem(Expression):
    """array[i] with a literal ordinal (complexTypeExtractors.scala:88
    GpuGetArrayItem analog): supports CreateArray children (static pick) and
    StringSplit (fused split-part kernel — the array never materializes)."""
    child: Expression
    ordinal: int

    def dtype(self) -> DType:
        from spark_rapids_tpu.exprs.generators import CreateArray
        if isinstance(self.child, CreateArray):
            return self.child.element_type()
        if isinstance(self.child, StringSplit):
            return DType.STRING
        raise TypeError("GetArrayItem supports created arrays and split() "
                        "results only")

    def nullable(self) -> bool:
        return True

    def eval(self, ctx: EvalCtx) -> ColV:
        from spark_rapids_tpu.exprs.generators import CreateArray
        xp = ctx.xp
        if isinstance(self.child, CreateArray):
            items = self.child.items
            if not (0 <= self.ordinal < len(items)):
                return _all_null(xp, self.child.element_type(), ctx.capacity,
                                 ctx.string_max_bytes)
            return items[self.ordinal].eval(ctx)
        split: StringSplit = self.child
        v = _as_column(xp, split.c.eval(ctx), ctx.capacity)
        pat = _literal_utf8(split.pattern_e, "split pattern")
        if pat is None or self.ordinal < 0:
            return _all_null(xp, DType.STRING, ctx.capacity,
                             v.data.shape[-1])
        W = v.data.shape[-1]
        if xp is np:
            import re as _re
            # Java Pattern.split ignores capture groups; python interleaves
            # them — convert (x) to (?:x) for the reference path
            cpu_pat = _re.sub(r"(?<!\\)\((?!\?)", "(?:", pat.decode())
            rx = _re.compile(cpu_pat)
            n = v.data.shape[0]
            out = np.zeros((n, W), dtype=np.uint8)
            lens = np.zeros(n, dtype=np.int32)
            valid = np.asarray(v.validity).copy()
            for i in range(n):
                s = bytes(v.data[i, :v.lengths[i]]).decode(
                    "utf-8", errors="replace")
                parts = rx.split(s)
                if self.ordinal < len(parts):
                    b = parts[self.ordinal].encode()[:W]
                    out[i, :len(b)] = bytearray(b)
                    lens[i] = len(b)
                else:
                    valid[i] = False
            return ColV(DType.STRING, out, valid, lens)
        sel, span_len = _regex_spans(xp, pat.decode(), v.data, v.lengths, W)
        data, lengths, exists = sk.split_field(
            xp, v.data, v.lengths, sel, span_len, self.ordinal, W)
        return ColV(DType.STRING, data,
                    xp.logical_and(v.validity, exists), lengths)
