"""String function expressions (reference: stringFunctions.scala, 862 LoC).

All operate on the fixed-width byte-matrix layout via ops/strings kernels.
Upper/Lower are ASCII-only on device (non-ASCII bytes pass through unchanged);
full-unicode case mapping falls back to CPU, mirroring the reference's
incompat gating of cuDF's case ops.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression, UnaryExpression
from spark_rapids_tpu.exprs.literals import Literal
from spark_rapids_tpu.ops import strings as sk


def _as_column(xp, v: ColV, capacity: int) -> ColV:
    """Broadcast a scalar string ColV to a column of the given capacity."""
    if not v.is_scalar:
        return v
    W = v.data.shape[-1]
    data = xp.broadcast_to(v.data[None, :], (capacity, W))
    lengths = xp.broadcast_to(xp.reshape(v.lengths, (1,)), (capacity,))
    validity = xp.broadcast_to(xp.reshape(v.validity, (1,)), (capacity,))
    return ColV(DType.STRING, data, lengths=lengths, validity=validity)


@dataclass(frozen=True)
class Upper(UnaryExpression):
    c: Expression

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.STRING, sk.upper_ascii(ctx.xp, v.data), v.validity,
                    v.lengths, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Lower(UnaryExpression):
    c: Expression

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.STRING, sk.lower_ascii(ctx.xp, v.data), v.validity,
                    v.lengths, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Length(Expression):
    """Character (not byte) length, like Spark's length()."""
    c: Expression

    def dtype(self) -> DType:
        return DType.INT

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        data = sk.char_lengths(ctx.xp, v.data, v.lengths)
        return ColV(DType.INT, data, v.validity, is_scalar=v.is_scalar)


class _ConstPatternPredicate(Expression):
    """Base for StartsWith/EndsWith/Contains with a literal pattern (the reference
    also requires literal patterns for these — GpuOverrides string rules)."""

    def dtype(self) -> DType:
        return DType.BOOLEAN

    @property
    def pattern(self) -> bytes:
        lit = self.children[1]
        if not isinstance(lit, Literal) or lit.value is None:
            raise TypeError(f"{type(self).__name__} requires a non-null literal pattern")
        return str(lit.value).encode("utf-8")

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        col = _as_column(xp, v, ctx.capacity)
        W = col.data.shape[-1]
        data = self.do_match(xp, col, W)
        return ColV(DType.BOOLEAN, data, col.validity)

    def do_match(self, xp, col: ColV, W: int):
        raise NotImplementedError


@dataclass(frozen=True)
class StartsWith(_ConstPatternPredicate):
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        return sk.starts_with(xp, col.data, col.lengths, self.pattern, W)


@dataclass(frozen=True)
class EndsWith(_ConstPatternPredicate):
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        return sk.ends_with(xp, col.data, col.lengths, self.pattern, W)


@dataclass(frozen=True)
class Contains(_ConstPatternPredicate):
    c: Expression
    p: Expression

    def do_match(self, xp, col, W):
        return sk.contains(xp, col.data, col.lengths, self.pattern, W)


@dataclass(frozen=True)
class Like(_ConstPatternPredicate):
    r"""SQL LIKE with literal pattern. Device path supports patterns that reduce to
    anchored/substring matches: 'abc', 'abc%', '%abc', '%abc%' (no '_', no inner
    '%'); everything else is tagged for CPU fallback by the plan layer."""
    c: Expression
    p: Expression
    escape: str = "\\"

    @staticmethod
    def classify(pattern: str) -> Optional[Tuple[str, str]]:
        """Return (kind, needle) where kind in {exact, prefix, suffix, contains},
        or None if the pattern needs a real regex engine."""
        if "_" in pattern:
            return None
        body = pattern.strip("%")
        if "%" in body or "\\" in body:
            return None
        starts = pattern.startswith("%")
        ends = pattern.endswith("%")
        if starts and ends:
            return ("contains", body)
        if ends:
            return ("prefix", body)
        if starts:
            return ("suffix", body)
        return ("exact", body)

    @staticmethod
    def to_regex(pattern: str) -> str:
        """SQL LIKE pattern -> anchored python regex (escape char '\\')."""
        import re as _re
        out = []
        i = 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == "\\" and i + 1 < len(pattern):
                out.append(_re.escape(pattern[i + 1]))
                i += 2
                continue
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(_re.escape(ch))
            i += 1
        # \Z not $: $ would also match before a trailing newline
        return "^" + "".join(out) + r"\Z"

    def do_match(self, xp, col, W):
        pat = self.pattern.decode("utf-8")
        kind_needle = Like.classify(pat)
        if kind_needle is None:
            if xp is np:
                # eager CPU engine: full regex semantics (the fallback path the
                # plan layer routes unsupported patterns to)
                import re as _re
                rx = _re.compile(Like.to_regex(pat), _re.DOTALL)
                n = col.data.shape[0]
                res = np.zeros(n, dtype=bool)
                for i in range(n):
                    s = bytes(col.data[i, :col.lengths[i]]).decode(
                        "utf-8", errors="replace")
                    res[i] = rx.match(s) is not None
                return res
            raise NotImplementedError(f"LIKE pattern {pat!r} needs regex; CPU fallback")
        kind, needle = kind_needle
        nb = needle.encode("utf-8")
        if kind == "contains":
            return sk.contains(xp, col.data, col.lengths, nb, W)
        if kind == "prefix":
            return sk.starts_with(xp, col.data, col.lengths, nb, W)
        if kind == "suffix":
            return sk.ends_with(xp, col.data, col.lengths, nb, W)
        eq_len = col.lengths == len(nb)
        return xp.logical_and(
            sk.starts_with(xp, col.data, col.lengths, nb, W), eq_len)


@dataclass(frozen=True)
class Substring(Expression):
    """substring(str, pos, len) with Spark 1-based/negative-pos semantics, on
    *character* positions (byte positions only when the column is pure ASCII is
    not assumed: we compute byte offsets from char offsets vectorized)."""
    c: Expression
    pos: Expression
    length: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        pos = self.pos.eval(ctx)
        ln = self.length.eval(ctx)
        W = v.data.shape[-1]
        nchars = sk.char_lengths(xp, v.data, v.lengths)
        p = pos.data.astype(np.int32)
        l = xp.maximum(ln.data.astype(np.int32), 0)
        # Spark: pos 1-based; 0 behaves like 1; negative counts from the end.
        start_char = xp.where(p > 0, p - 1,
                              xp.where(p == 0, 0, xp.maximum(nchars + p, 0)))
        start_char = xp.minimum(start_char, nchars)
        end_char = xp.minimum(start_char + l, nchars)
        # char index -> byte offset: count non-continuation bytes cumulatively
        in_range = np.arange(W, dtype=np.int32)[None, :] < v.lengths[:, None]
        is_start = xp.logical_and((v.data & 0xC0) != 0x80, in_range)
        char_idx = xp.cumsum(is_start.astype(np.int32), axis=-1)  # 1-based char no.
        # byte offset of char k = first position where char_idx == k+1
        def char_to_byte(k):
            # number of bytes before char k = count of positions with char_idx <= k
            return xp.sum(xp.logical_and(in_range, char_idx <= k[:, None]),
                          axis=-1).astype(np.int32)
        start_b = char_to_byte(start_char)
        end_b = char_to_byte(end_char)
        data, lengths = sk.substring(xp, v.data, v.lengths, start_b,
                                     end_b - start_b, W)
        validity = xp.logical_and(v.validity,
                                  xp.logical_and(pos.validity, ln.validity))
        return ColV(DType.STRING, data, validity, lengths)


@dataclass(frozen=True)
class Concat(Expression):
    """concat(...): null if any input is null (Spark semantics)."""
    exprs: Tuple

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        vals = [_as_column(xp, e.eval(ctx), ctx.capacity) for e in self.exprs]
        out = vals[0]
        W = ctx.string_max_bytes
        for v in vals[1:]:
            data, lengths = sk.concat2(xp, out.data, out.lengths, v.data, v.lengths, W)
            validity = xp.logical_and(out.validity, v.validity)
            out = ColV(DType.STRING, data, validity, lengths)
        return out


@dataclass(frozen=True)
class StringTrim(Expression):
    """trim(str): strip ASCII spaces from both ends (Spark trims ' ' only)."""
    c: Expression

    def dtype(self) -> DType:
        return DType.STRING

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = _as_column(xp, self.c.eval(ctx), ctx.capacity)
        W = v.data.shape[-1]
        pos = np.arange(W, dtype=np.int32)[None, :]
        in_range = pos < v.lengths[:, None]
        non_space = xp.logical_and(v.data != 32, in_range)
        any_ns = xp.any(non_space, axis=-1)
        first = xp.argmax(non_space, axis=-1).astype(np.int32)
        last = (W - 1 - xp.argmax(non_space[:, ::-1], axis=-1)).astype(np.int32)
        start = xp.where(any_ns, first, 0)
        new_len = xp.where(any_ns, last - first + 1, 0)
        data, lengths = sk.substring(xp, v.data, v.lengths, start, new_len, W)
        return ColV(DType.STRING, data, v.validity, lengths)
