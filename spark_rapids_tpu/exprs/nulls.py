"""Null-handling expressions (reference: nullExpressions.scala, 297 LoC)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression, widen


@dataclass(frozen=True)
class IsNull(Expression):
    c: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        data = ctx.xp.logical_not(v.validity)
        return ColV(DType.BOOLEAN, data, ctx.xp.ones_like(data, dtype=bool),
                    is_scalar=v.is_scalar)


@dataclass(frozen=True)
class IsNotNull(Expression):
    c: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.BOOLEAN, v.validity,
                    ctx.xp.ones_like(v.validity, dtype=bool), is_scalar=v.is_scalar)


@dataclass(frozen=True)
class IsNan(Expression):
    """Spark: isnan(null) = false, never null."""
    c: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        data = xp.logical_and(xp.isnan(v.data), v.validity)
        return ColV(DType.BOOLEAN, data, xp.ones_like(data, dtype=bool),
                    is_scalar=v.is_scalar)


@dataclass(frozen=True)
class Coalesce(Expression):
    exprs: Tuple

    def dtype(self) -> DType:
        return DType.common_type_all([e.dtype() for e in self.exprs])

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        dt = self.dtype()
        out = None
        for e in self.exprs:
            v = widen(ctx, e.eval(ctx), dt)
            if out is None:
                out = v
                continue
            take_new = xp.logical_and(xp.logical_not(out.validity), v.validity)
            if dt is DType.STRING:
                from spark_rapids_tpu.ops.strings import (_bcast_rows,
                                                          align_widths)
                # a string LITERAL evals as one row: broadcast to the column
                vdat, vlen = _bcast_rows(xp, v.data, v.lengths, out.data)
                odat, olen = _bcast_rows(xp, out.data, out.lengths, vdat)
                v = ColV(dt, vdat, xp.broadcast_to(
                    xp.asarray(v.validity), take_new.shape), vlen)
                out = ColV(dt, odat, out.validity, olen)
                vd, od = align_widths(xp, v.data, out.data)
                tn = take_new[..., None] if hasattr(take_new, "ndim") and vd.ndim == 2 else take_new
                data = xp.where(tn, vd, od)
                lengths = xp.where(take_new, v.lengths, out.lengths)
                out = ColV(dt, data, xp.logical_or(out.validity, v.validity), lengths)
            else:
                data = xp.where(take_new, v.data, out.data)
                out = ColV(dt, data, xp.logical_or(out.validity, v.validity))
        return out


@dataclass(frozen=True)
class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN, else a. Null-intolerant per branch."""
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return DType.common_numeric(self.l.dtype(), self.r.dtype())

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        dt = self.dtype()
        a = self.l.eval(ctx)
        b = self.r.eval(ctx)
        ad = a.data.astype(dt.np_dtype())
        bd = b.data.astype(dt.np_dtype())
        use_b = xp.isnan(ad)
        data = xp.where(use_b, bd, ad)
        # null-intolerant on the left: a NULL left slot (whose garbage data may be
        # NaN) must stay NULL, never substitute b
        valid = xp.logical_and(a.validity,
                               xp.where(use_b, b.validity, True))
        return ColV(dt, data, valid, is_scalar=a.is_scalar and b.is_scalar)


@dataclass(frozen=True)
class AtLeastNNonNulls(Expression):
    """Used by dropna: true when >= n of the children are non-null (and non-NaN
    for floats), never null."""
    n: int
    exprs: Tuple

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        count = None
        for e in self.exprs:
            v = e.eval(ctx)
            ok = v.validity
            if v.dtype.is_floating:
                ok = xp.logical_and(ok, xp.logical_not(xp.isnan(v.data)))
            c = ok.astype(np.int32)
            count = c if count is None else count + c
        data = count >= self.n
        return ColV(DType.BOOLEAN, data, xp.ones_like(data, dtype=bool))
