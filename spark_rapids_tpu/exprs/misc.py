"""Named expressions, sort ordering, and id/random expressions.

Reference analogs: namedExpressions.scala (Alias), GpuSortExec's SortOrder,
GpuSparkPartitionID.scala:58, GpuMonotonicallyIncreasingID.scala:75,
GpuRandomExpressions.scala (Rand with per-batch seeded RNG),
NormalizeFloatingNumbers.scala / constraintExpressions.scala.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression, UnaryExpression


@dataclass(frozen=True)
class Alias(Expression):
    c: Expression
    name: str

    def dtype(self) -> DType:
        return self.c.dtype()

    def nullable(self) -> bool:
        return self.c.nullable()

    @property
    def name_hint(self) -> str:
        return self.name

    def eval(self, ctx: EvalCtx) -> ColV:
        return self.c.eval(ctx)

    def __str__(self) -> str:
        return f"{self.c} AS {self.name}"


@dataclass(frozen=True)
class SortOrder(Expression):
    """Sort key spec: ascending/descending + null ordering. Not row-evaluable as a
    value; consumed by sort/window/range-partition execs."""
    child: Expression
    ascending: bool = True
    nulls_first: bool = True

    @staticmethod
    def asc(e: Expression) -> "SortOrder":
        return SortOrder(e, True, True)

    @staticmethod
    def desc(e: Expression) -> "SortOrder":
        return SortOrder(e, False, False)

    def dtype(self) -> DType:
        return self.child.dtype()

    def eval(self, ctx: EvalCtx) -> ColV:
        return self.child.eval(ctx)


@dataclass(frozen=True)
class SparkPartitionID(Expression):
    """Partition ordinal, injected by the exec at runtime via ctx attribute."""

    def dtype(self) -> DType:
        return DType.INT

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        pid = getattr(ctx, "partition_id", 0)
        data = xp.full((ctx.capacity,), pid, dtype=np.int32)
        return ColV(DType.INT, data, xp.ones_like(data, dtype=bool))


@dataclass(frozen=True)
class MonotonicallyIncreasingID(Expression):
    """(partition_id << 33) + row offset within partition."""

    def dtype(self) -> DType:
        return DType.LONG

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        pid = getattr(ctx, "partition_id", 0)
        base = getattr(ctx, "row_offset", 0)
        # asarray, not np.int64(): pid may be a traced shard index under
        # mesh execution (mesh execs inject lax.axis_index as partition_id)
        data = ((xp.asarray(pid).astype(np.int64) << np.int64(33)) + base
                + xp.arange(ctx.capacity, dtype=np.int64))
        return ColV(DType.LONG, data, xp.ones_like(data, dtype=bool))


@dataclass(frozen=True)
class Rand(Expression):
    """rand(seed): per-batch threefry stream; XORSHIFT in the reference.

    Deterministic per (seed, partition, batch) like Spark's per-partition seeding,
    but uses jax's counter-based PRNG — the TPU-idiomatic way to get reproducible
    parallel streams.
    """
    seed: int = 0

    def dtype(self) -> DType:
        return DType.DOUBLE

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        pid = getattr(ctx, "partition_id", 0)
        batch_no = getattr(ctx, "batch_ordinal", 0)
        if ctx.is_tracing:
            import jax
            from spark_rapids_tpu import shims
            key = jax.random.fold_in(
                jax.random.fold_in(shims.get().prng_key(self.seed), pid),
                batch_no)
            data = jax.random.uniform(key, (ctx.capacity,), dtype=np.float64)
        else:
            rng = np.random.default_rng((self.seed, pid, batch_no))
            data = rng.random(ctx.capacity)
        return ColV(DType.DOUBLE, data, xp.ones((ctx.capacity,), dtype=bool))


@dataclass(frozen=True)
class KnownFloatingPointNormalized(UnaryExpression):
    c: Expression

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        return child.data


@dataclass(frozen=True)
class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize NaN bit patterns and -0.0 -> +0.0 (pre-grouping/join)."""
    c: Expression

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        xp = ctx.xp
        d = child.data
        d = xp.where(xp.isnan(d), xp.asarray(np.nan, dtype=d.dtype), d)
        return xp.where(d == 0, xp.asarray(0.0, dtype=d.dtype), d)


# ---------------------------------------------------------------- input file
#: hidden column names a file scan emits when the plan references input-file
#: metadata (GpuInputFileBlock.scala: InputFileName / InputFileBlockStart /
#: InputFileBlockLength riding the scan's per-file metadata)
INPUT_FILE_NAME_COL = "__input_file_name"
INPUT_FILE_START_COL = "__input_file_block_start"
INPUT_FILE_LENGTH_COL = "__input_file_block_length"

#: THE spec of the hidden trio — (name, dtype, non-scan default) — shared by
#: FileScan.schema(), the planner's union defaults, and the scan fill, so a
#: fourth column or dtype change is one edit
INPUT_FILE_META_SPEC = (
    (INPUT_FILE_NAME_COL, DType.STRING, ""),
    (INPUT_FILE_START_COL, DType.LONG, -1),
    (INPUT_FILE_LENGTH_COL, DType.LONG, -1),
)


@dataclass(frozen=True)
class _InputFileMeta(Expression):
    """Marker expression resolved at bind time to the scan's hidden metadata
    column. Rows not produced by a file scan get '' / -1 (Spark's defaults
    from InputFileBlockHolder)."""

    _col = ""

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        raise TypeError(
            f"{type(self).__name__} must be bound against a file scan "
            "(no file-scan source below this expression)")

    @property
    def name_hint(self) -> str:
        import re
        return re.sub(r"(?<!^)(?=[A-Z])", "_", type(self).__name__).lower()


@dataclass(frozen=True)
class InputFileName(_InputFileMeta):
    _col = INPUT_FILE_NAME_COL

    def dtype(self) -> DType:
        return DType.STRING


@dataclass(frozen=True)
class InputFileBlockStart(_InputFileMeta):
    _col = INPUT_FILE_START_COL

    def dtype(self) -> DType:
        return DType.LONG


@dataclass(frozen=True)
class InputFileBlockLength(_InputFileMeta):
    _col = INPUT_FILE_LENGTH_COL

    def dtype(self) -> DType:
        return DType.LONG
