"""Expression layer: ~80 Spark-compatible columnar expressions (growing toward the
reference's ~150), each evaluable eagerly on CPU (numpy) or traced into a fused
XLA program on TPU."""
from spark_rapids_tpu.exprs.core import (BoundReference, ColV, EvalCtx, Expression,
                                         UnresolvedAttribute, bind_expression)
from spark_rapids_tpu.exprs.literals import Literal
from spark_rapids_tpu.exprs.arithmetic import (Abs, Add, Divide, Greatest,
                                               IntegralDivide, Least, Multiply,
                                               Pmod, Remainder, Subtract, UnaryMinus,
                                               UnaryPositive)
from spark_rapids_tpu.exprs.predicates import (And, EqualNullSafe, EqualTo,
                                               GreaterThan, GreaterThanOrEqual, In,
                                               LessThan, LessThanOrEqual, Not,
                                               NotEqual, Or)
from spark_rapids_tpu.exprs.nulls import (AtLeastNNonNulls, Coalesce, IsNan, IsNotNull,
                                          IsNull, NaNvl)
from spark_rapids_tpu.exprs.conditional import CaseWhen, If
from spark_rapids_tpu.exprs.math import (Acos, Asin, Atan, Atan2, Cbrt, Ceil, Cos,
                                         Cosh, Exp, Expm1, Floor, Log, Log1p, Log2,
                                         Log10, Pow, Rint, Round, Signum, Sin, Sinh,
                                         Sqrt, Tan, Tanh, ToDegrees, ToRadians)
from spark_rapids_tpu.exprs.bitwise import (BitwiseAnd, BitwiseNot, BitwiseOr,
                                            BitwiseXor, ShiftLeft, ShiftRight,
                                            ShiftRightUnsigned)
from spark_rapids_tpu.exprs.cast import Cast, can_cast_on_device
from spark_rapids_tpu.exprs.strings import (Concat, Contains, EndsWith, InitCap,
                                            Length, Like, Lower, StartsWith,
                                            StringLocate, StringLPad,
                                            StringReplace, StringRPad,
                                            StringTrim, StringTrimLeft,
                                            StringTrimRight, Substring,
                                            SubstringIndex, Upper)
from spark_rapids_tpu.exprs.datetime import (DateAdd, DateDiff, DateSub, DayOfMonth,
                                             DayOfWeek, DayOfYear, Hour, LastDay,
                                             Minute, Month, Quarter, Second, Year)
from spark_rapids_tpu.exprs.aggregates import (AggregateFunction, Average, Corr,
                                               Count, CovarPop, CovarSamp,
                                               DistinctAgg, First, Last, Max,
                                               Min, StddevPop, StddevSamp, Sum,
                                               VariancePop, VarianceSamp)
from spark_rapids_tpu.exprs.misc import (Alias, KnownFloatingPointNormalized,
                                         MonotonicallyIncreasingID,
                                         NormalizeNaNAndZero, Rand, SortOrder,
                                         SparkPartitionID)
from spark_rapids_tpu.exprs.windows import (CumeDist, DenseRank, Lag, Lead, NTile,
                                            PercentRank, Rank, RowNumber,
                                            WindowExpression, WindowFrame)
