"""Window expressions (reference: GpuWindowExpression.scala, 723 LoC — window
frames/spec/rownumber; GpuWindowExec.scala).

A ``WindowExpression`` pairs a function (an AggregateFunction reused verbatim, or
a ranking WindowFunction) with its partition keys, order keys, and frame. The
window exec sorts once per (partition, order) spec and hands every expression a
shared FrameCtx (ops/window.py); aggregates reduce their buffers over per-row
frame intervals with the SAME BufferSpec kinds used by group-by aggregation, so
Sum/Count/Min/Max/Average/First/Last are windowed for free.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple, Union

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression
from spark_rapids_tpu.exprs.misc import SortOrder


@dataclass(frozen=True)
class WindowFrame:
    """Frame spec. ``lower``/``upper``: None = unbounded; ROWS: int row offset
    (negative = preceding); RANGE: numeric offset on the single order key, with
    0 = CURRENT ROW (peer-inclusive)."""
    frame_type: str = "range"  # "rows" | "range"
    lower: Optional[Union[int, float]] = None
    upper: Optional[Union[int, float]] = 0


class WindowFunction(Expression):
    """Ranking-style function computed from frame/peer/partition positions."""

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        raise NotImplementedError(type(self).__name__)

    def eval(self, ctx: EvalCtx) -> ColV:
        raise TypeError(f"{type(self).__name__} must be evaluated by a window exec")


@dataclass(frozen=True)
class WindowExpression(Expression):
    """function OVER (PARTITION BY part_keys ORDER BY orders frame)."""
    fn: Expression  # AggregateFunction or WindowFunction
    part_keys: Tuple[Expression, ...] = ()
    orders: Tuple[SortOrder, ...] = ()
    frame: Optional[WindowFrame] = None

    def __post_init__(self):
        if isinstance(self.fn, WindowFunction) and not self.orders:
            # Spark's analyzer error for rank/lead/lag/... without ORDER BY;
            # silent degenerate results (rank()==1 everywhere) are worse
            raise ValueError(
                f"window function {type(self.fn).__name__} requires the "
                f"window to be ordered (add orderBy to the window spec)")

    def resolved_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        if self.orders:
            # SQL default with ORDER BY: RANGE UNBOUNDED PRECEDING..CURRENT ROW
            return WindowFrame("range", None, 0)
        return WindowFrame("rows", None, None)

    def dtype(self) -> DType:
        return self.fn.dtype()

    def nullable(self) -> bool:
        return self.fn.nullable()

    @property
    def name_hint(self) -> str:
        return self.fn.name_hint

    def eval(self, ctx: EvalCtx) -> ColV:
        raise TypeError("WindowExpression must be evaluated by a window exec")

    def sort_spec_key(self):
        """Window expressions sharing this key can share one sort + FrameCtx."""
        return (self.part_keys, self.orders)


# ------------------------------------------------------------------ ranking fns
@dataclass(frozen=True)
class RowNumber(WindowFunction):
    def dtype(self) -> DType:
        return DType.INT

    def nullable(self) -> bool:
        return False

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        data = (fr.idx - fr.seg_first + 1).astype(np.int32)
        return ColV(DType.INT, data, fr.salive)


@dataclass(frozen=True)
class Rank(WindowFunction):
    def dtype(self) -> DType:
        return DType.INT

    def nullable(self) -> bool:
        return False

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        data = (fr.peer_first - fr.seg_first + 1).astype(np.int32)
        return ColV(DType.INT, data, fr.salive)


@dataclass(frozen=True)
class DenseRank(WindowFunction):
    def dtype(self) -> DType:
        return DType.INT

    def nullable(self) -> bool:
        return False

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        xp = ctx.xp
        # count of peer-group starts in (seg_first, idx]
        starts = (fr.peer_first == fr.idx).astype(np.int64)
        c = xp.cumsum(starts)
        data = (c - c[xp.clip(fr.seg_first, 0, fr.capacity - 1)] + 1)
        return ColV(DType.INT, data.astype(np.int32), fr.salive)


@dataclass(frozen=True)
class PercentRank(WindowFunction):
    def dtype(self) -> DType:
        return DType.DOUBLE

    def nullable(self) -> bool:
        return False

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        xp = ctx.xp
        rank = (fr.peer_first - fr.seg_first).astype(np.float64)
        denom = xp.maximum(fr.seg_size - 1, 1).astype(np.float64)
        data = xp.where(fr.seg_size > 1, rank / denom, np.float64(0.0))
        return ColV(DType.DOUBLE, data, fr.salive)


@dataclass(frozen=True)
class CumeDist(WindowFunction):
    def dtype(self) -> DType:
        return DType.DOUBLE

    def nullable(self) -> bool:
        return False

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        xp = ctx.xp
        n = (fr.peer_last - fr.seg_first + 1).astype(np.float64)
        denom = xp.maximum(fr.seg_size, 1).astype(np.float64)
        return ColV(DType.DOUBLE, n / denom, fr.salive)


@dataclass(frozen=True)
class NTile(WindowFunction):
    n: int = 1

    def __post_init__(self):
        if self.n < 1:
            raise ValueError(f"ntile() parameter n must be positive, got {self.n}")

    def dtype(self) -> DType:
        return DType.INT

    def nullable(self) -> bool:
        return False

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        xp = ctx.xp
        # Spark NTile: first (rows % n) buckets get (rows/n + 1) rows each
        i0 = fr.idx - fr.seg_first
        rows = xp.maximum(fr.seg_size, 1)
        n = np.int64(self.n)
        base = rows // n
        rem = rows % n
        big = rem * (base + 1)
        in_big = i0 < big
        bucket_big = i0 // xp.maximum(base + 1, 1)
        bucket_small = rem + (i0 - big) // xp.maximum(base, 1)
        data = xp.where(in_big, bucket_big, bucket_small) + 1
        return ColV(DType.INT, data.astype(np.int32), fr.salive)


class _LeadLag(WindowFunction):
    sign = 0

    def dtype(self) -> DType:
        return self.c.dtype()

    def window_eval(self, ctx: EvalCtx, fr) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)  # ctx columns are already in sorted order
        j = fr.idx + self.sign * int(self.offset)
        in_part = xp.logical_and(j >= fr.seg_first, j <= fr.seg_last)
        jc = xp.clip(j, 0, fr.capacity - 1)
        from spark_rapids_tpu.exprs.literals import Literal
        default = self.default if self.default is not None else Literal(
            None, DType.NULL)
        d = default.eval(ctx)
        from spark_rapids_tpu.exprs.core import widen
        d = widen(ctx, d, v.dtype)
        data = xp.where(in_part[..., None] if v.dtype is DType.STRING
                        else in_part, v.data[jc], d.data)
        valid = xp.where(in_part, v.validity[jc], d.validity)
        valid = xp.logical_and(valid, fr.salive)
        if v.dtype is DType.STRING:
            lengths = xp.where(in_part, v.lengths[jc], d.lengths)
            return ColV(v.dtype, data, valid, lengths)
        return ColV(v.dtype, data, valid)


@dataclass(frozen=True)
class Lead(_LeadLag):
    c: Expression = None  # type: ignore[assignment]
    offset: int = 1
    default: Optional[Expression] = None
    sign = 1


@dataclass(frozen=True)
class Lag(_LeadLag):
    c: Expression = None  # type: ignore[assignment]
    offset: int = 1
    default: Optional[Expression] = None
    sign = -1
