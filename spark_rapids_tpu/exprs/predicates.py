"""Predicate expressions (reference: predicates.scala, 631 LoC).

Spark semantics: comparisons are null-intolerant; AND/OR use Kleene three-valued
logic (false AND null = false, true OR null = true). Spark's documented float
semantics (see Spark SQL "NaN Semantics"): NaN = NaN returns true, and NaN sorts
greater than every other value — so float comparisons here special-case NaN
rather than using raw IEEE compares.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import (BinaryExpression, ColV, EvalCtx, Expression,
                                         and_validity, cast_operands)
from spark_rapids_tpu.ops import strings as sk


def _is_float(v: ColV) -> bool:
    return v.dtype.is_floating


class _Comparison(BinaryExpression):
    op: str = ""

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        xp = ctx.xp
        if l.dtype is DType.STRING:
            return sk.string_compare(xp, self.op, l.data, l.lengths, r.data, r.lengths)
        a, b = l.data, r.data
        if _is_float(l):
            return _float_compare(xp, self.op, a, b)
        return {"eq": lambda: a == b, "ne": lambda: a != b,
                "lt": lambda: a < b, "le": lambda: a <= b,
                "gt": lambda: a > b, "ge": lambda: a >= b}[self.op]()


def _float_compare(xp, op, a, b):
    """Spark double ordering: NaN == NaN true; NaN greater than everything."""
    an, bn = xp.isnan(a), xp.isnan(b)
    both_nan = xp.logical_and(an, bn)
    if op == "eq":
        return xp.logical_or(both_nan, a == b)
    if op == "ne":
        return xp.logical_not(xp.logical_or(both_nan, a == b))
    if op == "lt":
        return xp.logical_or(xp.logical_and(xp.logical_not(an), bn), a < b)
    if op == "le":
        return xp.logical_or(bn, a <= b)
    if op == "gt":
        return xp.logical_or(xp.logical_and(an, xp.logical_not(bn)), a > b)
    if op == "ge":
        return xp.logical_or(an, a >= b)
    raise ValueError(op)


@dataclass(frozen=True)
class EqualTo(_Comparison):
    l: Expression
    r: Expression
    op = "eq"


@dataclass(frozen=True)
class NotEqual(_Comparison):
    l: Expression
    r: Expression
    op = "ne"


@dataclass(frozen=True)
class LessThan(_Comparison):
    l: Expression
    r: Expression
    op = "lt"


@dataclass(frozen=True)
class LessThanOrEqual(_Comparison):
    l: Expression
    r: Expression
    op = "le"


@dataclass(frozen=True)
class GreaterThan(_Comparison):
    l: Expression
    r: Expression
    op = "gt"


@dataclass(frozen=True)
class GreaterThanOrEqual(_Comparison):
    l: Expression
    r: Expression
    op = "ge"


@dataclass(frozen=True)
class EqualNullSafe(BinaryExpression):
    """<=> : nulls compare equal; never returns null."""
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def nullable(self) -> bool:
        return False

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        l, r = cast_operands(ctx, l, r, self.operand_dtype())
        if l.dtype is DType.STRING:
            eq = sk.string_eq(xp, l.data, l.lengths, r.data, r.lengths)
        elif _is_float(l):
            eq = _float_compare(xp, "eq", l.data, r.data)
        else:
            eq = l.data == r.data
        both_null = xp.logical_and(xp.logical_not(l.validity),
                                   xp.logical_not(r.validity))
        both_valid = xp.logical_and(l.validity, r.validity)
        data = xp.logical_or(both_null, xp.logical_and(both_valid, eq))
        valid = xp.ones_like(data, dtype=bool) if hasattr(data, "shape") else True
        return ColV(DType.BOOLEAN, data, valid,
                    is_scalar=l.is_scalar and r.is_scalar)


@dataclass(frozen=True)
class Not(Expression):
    c: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def eval(self, ctx: EvalCtx) -> ColV:
        v = self.c.eval(ctx)
        return ColV(DType.BOOLEAN, ctx.xp.logical_not(v.data), v.validity,
                    is_scalar=v.is_scalar)


@dataclass(frozen=True)
class And(Expression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        l = self.l.eval(ctx)
        r = self.r.eval(ctx)
        res_false = xp.logical_or(
            xp.logical_and(l.validity, xp.logical_not(l.data)),
            xp.logical_and(r.validity, xp.logical_not(r.data)))
        valid = xp.logical_or(xp.logical_and(l.validity, r.validity), res_false)
        data = xp.logical_and(xp.logical_and(l.data, r.data),
                              xp.logical_not(res_false))
        return ColV(DType.BOOLEAN, data, valid,
                    is_scalar=l.is_scalar and r.is_scalar)


@dataclass(frozen=True)
class Or(Expression):
    l: Expression
    r: Expression

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        l = self.l.eval(ctx)
        r = self.r.eval(ctx)
        res_true = xp.logical_or(xp.logical_and(l.validity, l.data),
                                 xp.logical_and(r.validity, r.data))
        valid = xp.logical_or(xp.logical_and(l.validity, r.validity), res_true)
        data = xp.logical_or(l.data, r.data)
        return ColV(DType.BOOLEAN, data, valid,
                    is_scalar=l.is_scalar and r.is_scalar)


@dataclass(frozen=True)
class In(Expression):
    """value IN (literals...) — reference: GpuInSet.scala:98.

    Spark: true if match; null if no match and (value is null or list has null);
    false otherwise.
    """
    value: Expression
    items: Tuple  # of Literal

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.value.eval(ctx)
        found = None
        has_null_item = False
        for lit in self.items:
            lv = lit.eval(ctx)
            if lit.value is None:
                has_null_item = True
                continue
            lv_cast, v_cast = lv, v
            if v.dtype != lv.dtype and v.dtype.is_numeric and lv.dtype.is_numeric:
                common = DType.common_numeric(v.dtype, lv.dtype)
                v_cast = ColV(common, v.data.astype(common.np_dtype()), v.validity)
                lv_cast = ColV(common, lv.data.astype(common.np_dtype()), lv.validity)
            if v.dtype is DType.STRING:
                eq = sk.string_eq(xp, v_cast.data, v.lengths, lv.data, lv.lengths)
            elif v.dtype.is_floating:
                eq = _float_compare(xp, "eq", v_cast.data, lv_cast.data)
            else:
                eq = v_cast.data == lv_cast.data
            found = eq if found is None else xp.logical_or(found, eq)
        if found is None:
            found = xp.zeros_like(v.validity, dtype=bool)
        valid = xp.logical_and(v.validity,
                               xp.logical_or(found, not has_null_item))
        return ColV(DType.BOOLEAN, found, valid, is_scalar=v.is_scalar)


@dataclass(frozen=True)
class InSet(Expression):
    """value IN (large literal set) — GpuInSet.scala:98 analog. Where In
    evaluates one equality per item (fused but O(items) passes), InSet does
    ONE searchsorted membership probe against the sorted set — the device
    cost is O(log n) per row however large the list. Numeric/date values
    only (strings keep the per-item path via In)."""
    value: Expression
    values: Tuple  # python scalars, no nulls
    has_null: bool = False

    def dtype(self) -> DType:
        return DType.BOOLEAN

    def eval(self, ctx: EvalCtx) -> ColV:
        import numpy as _np
        xp = ctx.xp
        v = self.value.eval(ctx)
        # compare in the WIDER domain: a double column probed against an
        # int set must not truncate 3.7 -> 3, and float literals probed
        # against an int column must not truncate either (Spark widens)
        any_float_lit = any(isinstance(x, float) for x in self.values)
        cmp_dtype = (_np.float64 if (v.dtype.is_floating or any_float_lit)
                     else v.dtype.np_dtype())
        arr = _np.sort(_np.asarray(list(self.values)).astype(cmp_dtype))
        table = xp.asarray(arr)
        d = v.data.astype(cmp_dtype)
        idx = xp.searchsorted(table, d)
        idx_c = xp.clip(idx, 0, len(arr) - 1)
        found = xp.logical_and(idx < len(arr), table[idx_c] == d)
        # Spark 3VL: null when no match and (value null or set has null)
        validity = v.validity if not self.has_null else \
            xp.logical_and(v.validity, found)
        return ColV(DType.BOOLEAN, found, validity, is_scalar=v.is_scalar)
