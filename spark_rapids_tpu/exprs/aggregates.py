"""Aggregate functions (reference: AggregateFunctions.scala, 502 LoC —
Count/Max/Min/Sum/Average/First/Last with cudf aggs).

TPU design: an aggregate declares *buffer specs* — (projection of the input row,
reduction kind) pairs. The hash-aggregate exec evaluates the projections, then
applies the reduction per group via jax segment ops; the SAME reduction kind merges
partial buffers across batches/partitions (Spark's update/merge symmetry), so
Partial/PartialMerge/Final modes and distributed tree-reduction all reuse one
kernel path.

Reduction kinds: sum, min, max, first, last. Null handling: inputs are projected to
(neutral value, 0/1 valid flag); a group's result is null iff no valid input
reached it (Spark ignores nulls in aggs; count never returns null).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression


@dataclass(frozen=True)
class BufferSpec:
    """One aggregation buffer: projected dtype + reduction kind.

    ``ignore_nulls`` only matters for first/last: when True the reduction picks the
    first/last *valid* row of the group; when False it picks the first/last row
    outright (which may be null)."""
    dtype: DType
    kind: str  # sum | min | max | first | last
    ignore_nulls: bool = False


class AggregateFunction(Expression):
    """Base for declarative aggregate functions. Not row-evaluable."""

    @property
    def child(self) -> Expression:
        return self.children[0] if self.children else None

    def eval(self, ctx: EvalCtx) -> ColV:
        raise TypeError(f"{type(self).__name__} must be evaluated by an aggregate exec")

    def buffer_specs(self) -> List[BufferSpec]:
        raise NotImplementedError

    def project(self, ctx: EvalCtx) -> List[ColV]:
        """Input row -> per-buffer update values (pre-reduction)."""
        raise NotImplementedError

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        """Reduced buffers -> final result column."""
        raise NotImplementedError


def _sum_dtype(dt: DType) -> DType:
    if dt.is_floating:
        return DType.DOUBLE
    if dt.is_integral:
        return DType.LONG
    raise TypeError(f"sum of {dt}")


@dataclass(frozen=True)
class Sum(AggregateFunction):
    c: Expression

    def dtype(self) -> DType:
        return _sum_dtype(self.c.dtype())

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(self.dtype(), "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        dt = self.dtype()
        data = ctx.xp.where(v.validity, v.data, 0).astype(dt.np_dtype())
        return [ColV(dt, data, v.validity)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        return buffers[0]


@dataclass(frozen=True)
class Count(AggregateFunction):
    """count(expr) — non-null count; count(1)/count(*) via Literal child."""
    c: Expression

    def dtype(self) -> DType:
        return DType.LONG

    def nullable(self) -> bool:
        return False

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(DType.LONG, "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        xp = ctx.xp
        ones = v.validity.astype(np.int64)
        if v.is_scalar:
            ones = xp.broadcast_to(ones, (ctx.capacity,))
        return [ColV(DType.LONG, ones, xp.ones_like(ones, dtype=bool))]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        b = buffers[0]
        # count is 0, not null, for all-null groups
        return ColV(DType.LONG, b.data, xp.ones_like(b.validity, dtype=bool))


class _MinMax(AggregateFunction):
    kind = ""

    def dtype(self) -> DType:
        return self.c.dtype()

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(self.dtype(), self.kind)]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        if v.dtype is DType.STRING:
            # strings reduce via rank-based segment pick; no neutral substitution
            return [v]
        xp = ctx.xp
        neutral = _reduce_neutral(self.kind, v.dtype)
        data = xp.where(v.validity, v.data, neutral)
        return [ColV(v.dtype, data, v.validity)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        return buffers[0]


@dataclass(frozen=True)
class Min(_MinMax):
    c: Expression
    kind = "min"


@dataclass(frozen=True)
class Max(_MinMax):
    c: Expression
    kind = "max"


@dataclass(frozen=True)
class Average(AggregateFunction):
    c: Expression

    def dtype(self) -> DType:
        return DType.DOUBLE

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(DType.DOUBLE, "sum"), BufferSpec(DType.LONG, "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        xp = ctx.xp
        s = xp.where(v.validity, v.data, 0).astype(np.float64)
        n = v.validity.astype(np.int64)
        ones = xp.ones_like(n, dtype=bool)
        return [ColV(DType.DOUBLE, s, v.validity), ColV(DType.LONG, n, ones)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        s, n = buffers
        cnt = n.data
        safe = xp.where(cnt == 0, 1, cnt)
        data = s.data / safe
        valid = cnt > 0
        return ColV(DType.DOUBLE, data, valid)


class _FirstLast(AggregateFunction):
    kind = ""

    def dtype(self) -> DType:
        return self.c.dtype()

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(self.dtype(), self.kind, self.ignore_nulls)]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        return [self.c.eval(ctx)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        return buffers[0]


@dataclass(frozen=True)
class First(_FirstLast):
    c: Expression
    ignore_nulls: bool = False
    kind = "first"


@dataclass(frozen=True)
class Last(_FirstLast):
    c: Expression
    ignore_nulls: bool = False
    kind = "last"


def _reduce_neutral(kind: str, dt: DType):
    """Neutral element substituted for null inputs before reduction."""
    npdt = dt.np_dtype()
    if kind == "sum":
        return np.asarray(0, dtype=npdt)
    if kind == "min":
        if dt.is_floating:
            return np.asarray(np.inf, dtype=npdt)
        if dt is DType.BOOLEAN:
            return True
        return np.iinfo(npdt).max
    if kind == "max":
        if dt.is_floating:
            return np.asarray(-np.inf, dtype=npdt)
        if dt is DType.BOOLEAN:
            return False
        return np.iinfo(npdt).min
    raise ValueError(kind)
