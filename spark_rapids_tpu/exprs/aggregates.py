"""Aggregate functions (reference: AggregateFunctions.scala, 502 LoC —
Count/Max/Min/Sum/Average/First/Last with cudf aggs).

TPU design: an aggregate declares *buffer specs* — (projection of the input row,
reduction kind) pairs. The hash-aggregate exec evaluates the projections, then
applies the reduction per group via jax segment ops; the SAME reduction kind merges
partial buffers across batches/partitions (Spark's update/merge symmetry), so
Partial/PartialMerge/Final modes and distributed tree-reduction all reuse one
kernel path.

Reduction kinds: sum, min, max, first, last. Null handling: inputs are projected to
(neutral value, 0/1 valid flag); a group's result is null iff no valid input
reached it (Spark ignores nulls in aggs; count never returns null).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression


@dataclass(frozen=True)
class BufferSpec:
    """One aggregation buffer: projected dtype + reduction kind.

    ``ignore_nulls`` only matters for first/last: when True the reduction picks the
    first/last *valid* row of the group; when False it picks the first/last row
    outright (which may be null)."""
    dtype: DType
    kind: str  # sum | min | max | first | last
    ignore_nulls: bool = False


class AggregateFunction(Expression):
    """Base for declarative aggregate functions. Not row-evaluable."""

    @property
    def child(self) -> Expression:
        return self.children[0] if self.children else None

    def eval(self, ctx: EvalCtx) -> ColV:
        raise TypeError(f"{type(self).__name__} must be evaluated by an aggregate exec")

    def buffer_specs(self) -> List[BufferSpec]:
        raise NotImplementedError

    def project(self, ctx: EvalCtx) -> List[ColV]:
        """Input row -> per-buffer update values (pre-reduction)."""
        raise NotImplementedError

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        """Reduced buffers -> final result column."""
        raise NotImplementedError


def _sum_dtype(dt: DType) -> DType:
    if dt.is_floating:
        return DType.DOUBLE
    if dt.is_integral:
        return DType.LONG
    raise TypeError(f"sum of {dt}")


@dataclass(frozen=True)
class Sum(AggregateFunction):
    c: Expression

    def dtype(self) -> DType:
        return _sum_dtype(self.c.dtype())

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(self.dtype(), "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        dt = self.dtype()
        data = ctx.xp.where(v.validity, v.data, 0).astype(dt.np_dtype())
        return [ColV(dt, data, v.validity)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        return buffers[0]


@dataclass(frozen=True)
class Count(AggregateFunction):
    """count(expr) — non-null count; count(1)/count(*) via Literal child."""
    c: Expression

    def dtype(self) -> DType:
        return DType.LONG

    def nullable(self) -> bool:
        return False

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(DType.LONG, "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        xp = ctx.xp
        ones = v.validity.astype(np.int64)
        if v.is_scalar:
            ones = xp.broadcast_to(ones, (ctx.capacity,))
        return [ColV(DType.LONG, ones, xp.ones_like(ones, dtype=bool))]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        b = buffers[0]
        # count is 0, not null, for all-null groups
        return ColV(DType.LONG, b.data, xp.ones_like(b.validity, dtype=bool))


class _MinMax(AggregateFunction):
    kind = ""

    def dtype(self) -> DType:
        return self.c.dtype()

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(self.dtype(), self.kind)]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        if v.dtype is DType.STRING:
            # strings reduce via rank-based segment pick; no neutral substitution
            return [v]
        xp = ctx.xp
        neutral = _reduce_neutral(self.kind, v.dtype)
        data = xp.where(v.validity, v.data, neutral)
        return [ColV(v.dtype, data, v.validity)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        return buffers[0]


@dataclass(frozen=True)
class Min(_MinMax):
    c: Expression
    kind = "min"


@dataclass(frozen=True)
class Max(_MinMax):
    c: Expression
    kind = "max"


@dataclass(frozen=True)
class Average(AggregateFunction):
    c: Expression

    def dtype(self) -> DType:
        return DType.DOUBLE

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(DType.DOUBLE, "sum"), BufferSpec(DType.LONG, "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        xp = ctx.xp
        s = xp.where(v.validity, v.data, 0).astype(np.float64)
        n = v.validity.astype(np.int64)
        ones = xp.ones_like(n, dtype=bool)
        return [ColV(DType.DOUBLE, s, v.validity), ColV(DType.LONG, n, ones)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        s, n = buffers
        cnt = n.data
        safe = xp.where(cnt == 0, 1, cnt)
        data = s.data / safe
        valid = cnt > 0
        return ColV(DType.DOUBLE, data, valid)


class _FirstLast(AggregateFunction):
    kind = ""

    def dtype(self) -> DType:
        return self.c.dtype()

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(self.dtype(), self.kind, self.ignore_nulls)]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        return [self.c.eval(ctx)]

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        return buffers[0]


@dataclass(frozen=True)
class First(_FirstLast):
    c: Expression
    ignore_nulls: bool = False
    kind = "first"


@dataclass(frozen=True)
class Last(_FirstLast):
    c: Expression
    ignore_nulls: bool = False
    kind = "last"


class _MomentBase(AggregateFunction):
    """Shared machinery for variance/stddev: buffers (count, sum, sum-of-squares),
    all sum-mergeable so distributed partial/final merge reuses the sum kernel
    (Spark computes these with a mutable central-moment buffer; a sum-of-powers
    decomposition is the order-independent equivalent that XLA segment ops want)."""

    def dtype(self) -> DType:
        return DType.DOUBLE

    def buffer_specs(self) -> List[BufferSpec]:
        return [BufferSpec(DType.LONG, "sum"), BufferSpec(DType.DOUBLE, "sum"),
                BufferSpec(DType.DOUBLE, "sum")]

    def project(self, ctx: EvalCtx) -> List[ColV]:
        v = self.c.eval(ctx)
        xp = ctx.xp
        x = xp.where(v.validity, v.data, 0).astype(np.float64)
        valid = v.validity
        if v.is_scalar:
            x = xp.broadcast_to(x, (ctx.capacity,))
            valid = xp.broadcast_to(valid, (ctx.capacity,))
        n = valid.astype(np.int64)
        ones = xp.ones_like(n, dtype=bool)
        return [ColV(DType.LONG, n, ones), ColV(DType.DOUBLE, x, valid),
                ColV(DType.DOUBLE, x * x, valid)]

    def _moments(self, xp, buffers):
        n = buffers[0].data.astype(np.float64)
        s, ss = buffers[1].data, buffers[2].data
        safe_n = xp.where(n == 0, 1.0, n)
        # max() guards the tiny negative residue of catastrophic cancellation
        m2 = xp.maximum(ss - s * s / safe_n, 0.0)
        return n, m2


@dataclass(frozen=True)
class VarianceSamp(_MomentBase):
    c: Expression

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n, m2 = self._moments(xp, buffers)
        data = m2 / xp.where(n < 2, 1.0, n - 1.0)
        return ColV(DType.DOUBLE, data, n >= 2)


@dataclass(frozen=True)
class VariancePop(_MomentBase):
    c: Expression

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n, m2 = self._moments(xp, buffers)
        data = m2 / xp.where(n == 0, 1.0, n)
        return ColV(DType.DOUBLE, data, n >= 1)


@dataclass(frozen=True)
class StddevSamp(_MomentBase):
    c: Expression

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n, m2 = self._moments(xp, buffers)
        data = xp.sqrt(m2 / xp.where(n < 2, 1.0, n - 1.0))
        return ColV(DType.DOUBLE, data, n >= 2)


@dataclass(frozen=True)
class StddevPop(_MomentBase):
    c: Expression

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n, m2 = self._moments(xp, buffers)
        data = xp.sqrt(m2 / xp.where(n == 0, 1.0, n))
        return ColV(DType.DOUBLE, data, n >= 1)


class _BivariateBase(AggregateFunction):
    """corr/covar buffers: (n, Σx, Σy, Σxy[, Σx², Σy²]); a row participates
    only when BOTH sides are non-null (Spark's pairwise-deletion semantics)."""
    with_squares = False

    @property
    def x(self) -> Expression:
        return self.children[0]

    @property
    def y(self) -> Expression:
        return self.children[1]

    def dtype(self) -> DType:
        return DType.DOUBLE

    def buffer_specs(self) -> List[BufferSpec]:
        n = 6 if self.with_squares else 4
        return ([BufferSpec(DType.LONG, "sum")]
                + [BufferSpec(DType.DOUBLE, "sum")] * (n - 1))

    def project(self, ctx: EvalCtx) -> List[ColV]:
        xv = self.x.eval(ctx)
        yv = self.y.eval(ctx)
        xp = ctx.xp
        both = xp.logical_and(xv.validity, yv.validity)
        x = xp.where(both, xv.data, 0).astype(np.float64)
        y = xp.where(both, yv.data, 0).astype(np.float64)
        if xv.is_scalar or yv.is_scalar:
            x = xp.broadcast_to(x, (ctx.capacity,))
            y = xp.broadcast_to(y, (ctx.capacity,))
            both = xp.broadcast_to(both, (ctx.capacity,))
        n = both.astype(np.int64)
        ones = xp.ones_like(n, dtype=bool)
        cols = [ColV(DType.LONG, n, ones), ColV(DType.DOUBLE, x, both),
                ColV(DType.DOUBLE, y, both), ColV(DType.DOUBLE, x * y, both)]
        if self.with_squares:
            cols += [ColV(DType.DOUBLE, x * x, both),
                     ColV(DType.DOUBLE, y * y, both)]
        return cols


@dataclass(frozen=True)
class Corr(_BivariateBase):
    cx: Expression
    cy: Expression
    with_squares = True

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n = buffers[0].data.astype(np.float64)
        sx, sy, sxy, sxx, syy = (b.data for b in buffers[1:])
        safe_n = xp.where(n == 0, 1.0, n)
        cov = sxy - sx * sy / safe_n
        vx = xp.maximum(sxx - sx * sx / safe_n, 0.0)
        vy = xp.maximum(syy - sy * sy / safe_n, 0.0)
        denom = xp.sqrt(vx * vy)
        data = cov / xp.where(denom == 0, 1.0, denom)
        # Spark: corr is null for n<2 or zero variance (NaN actually) — match
        # the null-on-degenerate convention used across this engine
        valid = xp.logical_and(n >= 2, denom > 0)
        return ColV(DType.DOUBLE, data, valid)


class _CovarBase(_BivariateBase):
    def _cov(self, xp, buffers):
        n = buffers[0].data.astype(np.float64)
        sx, sy, sxy = (b.data for b in buffers[1:4])
        safe_n = xp.where(n == 0, 1.0, n)
        return n, sxy - sx * sy / safe_n


@dataclass(frozen=True)
class CovarSamp(_CovarBase):
    cx: Expression
    cy: Expression

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n, cov = self._cov(xp, buffers)
        data = cov / xp.where(n < 2, 1.0, n - 1.0)
        return ColV(DType.DOUBLE, data, n >= 2)


@dataclass(frozen=True)
class CovarPop(_CovarBase):
    cx: Expression
    cy: Expression

    def evaluate(self, xp, buffers: List[ColV]) -> ColV:
        n, cov = self._cov(xp, buffers)
        data = cov / xp.where(n == 0, 1.0, n)
        return ColV(DType.DOUBLE, data, n >= 1)


@dataclass(frozen=True)
class DistinctAgg(AggregateFunction):
    """Marker wrapping an aggregate over DISTINCT values of its child.

    Never executed directly: GroupedData.agg rewrites any aggregation that
    contains one into dedup-then-aggregate subplans joined on the grouping keys
    (the join-based form of Spark's RewriteDistinctAggregates; the reference GPU
    plugin does not accelerate distinct aggregates at all in v0 — this engine
    runs them through the same two-phase group-by kernels as everything else)."""
    inner: AggregateFunction

    def dtype(self) -> DType:
        return self.inner.dtype()

    def nullable(self) -> bool:
        return self.inner.nullable()

    @property
    def name_hint(self) -> str:
        return f"{self.inner.name_hint}_distinct"


def _reduce_neutral(kind: str, dt: DType):
    """Neutral element substituted for null inputs before reduction."""
    npdt = dt.np_dtype()
    if kind == "sum":
        return np.asarray(0, dtype=npdt)
    if kind == "min":
        if dt.is_floating:
            return np.asarray(np.inf, dtype=npdt)
        if dt is DType.BOOLEAN:
            return True
        return np.iinfo(npdt).max
    if kind == "max":
        if dt.is_floating:
            return np.asarray(-np.inf, dtype=npdt)
        if dt is DType.BOOLEAN:
            return False
        return np.iinfo(npdt).min
    raise ValueError(kind)
