"""Generator expressions (reference: GpuGenerateExec.scala meta handling).

The reference's v0 scope is explode/posexplode of a *created* array —
``Explode(CreateArray(exprs))`` or an array literal (GpuGenerateExec.scala:45-62
``arrayExprs``, tagPlanForGpu "Only posexplode of a created array is currently
supported"). That keeps every shape static: each input row emits exactly
len(elements) output rows, which is the Expand kernel's shape. These classes are
plan-time markers consumed by the planner; they never reach expression
evaluation (ARRAY is not a columnar type here, same as the reference's type
gate excluding ArrayType, GpuOverrides.isSupportedType:389).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import Expression


@dataclass(frozen=True)
class CreateArray(Expression):
    """Array built from per-row scalar expressions (Spark's CreateArray)."""
    items: Tuple[Expression, ...]

    def dtype(self) -> DType:
        raise TypeError("array values only exist inside explode/posexplode on "
                        "this engine (ARRAY is not a columnar type)")

    def element_type(self) -> DType:
        dt = DType.NULL
        for e in self.items:
            et = e.dtype()
            if et is DType.NULL:
                continue
            dt = et if dt is DType.NULL else DType.common_type(dt, et)
        return dt


@dataclass(frozen=True)
class Explode(Expression):
    """One output row per array element (Spark's Explode generator)."""
    child_array: CreateArray
    #: with_position=True is posexplode: an extra int 'pos' column
    with_position: bool = False

    def dtype(self) -> DType:
        return self.child_array.element_type()

    def nullable(self) -> bool:
        return any(e.nullable() for e in self.child_array.items)

    @property
    def name_hint(self) -> str:
        return "col"
