"""Literal expressions (reference: literals.scala, 211 LoC)."""
from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression


def infer_literal_dtype(value: Any) -> DType:
    if isinstance(value, bool):
        return DType.BOOLEAN
    if isinstance(value, int):
        return DType.INT if -(2**31) <= value < 2**31 else DType.LONG
    if isinstance(value, float):
        return DType.DOUBLE
    if isinstance(value, str):
        return DType.STRING
    if isinstance(value, datetime.datetime):
        return DType.TIMESTAMP
    if isinstance(value, datetime.date):
        return DType.DATE
    if value is None:
        return DType.NULL
    raise TypeError(f"cannot infer literal type for {value!r}")


def _to_physical(value: Any, dtype: DType) -> Any:
    """Python value -> Catalyst physical representation."""
    if value is None:
        return None
    if dtype is DType.DATE and isinstance(value, datetime.date):
        return (value - datetime.date(1970, 1, 1)).days
    if dtype is DType.TIMESTAMP and isinstance(value, datetime.datetime):
        if value.tzinfo is None:
            value = value.replace(tzinfo=datetime.timezone.utc)
        return int(value.timestamp() * 1_000_000)
    return value


@dataclass(frozen=True)
class Literal(Expression):
    value: Any
    lit_dtype: Optional[DType] = None

    @staticmethod
    def of(value: Any, dtype: Optional[DType] = None) -> "Literal":
        return Literal(value, dtype or infer_literal_dtype(value))

    def dtype(self) -> DType:
        return self.lit_dtype or infer_literal_dtype(self.value)

    def nullable(self) -> bool:
        return self.value is None

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        dt = self.dtype()
        phys = _to_physical(self.value, dt)
        valid = xp.asarray(phys is not None)
        if dt is DType.STRING:
            from spark_rapids_tpu.columnar.batch import string_width_bucket
            raw = (phys or "").encode("utf-8")
            if len(raw) > ctx.string_max_bytes:
                raise ValueError(f"string literal longer than device width "
                                 f"{ctx.string_max_bytes}")
            buf = np.zeros(string_width_bucket(len(raw),
                                               ctx.string_max_bytes),
                           dtype=np.uint8)
            buf[:len(raw)] = bytearray(raw)
            return ColV(dt, xp.asarray(buf), valid,
                        xp.asarray(np.int32(len(raw))), is_scalar=True)
        if dt is DType.NULL:
            return ColV(dt, xp.asarray(np.int8(0)), xp.asarray(False), is_scalar=True)
        data = xp.asarray(np.asarray(phys if phys is not None else 0,
                                     dtype=dt.np_dtype()))
        return ColV(dt, data, valid, is_scalar=True)

    def __str__(self) -> str:
        return repr(self.value)
