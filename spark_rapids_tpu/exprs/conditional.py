"""Conditional expressions: If / CaseWhen (reference: conditionalExpressions.scala,
251 LoC — if/case-when via cudf ifElse; here a where-chain fused by XLA)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import ColV, EvalCtx, Expression, widen


def _select(ctx: EvalCtx, cond, t: ColV, f: ColV, dt: DType) -> ColV:
    """cond ? t : f with validity selection. cond is a plain bool array."""
    xp = ctx.xp
    t = widen(ctx, t, dt)
    f = widen(ctx, f, dt)
    if dt is DType.STRING:
        from spark_rapids_tpu.exprs.strings import _as_column
        from spark_rapids_tpu.ops.strings import align_widths
        if getattr(cond, "ndim", 0) != 0:  # column-shaped condition
            t = _as_column(xp, t, ctx.capacity)
            f = _as_column(xp, f, ctx.capacity)
        td, fd = align_widths(xp, t.data, f.data)
        cnd = cond[..., None] if td.ndim == 2 else cond
        data = xp.where(cnd, td, fd)
        lengths = xp.where(cond, t.lengths, f.lengths)
        valid = xp.where(cond, t.validity, f.validity)
        return ColV(dt, data, valid, lengths)
    data = xp.where(cond, t.data, f.data)
    valid = xp.where(cond, t.validity, f.validity)
    return ColV(dt, data, valid)


@dataclass(frozen=True)
class If(Expression):
    pred: Expression
    t: Expression
    f: Expression

    def dtype(self) -> DType:
        return DType.common_type(self.t.dtype(), self.f.dtype())

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        p = self.pred.eval(ctx)
        cond = xp.logical_and(p.data, p.validity)  # null predicate -> else branch
        return _select(ctx, cond, self.t.eval(ctx), self.f.eval(ctx), self.dtype())


@dataclass(frozen=True)
class CaseWhen(Expression):
    """branches: ((cond, value), ...); else_value optional (null if absent)."""
    branches: Tuple  # of (Expression, Expression)
    else_value: Optional[Expression] = None

    def dtype(self) -> DType:
        dtypes = [v.dtype() for _, v in self.branches]
        if self.else_value is not None:
            dtypes.append(self.else_value.dtype())
        return DType.common_type_all(dtypes)

    @property
    def children(self) -> Tuple[Expression, ...]:
        out = []
        for c, v in self.branches:
            out.extend([c, v])
        if self.else_value is not None:
            out.append(self.else_value)
        return tuple(out)

    def map_children(self, fn) -> "CaseWhen":
        branches = tuple((fn(c), fn(v)) for c, v in self.branches)
        ev = fn(self.else_value) if self.else_value is not None else None
        return CaseWhen(branches, ev)

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        dt = self.dtype()
        from spark_rapids_tpu.exprs.literals import Literal
        else_expr = self.else_value or Literal(None, dt)
        out = widen(ctx, else_expr.eval(ctx), dt)
        # fold right-to-left so earlier branches win
        for cond_e, val_e in reversed(self.branches):
            p = cond_e.eval(ctx)
            cond = xp.logical_and(p.data, p.validity)
            v = val_e.eval(ctx)
            out = _select(ctx, cond, v, out, dt)
        return out
