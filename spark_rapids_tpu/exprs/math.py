"""Math expressions (reference: mathExpressions.scala, 378 LoC).

Spark semantics: unary math works in DOUBLE; log/log2/log10 return NULL for
inputs <= 0 (log1p for <= -1); sqrt of negative is NaN (stays valid);
asin/acos out of [-1,1] is NaN. round uses HALF_UP on the decimal value.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from spark_rapids_tpu.columnar.dtypes import DType
from spark_rapids_tpu.exprs.core import (BinaryExpression, ColV, EvalCtx, Expression,
                                         UnaryExpression)


class _DoubleUnary(UnaryExpression):
    """Unary math op evaluated in double."""

    def dtype(self) -> DType:
        return DType.DOUBLE

    def eval(self, ctx: EvalCtx) -> ColV:
        c = self.child.eval(ctx)
        d = c.data.astype(np.float64) if c.dtype != DType.DOUBLE else c.data
        data = self.fn(ctx.xp, d)
        validity = self.valid_fn(ctx.xp, d, c.validity)
        return ColV(DType.DOUBLE, data, validity, is_scalar=c.is_scalar)

    def fn(self, xp, d):
        raise NotImplementedError

    def valid_fn(self, xp, d, validity):
        return validity


def _double_unary(name: str, fn, valid_fn=None):
    @dataclass(frozen=True)
    class _Op(_DoubleUnary):
        c: Expression
        __qualname__ = name

        def fn(self, xp, d):
            return fn(xp, d)

        def valid_fn(self, xp, d, validity):
            if valid_fn is None:
                return validity
            return xp.logical_and(validity, valid_fn(xp, d))

        def sql_name(self) -> str:
            return name
    _Op.__name__ = name
    return _Op


Sqrt = _double_unary("Sqrt", lambda xp, d: xp.sqrt(xp.abs(d)) * xp.where(d < 0, xp.nan, 1.0))
Cbrt = _double_unary("Cbrt", lambda xp, d: xp.cbrt(d))
Exp = _double_unary("Exp", lambda xp, d: xp.exp(d))
Expm1 = _double_unary("Expm1", lambda xp, d: xp.expm1(d))
Log = _double_unary("Log", lambda xp, d: xp.log(xp.where(d <= 0, 1.0, d)),
                    valid_fn=lambda xp, d: d > 0)
Log2 = _double_unary("Log2", lambda xp, d: xp.log2(xp.where(d <= 0, 1.0, d)),
                     valid_fn=lambda xp, d: d > 0)
Log10 = _double_unary("Log10", lambda xp, d: xp.log10(xp.where(d <= 0, 1.0, d)),
                      valid_fn=lambda xp, d: d > 0)
Log1p = _double_unary("Log1p", lambda xp, d: xp.log1p(xp.where(d <= -1, 0.0, d)),
                      valid_fn=lambda xp, d: d > -1)
Sin = _double_unary("Sin", lambda xp, d: xp.sin(d))
Cos = _double_unary("Cos", lambda xp, d: xp.cos(d))
Tan = _double_unary("Tan", lambda xp, d: xp.tan(d))
Asin = _double_unary("Asin", lambda xp, d: xp.arcsin(d))
Acos = _double_unary("Acos", lambda xp, d: xp.arccos(d))
Atan = _double_unary("Atan", lambda xp, d: xp.arctan(d))
Sinh = _double_unary("Sinh", lambda xp, d: xp.sinh(d))
Cosh = _double_unary("Cosh", lambda xp, d: xp.cosh(d))
Tanh = _double_unary("Tanh", lambda xp, d: xp.tanh(d))
ToDegrees = _double_unary("ToDegrees", lambda xp, d: xp.degrees(d))
ToRadians = _double_unary("ToRadians", lambda xp, d: xp.radians(d))


@dataclass(frozen=True)
class Signum(_DoubleUnary):
    c: Expression

    def fn(self, xp, d):
        return xp.sign(d)


@dataclass(frozen=True)
class Floor(UnaryExpression):
    c: Expression

    def dtype(self) -> DType:
        return DType.LONG if self.child.dtype().is_floating else self.child.dtype()

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        if not child.dtype.is_floating:
            return child.data
        return ctx.xp.floor(child.data).astype(np.int64)


@dataclass(frozen=True)
class Ceil(UnaryExpression):
    c: Expression

    def dtype(self) -> DType:
        return DType.LONG if self.child.dtype().is_floating else self.child.dtype()

    def do_columnar(self, ctx: EvalCtx, child: ColV):
        if not child.dtype.is_floating:
            return child.data
        return ctx.xp.ceil(child.data).astype(np.int64)


@dataclass(frozen=True)
class Rint(_DoubleUnary):
    """rint: round half to even, stays double (Java Math.rint)."""
    c: Expression

    def fn(self, xp, d):
        return xp.round(d)


@dataclass(frozen=True)
class Pow(BinaryExpression):
    l: Expression
    r: Expression

    def operand_dtype(self) -> DType:
        return DType.DOUBLE

    def dtype(self) -> DType:
        return DType.DOUBLE

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return ctx.xp.power(l.data, r.data)


@dataclass(frozen=True)
class Atan2(BinaryExpression):
    l: Expression
    r: Expression

    def operand_dtype(self) -> DType:
        return DType.DOUBLE

    def dtype(self) -> DType:
        return DType.DOUBLE

    def do_columnar(self, ctx: EvalCtx, l: ColV, r: ColV):
        return ctx.xp.arctan2(l.data, r.data)


@dataclass(frozen=True)
class Round(Expression):
    """round(x, scale): HALF_UP rounding (Spark BigDecimal.ROUND_HALF_UP)."""
    c: Expression
    scale: int = 0

    def dtype(self) -> DType:
        return self.c.dtype()

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        v = self.c.eval(ctx)
        if v.dtype.is_integral and self.scale >= 0:
            return v
        factor = float(10 ** self.scale)
        scaled = v.data.astype(np.float64) * factor
        # HALF_UP: away from zero on .5 (numpy round is half-to-even)
        rounded = xp.sign(scaled) * xp.floor(xp.abs(scaled) + 0.5)
        data = rounded / factor
        if v.dtype.is_integral:
            data = data.astype(v.dtype.np_dtype())
        elif v.dtype is DType.FLOAT:
            data = data.astype(np.float32)
        return ColV(v.dtype, data, v.validity, is_scalar=v.is_scalar)


Cot = _double_unary("Cot", lambda xp, d: 1.0 / xp.tan(d))
Asinh = _double_unary("Asinh", lambda xp, d: xp.arcsinh(d))
Acosh = _double_unary("Acosh", lambda xp, d: xp.arccosh(d))
Atanh = _double_unary("Atanh", lambda xp, d: xp.arctanh(d))


@dataclass(frozen=True)
class Logarithm(BinaryExpression):
    """log(base, expr) — NULL when expr <= 0 or base <= 0 (Spark
    mathExpressions Logarithm semantics)."""
    b: Expression
    c: Expression

    def dtype(self) -> DType:
        return DType.DOUBLE

    def operand_dtype(self) -> DType:
        return DType.DOUBLE

    def eval(self, ctx: EvalCtx) -> ColV:
        xp = ctx.xp
        l = self.b.eval(ctx)
        r = self.c.eval(ctx)
        base = l.data.astype(np.float64)
        v = r.data.astype(np.float64)
        safe_b = xp.where(base > 0, base, 1.0)
        safe_v = xp.where(v > 0, v, 1.0)
        data = xp.log(safe_v) / xp.log(safe_b)
        validity = xp.logical_and(
            xp.logical_and(l.validity, r.validity),
            xp.logical_and(base > 0, v > 0))
        return ColV(DType.DOUBLE, data, validity,
                    is_scalar=l.is_scalar and r.is_scalar)
