"""spark_rapids_tpu: TPU-native SQL plan acceleration framework.

A ground-up re-design of the RAPIDS Accelerator for Apache Spark
(reference: ravitestgit/spark-rapids) for TPU hardware: Spark-style physical plans
execute as fused, jit-compiled XLA columnar programs over device batches, with
tiered HBM->host->disk spill, a device-admission semaphore, mesh-sharded
distributed execution via jax collectives, and a CPU (pyarrow) engine for
fallback + result-comparison testing.
"""
__version__ = "0.1.0"
