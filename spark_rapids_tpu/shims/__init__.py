"""Version shims: adapters over JAX API drift.

Reference analogs: SparkShims trait (SparkShims.scala:58-127 — ~25 methods
abstracting Spark API drift across 3.0.0/3.0.1/3.1.0/Databricks) and
ShimLoader (ShimLoader.scala:33-60 — ServiceLoader picking the provider whose
version_match accepts the runtime version). The reference's drift surface is
Spark; this framework's is JAX, whose public API moved repeatedly across the
0.4 -> 0.5+ line (new-style PRNG keys, jax.tree namespace, jax.make_mesh).
Every version-sensitive call in the engine routes through ``get()`` so
supporting a new JAX release means one new provider class, exactly like
adding a shims/sparkXYZ module in the reference.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class JaxShims:
    """Provider interface (SparkShims trait analog). Subclasses pin the
    version range they serve and override what drifted there."""

    @staticmethod
    def version_match(version: str) -> bool:
        raise NotImplementedError

    # ---- RNG ------------------------------------------------------------------
    def prng_key(self, seed: int):
        """New-style typed PRNG key (jax.random.key, 0.4.16+)."""
        import jax
        return jax.random.key(seed)

    # ---- trees ----------------------------------------------------------------
    def tree_map(self, fn, tree):
        """jax.tree.map (0.4.25+); older releases only had
        jax.tree_util.tree_map."""
        import jax
        return jax.tree.map(fn, tree)

    # ---- meshes ---------------------------------------------------------------
    def make_mesh(self, devices: Sequence, axis_names):
        """Build a Mesh over explicit devices (stable across versions; routed
        through the shim so a future Mesh-API change lands in one place)."""
        from jax.sharding import Mesh
        return Mesh(np.array(devices), axis_names)

    def shard_map(self, f, mesh, in_specs, out_specs, check_vma=False):
        """Top-level jax.shard_map (promoted from experimental in 0.5+);
        ``check_vma`` is the 0.5+ name of the replication check flag."""
        import jax
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

    # ---- dtype bit tricks -----------------------------------------------------
    def bitcast(self, arr, dtype):
        import jax
        return jax.lax.bitcast_convert_type(arr, dtype)


class Jax05PlusShims(JaxShims):
    """0.5.x and later (including the 0.9 line this image ships)."""

    @staticmethod
    def version_match(version: str) -> bool:
        major, minor = _parse(version)
        return (major, minor) >= (0, 5)


class Jax04Shims(JaxShims):
    """The 0.4 line: old-style uint32 PRNG keys were still the safe default
    and jax.tree.map did not exist before 0.4.25."""

    @staticmethod
    def version_match(version: str) -> bool:
        major, minor = _parse(version)
        return (major, minor) == (0, 4)

    def prng_key(self, seed: int):
        import jax
        return jax.random.PRNGKey(seed)

    def tree_map(self, fn, tree):
        import jax
        return jax.tree_util.tree_map(fn, tree)

    def shard_map(self, f, mesh, in_specs, out_specs, check_vma=False):
        """0.4 location (jax.experimental.shard_map) and flag name
        (check_rep)."""
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)


#: registration order = match priority (ShimLoader's provider list)
PROVIDERS: List[type] = [Jax05PlusShims, Jax04Shims]

_ACTIVE: Optional[JaxShims] = None


def _parse(version: str):
    parts = version.split(".")
    try:
        return int(parts[0]), int(parts[1])
    except (ValueError, IndexError):
        return (0, 0)


def get() -> JaxShims:
    """The provider matching the runtime jax version (ShimLoader.getShims
    analog); raises if no provider claims it, like the reference's
    'Could not find Spark Shim Loader' error."""
    global _ACTIVE
    if _ACTIVE is None:
        import jax
        version = jax.__version__
        for cls in PROVIDERS:
            if cls.version_match(version):
                _ACTIVE = cls()
                break
        else:
            raise RuntimeError(
                f"no shim provider matches jax {version}; supported: "
                f"{[c.__name__ for c in PROVIDERS]}")
    return _ACTIVE
