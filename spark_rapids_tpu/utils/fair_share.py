"""Weighted deficit round-robin primitives.

ONE fairness policy shared by the two admission layers — the serving
scheduler's query queues and the device-admission semaphore's waiter
queues — so their semantics cannot drift apart. Both layers keep their
own locking and queue structures; these helpers are pure functions over
(tenants, served-counters, weights).

Semantics:
- the next tenant served is the one with the lowest ``served / weight``
  deficit (a tenant with weight 3 is served three times as often as a
  tenant with weight 1 under contention); ties break deterministically
  by tenant name;
- FIFO within a tenant is the caller's queue discipline;
- on ACTIVATION (a tenant's queue going empty -> non-empty) the tenant's
  deficit resets to the current minimum over the other active tenants: a
  newcomer cannot jump ahead of standing backlogs by arriving with zero
  history, and a returning tenant is not starved while the others "catch
  up" to its long-served past (standard DRR counter reset, adapted to
  weighted deficits). A tenant activating alone resets to zero.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional


def weight_of(weights: Dict[str, float], tenant: str) -> float:
    return weights.get(tenant, 1.0)


def pick_tenant(active: Iterable[str], served: Dict[str, float],
                weights: Dict[str, float]) -> Optional[str]:
    """The active tenant with the lowest weighted deficit (None if no
    tenant is active)."""
    active = list(active)
    if not active:
        return None
    return min(active, key=lambda t: (served.get(t, 0.0)
                                      / weight_of(weights, t), t))


def activation_reset(tenant: str, active_others: Iterable[str],
                     served: Dict[str, float],
                     weights: Dict[str, float]) -> None:
    """Reset ``tenant``'s deficit as it (re)activates: join at the
    minimum deficit of the OTHER currently-active tenants (zero when
    alone). Mutates ``served`` in place; call under the owning lock."""
    others = [t for t in active_others if t != tenant]
    if others:
        floor = min(served.get(t, 0.0) / weight_of(weights, t)
                    for t in others)
    else:
        floor = 0.0
    served[tenant] = floor * weight_of(weights, tenant)
