from spark_rapids_tpu.utils.arm import closing_on_except, close_all, Retainable
from spark_rapids_tpu.utils.metrics import Metric, MetricSet, NamedRange
