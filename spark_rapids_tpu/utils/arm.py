"""Resource-lifetime helpers.

Analog of the reference's ``Arm`` trait (Arm.scala: ``withResource``/``closeOnExcept``)
and the ref-counted buffer conventions in RapidsBufferStore.scala:253. JAX arrays are
garbage collected, but spillable buffers, host staging memory, and shuffle handles need
deterministic close/refcount semantics, which these helpers provide.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterable, Iterator


@contextlib.contextmanager
def closing_on_except(resource: Any) -> Iterator[Any]:
    """Close ``resource`` only if the body raises (analog of Arm.closeOnExcept)."""
    try:
        yield resource
    except BaseException:
        with contextlib.suppress(Exception):
            resource.close()
        raise


def close_all(resources: Iterable[Any]) -> None:
    first_err = None
    for r in resources:
        try:
            if r is not None:
                r.close()
        except Exception as e:  # noqa: BLE001 - collect and re-raise first
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


class Retainable:
    """Ref-counted resource. Subclasses override ``_on_release``.

    Mirrors the acquire/release discipline of RapidsBuffer (RapidsBuffer.scala:61):
    constructed with refcount 1; ``retain`` bumps; ``close`` drops; the final drop
    triggers ``_on_release``. Double-close raises.
    """

    def __init__(self) -> None:
        self._refcount = 1
        self._lock = threading.Lock()

    def retain(self) -> "Retainable":
        with self._lock:
            if self._refcount <= 0:
                raise ValueError(f"retain() after close: {self!r}")
            self._refcount += 1
        return self

    @property
    def refcount(self) -> int:
        with self._lock:
            return self._refcount

    def close(self) -> None:
        with self._lock:
            if self._refcount <= 0:
                raise ValueError(f"double close: {self!r}")
            self._refcount -= 1
            release = self._refcount == 0
        if release:
            self._on_release()

    def _on_release(self) -> None:  # pragma: no cover - default no-op
        pass

    def __enter__(self) -> "Retainable":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
