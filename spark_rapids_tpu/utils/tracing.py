"""Structured query tracing: thread-bound spans in a bounded ring buffer.

The reference plugin is debuggable because every GpuExec carries
``totalTime``/``peakDevMemory``/``bufferTime`` and an NVTX range — you can
say which exec in a 20-node plan ate the wall clock. Our engine only
reported flat per-action counter DELTAS (utils/metrics.py): no per-operator
attribution, no timeline. This module is the missing layer, consumed by
three surfaces:

- **EXPLAIN ANALYZE** — ``PhysicalExec.tree_string(analyze=True)`` /
  ``TpuSession.explain_analyze()`` / ``QueryHandle.explain_analyze()``
  annotate each plan node with observed rows / batches / wall / self time
  (and grace-spill counts), Spark-UI style;
- **Perfetto / Chrome trace-event export** — ``export_chrome()`` writes
  the span window as ``{"traceEvents": [...]}`` JSON that loads in
  ``ui.perfetto.dev`` or ``chrome://tracing``, so overlapped pipelines
  (chunked upload vs compute, streaming D2H) are visually inspectable;
- **serve.stats** — the serving layer's rolling gauge window
  (serving/stats.py) rides the same per-query attribution.

Design constraints (the R002 contract):

- timestamps are ``time.perf_counter_ns`` taken at HOST boundaries that
  already exist — exec ``__next__`` calls, chunk staging returns, async
  D2H resolution, admission wakeups. No new device syncs anywhere: a span
  never calls ``block_until_ready``/``np.asarray`` on device data.
- disabled mode is near-zero-cost: every hook is gated on one module-bool
  read (``enabled()``); ``span()`` returns a shared no-op context manager
  without allocating. The disabled overhead is microbenchmarked in
  bench.py's ``observability`` section and gated in nightly CI.
- the ring buffer is bounded (``trace.maxBufferedSpans``): a long-running
  traced server overwrites its oldest spans instead of growing without
  bound. ``mark()``/``since()`` give an action-scoped window; per-query
  filtering uses the span's query id (bound thread-locally by the serving
  worker via ``serving.lifecycle.bind_query``).

Span layers (``cat``): ``exec`` (operator execute boundaries), ``transfer``
(chunk upload / async download), ``shuffle`` (fetch / retry), ``memory``
(grace partition / spill), ``serving`` (lifecycle transitions, admission
and preemption waits, wire frames).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

#: span layer names every consumer agrees on (docs/observability.md)
LAYER_EXEC = "exec"
LAYER_TRANSFER = "transfer"
LAYER_SHUFFLE = "shuffle"
LAYER_MEMORY = "memory"
LAYER_SERVING = "serving"


class SpanRecord:
    """One completed span (or instant event, ``dur_ns == 0``)."""

    __slots__ = ("name", "cat", "ts_ns", "dur_ns", "tid", "query_id",
                 "plan_id", "args", "seq")

    def __init__(self, name: str, cat: str, ts_ns: int, dur_ns: int,
                 tid: int, query_id: Optional[int],
                 plan_id: Optional[int], args: Optional[Dict[str, Any]],
                 seq: int):
        self.name = name
        self.cat = cat
        self.ts_ns = ts_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.query_id = query_id
        self.plan_id = plan_id
        self.args = args
        self.seq = seq

    def to_event(self) -> Dict[str, Any]:
        """Chrome trace-event form (``ph: X`` complete events; instants
        use ``ph: i``). Timestamps/durations are microseconds."""
        import os
        ev: Dict[str, Any] = {
            "name": self.name, "cat": self.cat, "pid": os.getpid(),
            "tid": self.tid, "ts": self.ts_ns / 1e3,
        }
        if self.dur_ns > 0:
            ev["ph"] = "X"
            ev["dur"] = self.dur_ns / 1e3
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        args = dict(self.args or {})
        if self.query_id is not None:
            args["query_id"] = self.query_id
        if self.plan_id is not None:
            args["plan_id"] = self.plan_id
        if args:
            ev["args"] = args
        return ev


class _NullSpan:
    """Shared no-op context manager returned while tracing is off —
    ``span()`` on the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager recording one span on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._tracer.record(self._name, self._cat, self._t0,
                            time.perf_counter_ns() - self._t0, self._args)
        return False


def _current_query_id() -> Optional[int]:
    # lazy, cached: only runs while tracing is ON (never on the hot path)
    global _CURRENT_QUERY
    if _CURRENT_QUERY is None:
        from spark_rapids_tpu.serving.lifecycle import current_query
        _CURRENT_QUERY = current_query
    q = _CURRENT_QUERY()
    return q.query_id if q is not None else None


_CURRENT_QUERY = None


class Tracer:
    """Bounded ring buffer of spans with an activation count.

    ``activate()`` scopes (one per traced action / served query) nest; the
    ring survives across scopes so a server can export a window covering
    many queries. ``mark()``/``since()`` give callers an action-scoped
    slice without copying the whole ring.
    """

    DEFAULT_CAPACITY = 65536

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = max(16, capacity)
        self._ring: List[Optional[SpanRecord]] = [None] * self._capacity
        self._seq = 0               # monotonically increasing record count
        self._active = 0
        #: the one-field fast path every disabled hook reads
        self.on = False

    # ---- activation --------------------------------------------------------
    def configure(self, capacity: int) -> None:
        """Resize the ring, PRESERVING the newest min(old, new) records —
        the capacity is effectively process-wide (one tracer, many
        sessions), so a session with a different trace.maxBufferedSpans
        must not wipe a just-finished query's exportable spans. Resizes
        are skipped while an activation is live (a shrink could drop part
        of a running action's window)."""
        with self._lock:
            capacity = max(16, int(capacity))
            if capacity == self._capacity or self._active > 0:
                return
            new_ring: List[Optional[SpanRecord]] = [None] * capacity
            lo = max(0, self._seq - min(self._capacity, capacity))
            for i in range(lo, self._seq):
                new_ring[i % capacity] = self._ring[i % self._capacity]
            self._capacity = capacity
            self._ring = new_ring

    def activate(self):
        """Context manager turning tracing on for the scope (nesting
        counts; ``on`` stays True until the outermost scope exits)."""
        tracer = self

        class _Scope:
            def __enter__(self):
                with tracer._lock:
                    tracer._active += 1
                    tracer.on = True
                return tracer

            def __exit__(self, *exc):
                with tracer._lock:
                    tracer._active -= 1
                    tracer.on = tracer._active > 0
                return False

        return _Scope()

    # ---- recording ---------------------------------------------------------
    def record(self, name: str, cat: str, ts_ns: int, dur_ns: int,
               args: Optional[Dict[str, Any]] = None,
               plan_id: Optional[int] = None,
               query_id: Optional[int] = None) -> None:
        if not self.on:
            return
        if query_id is None:
            query_id = _current_query_id()
        rec = SpanRecord(name, cat, ts_ns, dur_ns, threading.get_ident(),
                         query_id, plan_id, args, 0)
        with self._lock:
            rec.seq = self._seq
            self._ring[self._seq % self._capacity] = rec
            self._seq += 1

    def span(self, name: str, cat: str,
             args: Optional[Dict[str, Any]] = None):
        """Timed scope; the disabled path returns one shared no-op."""
        if not self.on:
            return _NULL_SPAN
        return _LiveSpan(self, name, cat, args)

    def instant(self, name: str, cat: str,
                args: Optional[Dict[str, Any]] = None) -> None:
        if not self.on:
            return
        self.record(name, cat, time.perf_counter_ns(), 0, args)

    # ---- reading -----------------------------------------------------------
    def mark(self) -> int:
        """Current sequence number — pass to ``since()`` for the spans
        recorded after this point (an action-scoped window)."""
        with self._lock:
            return self._seq

    def since(self, mark: int, query_id: Optional[int] = None
              ) -> List[SpanRecord]:
        """Spans recorded at or after ``mark`` (oldest first), optionally
        filtered to one query. Records the ring already overwrote are
        gone — the window is bounded by trace.maxBufferedSpans."""
        with self._lock:
            lo = max(mark, self._seq - self._capacity)
            out = [self._ring[i % self._capacity]
                   for i in range(lo, self._seq)]
        return [r for r in out
                if r is not None
                and (query_id is None or r.query_id == query_id)]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self._capacity
            self._seq = 0


#: the process-wide tracer every layer records into
TRACER = Tracer()


def enabled() -> bool:
    return TRACER.on


def span(name: str, cat: str, args: Optional[Dict[str, Any]] = None):
    return TRACER.span(name, cat, args)


def instant(name: str, cat: str,
            args: Optional[Dict[str, Any]] = None) -> None:
    TRACER.instant(name, cat, args)


def record(name: str, cat: str, ts_ns: int, dur_ns: int,
           args: Optional[Dict[str, Any]] = None,
           plan_id: Optional[int] = None,
           query_id: Optional[int] = None) -> None:
    TRACER.record(name, cat, ts_ns, dur_ns, args, plan_id, query_id)


# ---------------------------------------------------------------- exec spans
#: per-thread currently-recording exec frame, for self-time attribution:
#: a child exec's __next__ time nested inside its parent's subtracts from
#: the parent's SELF time (the classic profiler discipline). Producer
#: threads (PipelinedExec / prefetch) keep their own stack — cross-thread
#: overlap deliberately does not subtract (it is genuine concurrency).
_EXEC_TLS = threading.local()


class _ExecRecorder:
    """Aggregated observation of one exec node across one execute() call."""

    __slots__ = ("node", "wall_ns", "child_ns", "rows", "batches", "bytes",
                 "t_first")

    def __init__(self, node):
        self.node = node
        self.wall_ns = 0
        self.child_ns = 0
        self.rows = 0
        self.batches = 0
        self.bytes = 0
        self.t_first = 0


def observed_of(node) -> Optional[Dict[str, Any]]:
    """The node's accumulated observation dict (None before any traced
    execution). Keys: rows, batches, bytes, wall_ns, self_ns, partitions,
    plus grace_partitions / grace_depth when the out-of-core path ran."""
    return getattr(node, "_observed", None)


def _accumulate(node, rec: _ExecRecorder) -> None:
    obs = getattr(node, "_observed", None)
    with TRACER._lock:
        if obs is None:
            obs = node._observed = {"rows": 0, "batches": 0, "bytes": 0,
                                    "wall_ns": 0, "self_ns": 0,
                                    "partitions": 0}
        obs["rows"] += rec.rows
        obs["batches"] += rec.batches
        obs["bytes"] += rec.bytes
        obs["wall_ns"] += rec.wall_ns
        obs["self_ns"] += max(rec.wall_ns - rec.child_ns, 0)
        obs["partitions"] += 1


def note_exec_spill(node, partitions: int, depth: int) -> None:
    """Grace layer attribution: this node's input was grace-partitioned
    (EXPLAIN ANALYZE renders it as ``spill=nxd``). Cheap dict stores on
    the already-degraded path — recorded even when span tracing is off so
    analyze output stays truthful about spills. Same lock as
    ``_accumulate``: one plan node's partitions can execute on parallel
    task threads (cluster task slots)."""
    with TRACER._lock:
        obs = getattr(node, "_observed", None)
        if obs is None:
            obs = node._observed = {"rows": 0, "batches": 0, "bytes": 0,
                                    "wall_ns": 0, "self_ns": 0,
                                    "partitions": 0}
        obs["grace_partitions"] = obs.get("grace_partitions", 0) + partitions
        obs["grace_depth"] = max(obs.get("grace_depth", 0), depth)


def _profiler_annotation(name: str):
    """A jax.profiler.TraceAnnotation for ``name`` (the per-exec named
    range TRACE_ENABLED promises — NvtxWithMetrics analog), or None when
    the profiler is unavailable."""
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            import jax.profiler
            _TRACE_ANNOTATION = jax.profiler.TraceAnnotation
        except Exception:
            _TRACE_ANNOTATION = False
    if _TRACE_ANNOTATION is False:
        return None
    try:
        return _TRACE_ANNOTATION(name)
    except Exception:
        return None


_TRACE_ANNOTATION = None


def trace_exec(node, ctx, raw) -> Iterator:
    """Wrap one exec's ``execute()`` iteration with span recording: each
    ``__next__`` is timed (and shows as a named jax.profiler range), rows/
    batches/bytes are observed from the yielded batches, and ONE span per
    execute() call lands in the ring (ts = first pull, dur = pull window).
    Self time subtracts nested child pulls on the same thread.

    A subclass delegating to ``super().execute()`` (FusedAggregateStage ->
    TpuHashAggregate) must not double-record the node: when the CURRENT
    frame already records this node, the raw iterator passes through."""
    cur = getattr(_EXEC_TLS, "rec", None)
    if cur is not None and cur.node is node:
        yield from raw(node, ctx)
        return
    rec = _ExecRecorder(node)
    qid = _current_query_id()
    range_name = f"{node.name}#{node.plan_id}" if node.plan_id is not None \
        else node.name
    it = iter(raw(node, ctx))
    try:
        while True:
            parent = getattr(_EXEC_TLS, "rec", None)
            _EXEC_TLS.rec = rec
            ann = _profiler_annotation(range_name)
            t0 = time.perf_counter_ns()
            if rec.t_first == 0:
                rec.t_first = t0
            try:
                if ann is not None:
                    with ann:
                        batch = next(it)
                else:
                    batch = next(it)
            except StopIteration:
                return
            finally:
                dt = time.perf_counter_ns() - t0
                rec.wall_ns += dt
                if parent is not None:
                    parent.child_ns += dt
                _EXEC_TLS.rec = parent
            rec.batches += 1
            n = getattr(batch, "num_rows", None)
            if n is not None:
                rec.rows += int(n)
            rec.bytes += int(getattr(batch, "device_size_bytes", 0) or 0)
            yield batch
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()
        _accumulate(node, rec)
        if rec.t_first:
            TRACER.record(
                node.name, LAYER_EXEC, rec.t_first,
                time.perf_counter_ns() - rec.t_first,
                {"rows": rec.rows, "batches": rec.batches,
                 "bytes": rec.bytes,
                 "busy_ms": round(rec.wall_ns / 1e6, 3),
                 "self_ms": round(max(rec.wall_ns - rec.child_ns, 0) / 1e6,
                                  3),
                 "partition": ctx.partition_id},
                plan_id=node.plan_id, query_id=qid)


# ---------------------------------------------------------------- rendering
def _fmt_ms(ns: int) -> str:
    return f"{ns / 1e6:.1f}ms"


def analyze_annotation(node) -> str:
    """The EXPLAIN ANALYZE suffix for one plan node, '' when the node was
    never executed under tracing."""
    obs = getattr(node, "_observed", None)
    if obs is None:
        return ""
    parts = [f"rows={obs['rows']}", f"batches={obs['batches']}"]
    if obs.get("wall_ns"):
        parts.append(f"wall={_fmt_ms(obs['wall_ns'])}")
        parts.append(f"self={_fmt_ms(obs['self_ns'])}")
    if obs.get("bytes"):
        parts.append(f"bytes={obs['bytes']}")
    if obs.get("grace_partitions"):
        parts.append(f"spill={obs['grace_partitions']}p"
                     f"x{obs.get('grace_depth', 1)}d")
    return " (" + ", ".join(parts) + ")"


def export_chrome(records: List[SpanRecord], path: str,
                  metadata: Optional[Dict[str, Any]] = None) -> None:
    """Write ``records`` as Chrome trace-event JSON (loads in Perfetto /
    chrome://tracing). ``metadata`` lands in the top-level ``otherData``."""
    doc = {"traceEvents": [r.to_event() for r in records],
           "displayTimeUnit": "ms"}
    if metadata:
        doc["otherData"] = metadata
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)


def layer_counts(records: List[SpanRecord]) -> Dict[str, int]:
    """Span count per layer — the CI smoke's one-line acceptance check."""
    out: Dict[str, int] = {}
    for r in records:
        out[r.cat] = out.get(r.cat, 0) + 1
    return out
