"""Per-operator metrics and named trace ranges.

Analog of the reference's SQLMetrics wiring (GpuExec.scala:28-52 GpuMetricNames:
numOutputRows, numOutputBatches, totalTime, peakDevMemory, bufferTime, ...) and the
NVTX named ranges (NvtxWithMetrics.scala:44). On TPU the tracing backend is
``jax.profiler.TraceAnnotation``; ranges stay tied to an operator metric exactly like
NvtxWithMetrics ties a range to a SQLMetric.
"""
from __future__ import annotations

import contextlib as _contextlib
import threading
import time
from typing import Dict, Optional

# Standard metric names (GpuMetricNames analog, GpuExec.scala:28-52)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
TOTAL_TIME = "totalTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
BUFFER_TIME = "bufferTime"
DECODE_TIME = "tpuDecodeTime"

# Shuffle fault-tolerance counters (one MetricSet per transport, shared by
# the env/client/reader layers — RapidsShuffleInternalManager's
# rapidsShuffle* metrics role, extended with the retry/corruption story)
SHUFFLE_FETCH_RETRIES = "shuffleFetchRetries"        # reader re-fetches a peer
SHUFFLE_TRANSFER_RETRIES = "shuffleTransferRetries"  # per-block re-transfers
SHUFFLE_RPC_RETRIES = "shuffleRpcRetries"            # metadata request retries
SHUFFLE_CONNECT_RETRIES = "shuffleConnectRetries"    # TCP connect re-attempts
SHUFFLE_CHECKSUM_FAILURES = "shuffleChecksumFailures"  # corrupt payloads caught
SHUFFLE_PEER_EVICTIONS = "shufflePeerEvictions"      # dead clients evicted
SHUFFLE_CODEC_FALLBACKS = "shuffleCodecFallbacks"    # negotiated down to copy

SHUFFLE_METRIC_NAMES = (
    SHUFFLE_FETCH_RETRIES, SHUFFLE_TRANSFER_RETRIES, SHUFFLE_RPC_RETRIES,
    SHUFFLE_CONNECT_RETRIES, SHUFFLE_CHECKSUM_FAILURES,
    SHUFFLE_PEER_EVICTIONS, SHUFFLE_CODEC_FALLBACKS)

# Host-link transfer counters (bufferTime/gpuDecodeTime observability role,
# process-global like the link itself: uploads happen inside
# DeviceBatch.from_arrow / the chunked pipeline, far from any operator's
# MetricSet). session.last_metrics exposes the per-action delta plus the
# derived link GB/s.
TRANSFER_UPLOAD_BYTES = "transfer.upload_bytes"
TRANSFER_UPLOAD_SECONDS = "transfer.upload_seconds"
TRANSFER_UPLOAD_CHUNKS = "transfer.upload_chunks"
TRANSFER_DOWNLOAD_BYTES = "transfer.download_bytes"
TRANSFER_DOWNLOAD_SECONDS = "transfer.download_seconds"
TRANSFER_INFLIGHT_PEAK = "transfer.inflight_peak"
# compressed columnar path: bytes actually staged for the link (encoded
# forms: dict indices + dictionary, RLE run ends + run values) vs the bytes
# the decoded columns would have staged — the per-action ratio is the link
# compression the encoded path bought (transfer.compression_ratio in
# session.last_metrics["transfer"]).
TRANSFER_ENCODED_BYTES = "transfer.encoded_bytes"
TRANSFER_DECODED_EQUIV_BYTES = "transfer.decoded_equivalent_bytes"
#: batch programs that ran a filter/group-by/join on the encoded domain
#: (dictionary indices) instead of decoded values (exprs/encoded.py)
TRANSFER_ENCODED_DOMAIN_OPS = "transfer.encoded_domain_ops"
#: bytes of EXCHANGE data that bounced through the host (device -> host ->
#: device) instead of riding an in-mesh collective: the scatter of a
#: single-device intermediate onto the mesh, and TCP shuffle payloads (the
#: DCN path). The in-mesh all_to_all exchange keeps this at EXACTLY 0 —
#: only per-shard row COUNTS sync to the host, never row data (the bench
#: `mesh` section and CI assert the zero).
TRANSFER_HOST_HOP_BYTES = "transfer.host_hop_bytes"
#: shuffle exchanges that carried a column through partition/repack as
#: dictionary indices + shared dictionary instead of decoded values
TRANSFER_EXCHANGE_ENCODED_OPS = "transfer.exchange_encoded_ops"

TRANSFER_METRIC_NAMES = (
    TRANSFER_UPLOAD_BYTES, TRANSFER_UPLOAD_SECONDS, TRANSFER_UPLOAD_CHUNKS,
    TRANSFER_DOWNLOAD_BYTES, TRANSFER_DOWNLOAD_SECONDS,
    TRANSFER_INFLIGHT_PEAK, TRANSFER_ENCODED_BYTES,
    TRANSFER_DECODED_EQUIV_BYTES, TRANSFER_ENCODED_DOMAIN_OPS,
    TRANSFER_HOST_HOP_BYTES, TRANSFER_EXCHANGE_ENCODED_OPS)

# Out-of-core / memory-pressure counters (process-global like the tiered
# store they observe; session.last_metrics["memory"] exposes the per-action
# delta, and per-query handle snapshots carry the same section). The
# degradation story in one glance: how often operators hit pressure, how
# many grace partitions they fanned out, how deep the recursion went, and
# how many bytes each spill tier absorbed.
#: runtime pressure events that forced an operator into the out-of-core
#: path (reactive working-set trigger, store pressure callback, injected
#: allocation failure) — plan-time predicted partitioning does NOT count
MEM_PRESSURE_EVENTS = "memory.pressure_events"
#: spillable grace partitions created by out-of-core operators
MEM_SPILL_PARTITIONS = "memory.spill_partitions"
#: deepest grace recursion level reached (set_max; re-armed per action)
MEM_RECURSION_DEPTH = "memory.recursion_depth_peak"
#: bytes the device tier pushed down to the host tier
MEM_SPILLED_TO_HOST = "memory.bytes_spilled_to_host"
#: bytes the host tier pushed down to the disk tier
MEM_SPILLED_TO_DISK = "memory.bytes_spilled_to_disk"

MEMORY_METRIC_NAMES = (
    MEM_PRESSURE_EVENTS, MEM_SPILL_PARTITIONS, MEM_RECURSION_DEPTH,
    MEM_SPILLED_TO_HOST, MEM_SPILLED_TO_DISK)

# Network-serving counters (process-global like the wire they observe; the
# per-action delta lands in session.last_metrics["serving"], and per-query
# stream/preemption counts additionally ride QueryHandle.metrics).
#: bytes of Arrow-IPC result frames the query server pushed to clients
#: (retransmits of a corrupted frame count again — this is wire traffic)
SERVING_WIRE_BYTES_OUT = "serving.wire_bytes_out"
#: result batches streamed to clients (each counted once, at first send)
SERVING_STREAM_BATCHES = "serving.stream_batches"
#: batch-granularity preemptions: a running query yielded its device
#: permit to a starved tenant at an exec-boundary checkpoint
SERVING_PREEMPTIONS = "serving.preemptions"
#: queries made to WAIT by footprint admission because their
#: working_set_estimate did not fit the free device budget
SERVING_ADMISSION_REJECTIONS = "serving.admission_rejections_footprint"
#: corrupted result frames a client caught by checksum and re-fetched
SERVING_WIRE_RETRIES = "serving.wire_retries"
#: queries resubmitted to another replica after their replica died
#: mid-stream (client-side; each failover counts once per resubmission)
SERVING_FAILOVERS = "serving.failovers"
#: result frames a resumed query re-produced but SKIPPED because the
#: client already held them (dedup by batch sequence number — the
#: exactly-once delivery contract's server-side evidence)
SERVING_RESUMED_BATCHES = "serving.resumed_batches"
#: client-side circuit-breaker CLOSED->OPEN transitions (a replica hit
#: its consecutive-failure threshold and left the routing rotation)
SERVING_BREAKER_OPENS = "serving.breaker_opens"
#: graceful-drain initiations (serve.drain RPC or SIGTERM): the replica
#: flipped to DRAINING, redirecting new submissions while running
#: queries finish and streams flush
SERVING_DRAINS = "serving.drains"
#: supervised replica restarts (supervisor-side: one per respawn of a dead
#: slot, after its deterministic backoff elapsed; crash-loop-halted slots
#: stop counting because they stop restarting)
SERVING_RESTARTS = "serving.restarts"
#: autoscaler scale-up decisions that started a new supervised replica
SERVING_SCALE_UPS = "serving.scale_ups"
#: autoscaler scale-down decisions that retired a replica through the
#: graceful-drain path (zero in-flight queries dropped)
SERVING_SCALE_DOWNS = "serving.scale_downs"
#: submissions shed at the front door with a structured RETRYABLE
#: OverloadedError (per-tenant queue bound serving.maxQueuedPerTenant) —
#: load sheds before it queues, never mid-query
SERVING_SHEDS = "serving.sheds"
#: submissions rejected by the per-client concurrent-query quota
#: (serving.quota.maxConcurrentPerClient) with QuotaExceededError
SERVING_QUOTA_REJECTIONS = "serving.quota_rejections"

SERVING_METRIC_NAMES = (
    SERVING_WIRE_BYTES_OUT, SERVING_STREAM_BATCHES, SERVING_PREEMPTIONS,
    SERVING_ADMISSION_REJECTIONS, SERVING_WIRE_RETRIES, SERVING_FAILOVERS,
    SERVING_RESUMED_BATCHES, SERVING_BREAKER_OPENS, SERVING_DRAINS,
    SERVING_RESTARTS, SERVING_SCALE_UPS, SERVING_SCALE_DOWNS,
    SERVING_SHEDS, SERVING_QUOTA_REJECTIONS)

# Lineage-recompute counters (driver-process-global: the stage driver in
# parallel/cluster.py owns every bump — executors never recompute on their
# own). The escalation ladder in one glance: how often a lost map output
# was repaired by a scoped stage re-execution (instead of a whole-query
# failover), how many map tasks each repair replayed, and how often the
# per-stage attempt budget ran dry and the query escalated to PR 14's
# replica failover.
#: scoped stage re-executions triggered by a ShuffleFetchFailedError
#: (one per recompute round, however many map tasks it replays)
SHUFFLE_RECOMPUTES = "shuffle.recomputes"
#: lost map tasks re-executed on surviving peers (the "bounded" in
#: bounded re-execution: asserted < total map tasks by CI)
SHUFFLE_RECOMPUTED_MAP_TASKS = "shuffle.recomputed_map_tasks"
#: recompute rounds abandoned because shuffle.recompute.maxStageAttempts
#: was exhausted — the error re-surfaces and the failover path owns it
SHUFFLE_RECOMPUTE_ESCALATIONS = "shuffle.recompute_escalations"

RECOMPUTE_METRIC_NAMES = (
    SHUFFLE_RECOMPUTES, SHUFFLE_RECOMPUTED_MAP_TASKS,
    SHUFFLE_RECOMPUTE_ESCALATIONS)

# Adaptive-execution counters (driver-process-global: plan/adaptive.py's
# rewrite pass owns every bump — it runs once per action, in the driver,
# after the shuffle map stages materialized their statistics). The
# re-planning story in one glance: how many skewed partitions were split
# into map-id slices (or re-partitioned, for aggregates), how many small
# reduce partitions folded into coalesced reader groups, how often a
# shuffled join switched to broadcast from observed sizes, and how many
# fused stages the post-AQE re-fusion pass created over rewritten regions.
#: skewed reduce partitions split into PartialReducerSpec slices (joins)
#: or re-partitioned by group key (aggregates) — one per skewed partition
ADAPTIVE_SKEW_SPLITS = "adaptive.skew_splits"
#: reduce partitions removed by AQE coalescing (sum of n_before - n_after
#: over every coalesced reader the rewrite inserted)
ADAPTIVE_COALESCED_PARTITIONS = "adaptive.coalesced_partitions"
#: shuffled hash joins switched to broadcast from observed build sizes
ADAPTIVE_BROADCAST_SWITCHES = "adaptive.broadcast_switches"
#: fused stages newly created by the post-AQE re-fusion pass (stages the
#: plan-time fusion pass could not see because the rewrite created them)
ADAPTIVE_REFUSED_STAGES = "adaptive.refused_stages"

ADAPTIVE_METRIC_NAMES = (
    ADAPTIVE_SKEW_SPLITS, ADAPTIVE_COALESCED_PARTITIONS,
    ADAPTIVE_BROADCAST_SWITCHES, ADAPTIVE_REFUSED_STAGES)

# Per-query serving metrics (QueryHandle.metrics keys, serving/lifecycle.py):
# unlike the per-operator MetricSets — which live on per-action plan nodes —
# and the process-global transfer counters, these are scoped to ONE query
# handle, so concurrent queries never interleave in them.
QUERY_QUEUE_WAIT_S = "queue_wait_s"            # submit -> scheduler pickup
QUERY_ADMISSION_WAIT_S = "admission_wait_s"    # device-semaphore wait
QUERY_COMPILE_S = "compile_s"                  # first-call program builds
QUERY_WALL_S = "wall_s"                        # submit -> terminal state
QUERY_ROWS = "rows"                            # collected result rows

QUERY_METRIC_NAMES = (QUERY_QUEUE_WAIT_S, QUERY_ADMISSION_WAIT_S,
                      QUERY_COMPILE_S, QUERY_WALL_S, QUERY_ROWS)


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list (p50/p99 latency
    reporting for the serving bench and scheduler stats)."""
    if not sorted_vals:
        return 0.0
    if q <= 0:
        return float(sorted_vals[0])
    import math
    rank = math.ceil(q / 100.0 * len(sorted_vals))
    return float(sorted_vals[min(len(sorted_vals), max(1, rank)) - 1])


class Metric:
    __slots__ = ("name", "unit", "_value", "_lock")

    def __init__(self, name: str, unit: str = "sum"):
        self.name = name
        self.unit = unit
        self._value = 0
        self._lock = threading.Lock()

    def __getstate__(self):
        # plans ship to cluster executors by pickle; the lock is process-local
        return (self.name, self.unit, self._value)

    def __setstate__(self, state):
        self.name, self.unit, self._value = state
        self._lock = threading.Lock()

    def add(self, v: int) -> None:
        with self._lock:
            self._value += v

    def set_max(self, v: int) -> None:
        with self._lock:
            self._value = max(self._value, v)

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def __repr__(self) -> str:
        return f"Metric({self.name}={self.value})"


class MetricSet:
    """Mutable bag of metrics owned by one physical operator instance."""

    def __init__(self, *names: str):
        self._metrics: Dict[str, Metric] = {n: Metric(n) for n in names}

    def metric(self, name: str) -> Metric:
        m = self._metrics.get(name)
        if m is None:
            m = Metric(name)
            self._metrics[name] = m
        return m

    def __getitem__(self, name: str) -> Metric:
        return self.metric(name)

    def snapshot(self) -> Dict[str, int]:
        return {n: m.value for n, m in self._metrics.items()}


#: process-global transfer counters (see TRANSFER_METRIC_NAMES above)
TRANSFER_METRICS = MetricSet(*TRANSFER_METRIC_NAMES)

#: process-global memory-pressure counters (see MEMORY_METRIC_NAMES above)
MEMORY_METRICS = MetricSet(*MEMORY_METRIC_NAMES)

#: process-global network-serving counters (see SERVING_METRIC_NAMES above)
SERVING_METRICS = MetricSet(*SERVING_METRIC_NAMES)

#: driver-global lineage-recompute counters (see RECOMPUTE_METRIC_NAMES)
RECOMPUTE_METRICS = MetricSet(*RECOMPUTE_METRIC_NAMES)

#: driver-global adaptive-execution counters (see ADAPTIVE_METRIC_NAMES)
ADAPTIVE_METRICS = MetricSet(*ADAPTIVE_METRIC_NAMES)


def adaptive_snapshot() -> Dict[str, float]:
    """Action-start marker for ``adaptive_delta`` (all counters additive)."""
    return ADAPTIVE_METRICS.snapshot()


def adaptive_delta(before: Dict[str, float]) -> Dict[str, float]:
    """Per-action adaptive stats: counter deltas since ``before``. Like the
    recompute section the counters live in the driver process (the AQE
    rewrite is the only bump site); under concurrent queries a delta can
    still include an overlapping action's rewrite decisions."""
    now = ADAPTIVE_METRICS.snapshot()
    return {name: now[name] - before.get(name, 0)
            for name in ADAPTIVE_METRIC_NAMES}


def recompute_snapshot() -> Dict[str, float]:
    """Action-start marker for ``recompute_delta`` (all counters additive)."""
    return RECOMPUTE_METRICS.snapshot()


def recompute_delta(before: Dict[str, float]) -> Dict[str, float]:
    """Per-action recompute stats: counter deltas since ``before``. The
    counters live in the DRIVER process (the stage driver is the only bump
    site), so unlike the transfer/serving sections there is no executor-side
    aggregation to fold in; under concurrent queries a delta can still
    include an overlapping query's recompute rounds."""
    now = RECOMPUTE_METRICS.snapshot()
    return {name: now[name] - before.get(name, 0)
            for name in RECOMPUTE_METRIC_NAMES}


def serving_snapshot() -> Dict[str, float]:
    """Action-start marker for ``serving_delta`` (all counters additive)."""
    return SERVING_METRICS.snapshot()


def serving_delta(before: Dict[str, float]) -> Dict[str, float]:
    """Per-action serving stats: counter deltas since ``before``. Like the
    transfer section, counters are process-global — under concurrent
    queries an action's delta can include overlapping queries' wire
    traffic and preemptions; per-query exact counts live on the handle."""
    now = SERVING_METRICS.snapshot()
    return {name: now[name] - before.get(name, 0)
            for name in SERVING_METRIC_NAMES}


class _ActionDepth:
    """Per-action recursion-depth high-water mark, bound thread-locally by
    the action driver (``action_depth_scope``). This replaces the old
    re-armed global as the per-action record: the re-arm raced under
    CONCURRENT out-of-core queries (a later action's reset absorbed part
    of an overlapping action's peak — the PR 11 round-2 finding). The
    process-global metric keeps its lifetime high-water mark; per-action
    and per-query peaks come from this scope and the query handle."""

    __slots__ = ("peak",)

    def __init__(self):
        self.peak = 0


_DEPTH_TLS = threading.local()


@_contextlib.contextmanager
def action_depth_scope():
    """Context manager binding a fresh per-action depth holder to the
    calling thread (the thread that drives the operators; grace recursion
    runs on it). Yields the holder; read ``holder.peak`` after the
    action."""
    holder = _ActionDepth()
    prev = getattr(_DEPTH_TLS, "holder", None)
    _DEPTH_TLS.holder = holder
    try:
        yield holder
    finally:
        _DEPTH_TLS.holder = prev


def note_recursion_depth(depth: int, query=None) -> None:
    """One grace recursion level reached: attribute the high-water mark to
    (1) the process-lifetime global, (2) the thread-bound ACTION scope —
    the per-action record memory_delta reports — and (3) the owning
    query's handle when one is bound (mirroring per-handle snapshots)."""
    MEMORY_METRICS[MEM_RECURSION_DEPTH].set_max(depth)
    holder = getattr(_DEPTH_TLS, "holder", None)
    if holder is not None and depth > holder.peak:
        holder.peak = depth
    if query is not None:
        query.note_recursion_depth(depth)


def memory_snapshot() -> Dict[str, float]:
    """Action-start marker for ``memory_delta``. (No re-arm: the global
    recursion-depth metric is a process-lifetime high-water mark; the
    per-action peak comes from ``action_depth_scope``.)"""
    return MEMORY_METRICS.snapshot()


def memory_delta(before: Dict[str, float],
                 recursion_peak: Optional[int] = None) -> Dict[str, float]:
    """Per-action out-of-core stats: counter deltas since ``before``.
    ``recursion_peak`` is the action-scoped depth high-water mark from
    ``action_depth_scope`` (exact under concurrency); without it the
    global lifetime maximum is reported only when it ADVANCED during the
    window (conservative fallback for callers outside the action driver)."""
    now = MEMORY_METRICS.snapshot()
    out: Dict[str, float] = {}
    for name in MEMORY_METRIC_NAMES:
        if name == MEM_RECURSION_DEPTH:
            if recursion_peak is not None:
                out[name] = recursion_peak
            else:
                out[name] = (now[name]
                             if now[name] > before.get(name, 0) else 0)
            continue
        out[name] = now[name] - before.get(name, 0)
    return out


def transfer_snapshot() -> Dict[str, float]:
    """Action-start marker for ``transfer_delta``. Re-arms the in-flight
    high-water mark so the delta reports THIS action's peak, not the
    process-lifetime maximum."""
    snap = TRANSFER_METRICS.snapshot()
    TRANSFER_METRICS[TRANSFER_INFLIGHT_PEAK].reset()
    return snap


def transfer_delta(before: Dict[str, float]) -> Dict[str, float]:
    """Per-action transfer stats: counter deltas since ``before`` plus the
    derived link rates (upload_gb_per_sec / download_gb_per_sec)."""
    now = TRANSFER_METRICS.snapshot()
    out: Dict[str, float] = {}
    for name in TRANSFER_METRIC_NAMES:
        if name == TRANSFER_INFLIGHT_PEAK:
            # high-water mark since the matching transfer_snapshot call
            out[name] = now[name]
            continue
        out[name] = now[name] - before.get(name, 0)
    for direction in ("upload", "download"):
        b = out[f"transfer.{direction}_bytes"]
        s = out[f"transfer.{direction}_seconds"]
        out[f"transfer.{direction}_gb_per_sec"] = (
            round(b / s / 1e9, 3) if s > 0 else 0.0)
    # encoded-path link compression for this action: < 1.0 means the upload
    # shipped fewer bytes than the decoded columns would have
    dec = out[TRANSFER_DECODED_EQUIV_BYTES]
    out["transfer.compression_ratio"] = (
        round(out[TRANSFER_ENCODED_BYTES] / dec, 4) if dec > 0 else 1.0)
    return out


class NamedRange:
    """Timed, profiler-visible range tied to a metric (NvtxWithMetrics analog).

    Adds elapsed nanoseconds to ``metric`` on exit and, when tracing is enabled,
    shows up as a named range in the XLA/TensorBoard profile.
    """

    def __init__(self, name: str, metric: Optional[Metric] = None, trace: bool = False):
        self._name = name
        self._metric = metric
        self._trace = trace
        self._ctx = None
        self._t0 = 0

    def __enter__(self) -> "NamedRange":
        if self._trace:
            try:
                import jax.profiler
                self._ctx = jax.profiler.TraceAnnotation(self._name)
                self._ctx.__enter__()
            except Exception:
                self._ctx = None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        if self._metric is not None:
            self._metric.add(time.perf_counter_ns() - self._t0)
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
