"""Failure-escalation ladder: the engine's error taxonomy, declared in code.

PRs 2/16/14 built a three-rung escalation ladder — transfer retry
(shuffle/retry.py) → lineage-scoped stage recompute (parallel/cluster.py) →
whole-query replica failover (serving/client.py) — but until now its routing
discipline lived only in tests: a dozen error classes scattered across eight
modules with no declared retryable/permanent/cancellation contract.  This
module is the single place that contract is written down, and tpu-lint
R013–R015 (analysis/rules_exceptions.py) machine-check the package against it.

Every engine error class is registered here with:

  * a **classification** — how the ladder must treat it:
      - RETRYABLE:          safe to retry the failed operation in place
        (rung 1: transfer retry / drain redirect).
      - PERMANENT:          deterministic; retrying reproduces the failure.
      - CANCELLATION:       the caller gave up; must never be retried into
        life (R014 flags CANCELLATION → RETRYABLE conversions).
      - ESCALATION_SIGNAL:  carries structured payload that a HIGHER rung
        triages (recompute / failover); swallowing one breaks the ladder
        (R013 flags handlers that absorb a may-raised signal).
  * a **wire code** — the stable codec tag used when the exception crosses a
    process boundary (executor-daemon control socket, serving wire).  Types
    without a code degrade to OpaqueWireError, which is non-retryable by
    construction (R015 flags raise sites whose type would degrade).
  * its **home module** — classes stay defined next to the subsystem that
    raises them (no import churn); this module re-exports them lazily via
    PEP 562 ``__getattr__`` so ``from spark_rapids_tpu.utils.errors import
    ShuffleFetchFailedError`` works without import cycles.

The registry is intentionally lazy: keys are ``"module.path:ClassName"``
strings, so importing this module pulls in nothing else.  Classification
lookup walks ``type(exc).__mro__`` and matches on that key, so subclasses
inherit their base's classification.
"""
from __future__ import annotations

import importlib
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple, Type

# ---------------------------------------------------------------------------
# classifications

RETRYABLE = "RETRYABLE"
PERMANENT = "PERMANENT"
CANCELLATION = "CANCELLATION"
ESCALATION_SIGNAL = "ESCALATION_SIGNAL"

CLASSIFICATIONS = (RETRYABLE, PERMANENT, CANCELLATION, ESCALATION_SIGNAL)


class OpaqueWireError(RuntimeError):
    """An exception without a registered wire codec crossed a process
    boundary.  Deliberately non-retryable (PERMANENT): an unclassified
    failure must not be retried on a hunch — register the type instead."""

    def __init__(self, message: str, wire_code: str = "OPAQUE"):
        super().__init__(message)
        self.wire_code = wire_code


# ---------------------------------------------------------------------------
# registry

@dataclass(frozen=True)
class ErrorSpec:
    """One registered engine error class.

    ``home`` is ``"module.path:ClassName"``; ``fields`` are the structured
    attributes the wire codec round-trips; ``ctor`` says how decode rebuilds
    the instance: ``"message"`` (positional message only), ``"message+fields"``
    (message plus keyword fields), or ``"fields"`` (keyword fields only — the
    class formats its own message)."""

    home: str
    classification: str
    wire_code: str
    fields: Tuple[str, ...] = ()
    ctor: str = "message"
    ladder_signal: bool = False
    doc: str = ""

    @property
    def name(self) -> str:
        return self.home.rsplit(":", 1)[1]

    @property
    def module(self) -> str:
        return self.home.rsplit(":", 1)[0]


TAXONOMY: Tuple[ErrorSpec, ...] = (
    # --- escalation signals: structured payloads a higher rung triages -----
    ErrorSpec("spark_rapids_tpu.shuffle.manager:ShuffleFetchFailedError",
              ESCALATION_SIGNAL, "SHUFFLE_FETCH_FAILED",
              fields=("executor_id", "blocks"), ctor="message+fields",
              ladder_signal=True,
              doc="lost shuffle blocks; triggers lineage-scoped recompute"),
    ErrorSpec("spark_rapids_tpu.memory.buffer:SpillCorruptionError",
              ESCALATION_SIGNAL, "SPILL_CORRUPTION",
              fields=("path", "expected", "actual"), ctor="fields",
              ladder_signal=True,
              doc="spill file failed checksum on unspill; buffer is lost"),
    ErrorSpec("spark_rapids_tpu.serving.client:WireQueryError",
              ESCALATION_SIGNAL, "WIRE_QUERY",
              fields=("batches_delivered", "retryable"), ctor="message+fields",
              ladder_signal=True,
              doc="serving-wire query failure; failover triages .retryable"),
    # --- retryable: rung-1 handles these in place --------------------------
    ErrorSpec("spark_rapids_tpu.shuffle.table_meta:ChecksumError",
              RETRYABLE, "CHECKSUM", ladder_signal=True,
              doc="corrupt shuffle frame; transfer retry re-fetches"),
    ErrorSpec("spark_rapids_tpu.serving.lifecycle:SchedulerDrainingError",
              RETRYABLE, "SCHEDULER_DRAINING",
              doc="replica refusing new work; redirect to a peer"),
    ErrorSpec("spark_rapids_tpu.serving.lifecycle:OverloadedError",
              RETRYABLE, "OVERLOADED",
              fields=("retry_after_s",), ctor="message+fields",
              doc="front-door shed (tenant queue at bound); client honors "
                  "retry_after_s on its deterministic backoff"),
    ErrorSpec("spark_rapids_tpu.serving.lifecycle:QuotaExceededError",
              RETRYABLE, "QUOTA_EXCEEDED",
              fields=("retry_after_s",), ctor="message+fields",
              doc="per-client concurrency quota hit; retry after own "
                  "queries finish — rerouting cannot help"),
    # --- cancellation: must never be retried into life ---------------------
    ErrorSpec("spark_rapids_tpu.serving.lifecycle:QueryCancelledError",
              CANCELLATION, "QUERY_CANCELLED", ladder_signal=True,
              doc="caller cancelled; checkpoints re-raise, nothing retries"),
    ErrorSpec("spark_rapids_tpu.serving.lifecycle:QueryTimeoutError",
              CANCELLATION, "QUERY_TIMEOUT",
              doc="deadline exceeded; treated as cancellation by the ladder"),
    # --- permanent: deterministic, retrying reproduces the failure ---------
    ErrorSpec("spark_rapids_tpu.sql.lexer:SqlError",
              PERMANENT, "SQL", doc="malformed query text"),
    ErrorSpec("spark_rapids_tpu.ops.regex:RegexError",
              PERMANENT, "REGEX", doc="unsupported/invalid regex pattern"),
    ErrorSpec("spark_rapids_tpu.plan.catalyst_import:CatalystImportError",
              PERMANENT, "CATALYST_IMPORT", doc="unconvertible Catalyst plan"),
    ErrorSpec("spark_rapids_tpu.udf.compiler:UdfCompileError",
              PERMANENT, "UDF_COMPILE", doc="UDF body not compilable"),
    ErrorSpec("spark_rapids_tpu.utils.errors:OpaqueWireError",
              PERMANENT, "OPAQUE", doc="unregistered type crossed the wire"),
)

_BY_HOME: Dict[str, ErrorSpec] = {s.home: s for s in TAXONOMY}
_BY_NAME: Dict[str, ErrorSpec] = {s.name: s for s in TAXONOMY}
_BY_CODE: Dict[str, ErrorSpec] = {s.wire_code: s for s in TAXONOMY}
assert len(_BY_NAME) == len(TAXONOMY), "duplicate leaf class name in taxonomy"
assert len(_BY_CODE) == len(TAXONOMY), "duplicate wire code in taxonomy"


def ladder_signals() -> Tuple[str, ...]:
    """Leaf names of the classes whose swallowing breaks the ladder (R013)."""
    return tuple(s.name for s in TAXONOMY if s.ladder_signal)


def spec_for(exc: Any) -> Optional[ErrorSpec]:
    """Registered spec for an exception instance or class (MRO-aware:
    subclasses of a registered class inherit its spec)."""
    klass = exc if isinstance(exc, type) else type(exc)
    for base in klass.__mro__:
        spec = _BY_HOME.get(f"{base.__module__}:{base.__qualname__}")
        if spec is not None:
            return spec
    return None


def spec_by_name(name: str) -> Optional[ErrorSpec]:
    return _BY_NAME.get(name)


def classification_for(exc: Any) -> Optional[str]:
    spec = spec_for(exc)
    return spec.classification if spec is not None else None


def is_retryable(exc: Any) -> bool:
    return classification_for(exc) == RETRYABLE


def is_cancellation(exc: Any) -> bool:
    return classification_for(exc) == CANCELLATION


def resolve(spec: ErrorSpec) -> Type[BaseException]:
    """Import the spec's home module and return the class (lazy)."""
    mod = importlib.import_module(spec.module)
    return getattr(mod, spec.name)


# ---------------------------------------------------------------------------
# wire codec

def _tupled(v: Any) -> Any:
    # a JSON hop turns tuples into lists; structured fields (e.g. block
    # coordinates) are tuples in the engine, so coerce lists back on
    # decode.  Fields that rode a pickle transport (the executor-daemon
    # control socket) arrive untouched — including MapStatus/BlockId
    # namedtuples — and pass through unchanged.
    if isinstance(v, list):
        return tuple(_tupled(x) for x in v)
    return v


def encode_error(exc: BaseException, message: Optional[str] = None) -> dict:
    """Encode an exception for a process boundary.  Registered types carry
    their wire code + structured fields; anything else degrades to OPAQUE
    (non-retryable on the far side).  ``message`` overrides ``str(exc)`` —
    used by boundaries that want to ship a traceback.  Fields are shipped
    as-is: pickle transports keep full fidelity, JSON transports should
    serialize with ``default=str`` (exotic payloads degrade readably)."""
    spec = spec_for(exc)
    msg = message if message is not None else f"{type(exc).__name__}: {exc}"
    if spec is None:
        return {"code": "OPAQUE", "message": msg, "fields": {}}
    fields = {f: getattr(exc, f, None) for f in spec.fields}
    return {"code": spec.wire_code, "message": msg, "fields": fields}


def decode_error(payload: Any) -> BaseException:
    """Rebuild an exception from an encode_error payload.  Any malformed or
    unknown payload degrades to OpaqueWireError — never raises itself."""
    try:
        code = payload["code"]
        message = str(payload.get("message", ""))
        fields = {k: _tupled(v) for k, v in dict(payload.get("fields", {})).items()}
    except Exception:
        return OpaqueWireError(f"undecodable wire error payload: {payload!r}")
    spec = _BY_CODE.get(code)
    if spec is None:
        return OpaqueWireError(message, wire_code=code)
    try:
        klass = resolve(spec)
        if spec.ctor == "fields":
            exc = klass(**fields)
        elif spec.ctor == "message+fields":
            exc = klass(message, **fields)
        else:
            exc = klass(message)
    except Exception:
        return OpaqueWireError(message, wire_code=code)
    exc.wire_code = spec.wire_code
    return exc


# ---------------------------------------------------------------------------
# ladder boundary markers

def triage_boundary(fn):
    """Marks a function as a registered triage point of the failure ladder —
    a place that legitimately catches escalation signals and routes them
    (retry loop, recompute triage, failover decision, cancellation sink).
    No runtime behavior; tpu-lint R013/R014 read the decorator statically:
    handlers inside (or calling into) a triage boundary are exempt from the
    swallowed-signal rule, and classes arriving at one must be registered
    here."""
    fn.__ladder_triage_boundary__ = True
    return fn


#: context -> count of classified exceptions deliberately absorbed at a
#: terminal sink (cleanup/unwind paths where propagation would mask the
#: primary failure); keeps swallowed ladder signals observable
ABSORBED_COUNTS: Dict[str, int] = {}
_ABSORB_LOCK = threading.Lock()


@triage_boundary
def absorb(exc: BaseException, context: str) -> None:
    """Registered terminal triage: deliberately absorb ``exc`` on an
    unwind/cleanup path where propagating it would mask the primary
    failure (abandoning a stream, best-effort teardown).  The swallow is
    counted per (context, type) so a ladder signal dying here is still
    visible to operators — R013 accepts a handler that routes through
    this instead of silently ``pass``-ing."""
    key = f"{context}:{type(exc).__name__}"
    with _ABSORB_LOCK:
        ABSORBED_COUNTS[key] = ABSORBED_COUNTS.get(key, 0) + 1


def wire_boundary(fn):
    """Marks a function that serializes exceptions across a process boundary
    (executor-daemon control socket, serving wire).  No runtime behavior;
    tpu-lint R015 checks that every package exception type that may-raise
    into one has a registered wire code — unregistered types degrade to
    OpaqueWireError and lose their classification on the far side."""
    fn.__ladder_wire_boundary__ = True
    return fn


# ---------------------------------------------------------------------------
# lazy re-exports (PEP 562): the classes stay defined in their home modules

def __getattr__(name: str):
    spec = _BY_NAME.get(name)
    if spec is not None and spec.module != __name__:
        return resolve(spec)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_BY_NAME))
