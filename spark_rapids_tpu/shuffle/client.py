"""Shuffle client: fetch protocol state machine.

Reference analog: RapidsShuffleClient.scala (804 LoC) — metadata request →
TableMetas → PendingTransferRequests → BufferReceiveState:108 walking receive
bounce buffers, consumeBuffers:193 assembling the target buffer, then handing
the received buffer id to the fetch handler. The inflight throttle
(queuePending / maxReceiveInflightBytes) gates how many bytes of transfers are
outstanding per client.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, List, Optional, Tuple

from spark_rapids_tpu.shuffle import messages as msg
from spark_rapids_tpu.shuffle.catalog import (ReceivedBufferCatalog,
                                              ShuffleBlockId)
from spark_rapids_tpu.shuffle.codec import decompress_batch
from spark_rapids_tpu.shuffle.table_meta import TableMeta
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                ClientConnection,
                                                ShuffleTransport, Transaction,
                                                TransactionStatus)


class ShuffleFetchHandler:
    """Callbacks a task iterator registers for one fetch
    (RapidsShuffleFetchHandler analog)."""

    def start(self, expected_tables: int) -> None: ...

    def batch_received(self, received_id: int) -> None: ...

    def transfer_error(self, message: str) -> None: ...


class PendingTransferRequest:
    """One table awaiting transfer (PendingTransferRequest analog)."""

    def __init__(self, block: ShuffleBlockId, table_idx: int, meta: TableMeta):
        self.block = block
        self.table_idx = table_idx
        self.meta = meta


class BufferReceiveState:
    """Receives one table's packed buffer as chunked tag-addressed receives
    through the bounce pool, assembling into the final target buffer
    (BufferReceiveState + consumeBuffers analog)."""

    def __init__(self, client: "ShuffleClient", base_tag: int, wire_size: int,
                 chunk_size: int,
                 on_done: Callable[[Optional[bytearray], Optional[str]], None]):
        self.client = client
        self.base_tag = base_tag
        self.chunk_size = chunk_size
        self.wire_size = wire_size
        self.target = bytearray(wire_size)
        self.num_chunks = max(1, -(-wire_size // chunk_size))
        self._next_chunk = 0
        self._outstanding = 0
        self._failed = False
        self._lock = threading.Lock()
        self._on_done = on_done

    def start(self) -> None:
        window = min(self.num_chunks, 4)
        bounces = self.client.transport.recv_bounce.acquire(window)
        with self._lock:
            for bb in bounces:
                self._arm(bb)

    def _arm(self, bounce) -> None:
        i = self._next_chunk
        if i >= self.num_chunks or self._failed:
            bounce.close()
            if self._outstanding == 0:
                done, self._on_done = self._on_done, None
                if done is not None and not self._failed:
                    done(self.target, None)
            return
        self._next_chunk += 1
        self._outstanding += 1
        start = i * self.chunk_size
        length = min(self.chunk_size, self.wire_size - start)
        alt = AddressLengthTag(bounce.buffer, length, self.base_tag + i)

        def on_rx(tx: Transaction, bounce=bounce, i=i, start=start, length=length):
            with self._lock:
                self._outstanding -= 1
                if tx.status is not TransactionStatus.SUCCESS:
                    first_error = not self._failed
                    self._failed = True
                    bounce.close()
                    if first_error:
                        done, self._on_done = self._on_done, None
                        if done is not None:
                            done(None, tx.error_message or "receive failed")
                    return
                self.target[start:start + length] = bounce.buffer[:length]
                self._arm(bounce)
        self.client.connection.receive(alt, on_rx)


class ShuffleClient:
    """Per-peer fetch driver (RapidsShuffleClient analog)."""

    _tag_seq = itertools.count(1)

    def __init__(self, transport: ShuffleTransport,
                 connection: ClientConnection,
                 received_catalog: ReceivedBufferCatalog,
                 codec_name: str = "none"):
        self.transport = transport
        self.connection = connection
        self.received = received_catalog
        self.codec_name = codec_name
        self.chunk_size = transport.send_bounce.buffer_size

    # ---- protocol --------------------------------------------------------------
    def fetch(self, blocks: List[ShuffleBlockId],
              handler: ShuffleFetchHandler) -> None:
        """Fetch all tables of ``blocks`` from this peer; async — results land
        via handler callbacks."""
        if not blocks:
            handler.start(0)
            return
        req = msg.MetadataRequest(blocks[0].shuffle_id,
                                  blocks[0].partition_id, tuple(blocks))

        def on_meta(tx: Transaction):
            if tx.status is not TransactionStatus.SUCCESS:
                handler.transfer_error(tx.error_message or "metadata failed")
                return
            resp = msg.MetadataResponse.from_bytes(tx.response)
            pending = [PendingTransferRequest(b, i, m)
                       for b, i, m in resp.tables]
            # the tracker only lists non-empty blocks, so a requested block the
            # server no longer has is a lost block, not an empty one
            answered = {p.block for p in pending}
            missing = [b for b in blocks if b not in answered]
            if missing:
                handler.transfer_error(
                    f"peer {self.connection.peer_executor_id} lost blocks: "
                    f"{missing[:3]}{'...' if len(missing) > 3 else ''}")
                return
            handler.start(len(pending))
            for p in pending:
                self._issue_transfer(p, handler)
        self.connection.request(msg.REQ_METADATA, req.to_bytes(), on_meta)

    def _issue_transfer(self, p: PendingTransferRequest,
                        handler: ShuffleFetchHandler) -> None:
        base_tag = (next(self._tag_seq) << 16)
        treq = msg.TransferRequest(p.block, p.table_idx, base_tag,
                                   self.chunk_size, self.codec_name)
        # admission control before the server starts pushing chunks
        self.transport.throttle.acquire(p.meta.packed_size)
        released = threading.Event()

        def release_once():
            if not released.is_set():
                released.set()
                self.transport.throttle.release(p.meta.packed_size)

        def on_transfer_resp(tx: Transaction):
            if tx.status is not TransactionStatus.SUCCESS:
                release_once()
                handler.transfer_error(tx.error_message or "transfer failed")
                return
            resp = msg.TransferResponse.from_bytes(tx.response)

            def on_buffer(target: Optional[bytearray], error: Optional[str]):
                release_once()
                if error is not None:
                    handler.transfer_error(error)
                    return
                try:
                    raw, meta = decompress_batch(bytes(target), resp.meta)
                    rid = self.received.add(raw, meta)
                except Exception as e:  # noqa: BLE001
                    handler.transfer_error(f"{type(e).__name__}: {e}")
                    return
                handler.batch_received(rid)
            BufferReceiveState(self, base_tag, resp.wire_size,
                               self.chunk_size, on_buffer).start()
        self.connection.request(msg.REQ_TRANSFER, treq.to_bytes(),
                                on_transfer_resp)
