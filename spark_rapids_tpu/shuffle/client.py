"""Shuffle client: fetch protocol state machine.

Reference analog: RapidsShuffleClient.scala (804 LoC) — metadata request →
TableMetas → PendingTransferRequests → BufferReceiveState:108 walking receive
bounce buffers, consumeBuffers:193 assembling the target buffer, then handing
the received buffer id to the fetch handler. The inflight throttle
(queuePending / maxReceiveInflightBytes) gates how many bytes of transfers are
outstanding per client.

Fault tolerance on top of the reference protocol:

- the metadata RPC and each per-block transfer retry transient failures
  under ``spark.rapids.tpu.shuffle.maxRetries`` / ``.retryBackoffMs``
  (deterministic-jitter exponential backoff; retries re-issue on a timer
  thread, never on the transport's progress thread);
- every assembled buffer is verified against the server's crc32
  (TransferResponse.checksum) before decompression — corruption is a
  retryable error, not a wrong answer;
- a fetch fails AT MOST ONCE per attempt, and the error names exactly the
  blocks that were not delivered, so the reader (or the lineage recompute)
  re-fetches only those.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from spark_rapids_tpu.shuffle import messages as msg
from spark_rapids_tpu.shuffle import retry
from spark_rapids_tpu.shuffle.catalog import (ReceivedBufferCatalog,
                                              ShuffleBlockId)
from spark_rapids_tpu.shuffle.codec import (ChecksumError, decompress_batch,
                                            verify_checksum)
from spark_rapids_tpu.shuffle.table_meta import TableMeta
from spark_rapids_tpu.shuffle.transport import (AddressLengthTag,
                                                ClientConnection,
                                                ShuffleTransport, Transaction,
                                                TransactionStatus)
from spark_rapids_tpu.utils import metrics as mt
from spark_rapids_tpu.utils.errors import triage_boundary


class ShuffleFetchHandler:
    """Callbacks a task iterator registers for one fetch
    (RapidsShuffleFetchHandler analog)."""

    def start(self, expected_tables: int,
              tables: Sequence[Tuple[ShuffleBlockId, int]] = ()) -> None:
        """``tables`` enumerates the (block, table_idx) pairs this fetch will
        deliver — the reader's completion/dedup bookkeeping."""

    def batch_received(self, received_id: int,
                       block: Optional[ShuffleBlockId] = None,
                       table_idx: int = 0) -> None: ...

    def transfer_error(self, message: str,
                       failed_blocks: Sequence[ShuffleBlockId] = (),
                       permanent: bool = False) -> None:
        """``failed_blocks`` are the blocks with ≥1 undelivered table — the
        scope of a retry/recompute; blocks already delivered are excluded.
        ``permanent`` marks failures a re-fetch cannot fix (lost blocks):
        the reader must skip its retries and surface the recompute signal."""


class PendingTransferRequest:
    """One table awaiting transfer (PendingTransferRequest analog)."""

    def __init__(self, block: ShuffleBlockId, table_idx: int, meta: TableMeta):
        self.block = block
        self.table_idx = table_idx
        self.meta = meta


class _FetchState:
    """Bookkeeping for one fetch() call: which (block, table_idx) pairs are
    still undelivered, and a fail-once latch so concurrent transfer failures
    collapse into ONE transfer_error carrying the precise failure scope."""

    def __init__(self, blocks: Sequence[ShuffleBlockId],
                 handler: ShuffleFetchHandler):
        self.blocks = tuple(blocks)
        self.handler = handler
        self._lock = threading.Lock()
        self._pending: Set[Tuple[ShuffleBlockId, int]] = set()
        self._failed = False

    def register(self, tables: Sequence[Tuple[ShuffleBlockId, int]]) -> None:
        with self._lock:
            self._pending.update(tables)

    def mark_delivered(self, block: ShuffleBlockId, table_idx: int) -> None:
        with self._lock:
            self._pending.discard((block, table_idx))

    @property
    def failed(self) -> bool:
        with self._lock:
            return self._failed

    def fail(self, message: str, permanent: bool = False) -> None:
        with self._lock:
            if self._failed:
                return
            self._failed = True
            failed_blocks = tuple(sorted({b for b, _ in self._pending}))
        self.handler.transfer_error(message, failed_blocks, permanent)


class BufferReceiveState:
    """Receives one table's packed buffer as chunked tag-addressed receives
    through the bounce pool, assembling into the final target buffer
    (BufferReceiveState + consumeBuffers analog)."""

    def __init__(self, client: "ShuffleClient", base_tag: int, wire_size: int,
                 chunk_size: int,
                 on_done: Callable[[Optional[bytearray], Optional[str]], None]):
        self.client = client
        self.base_tag = base_tag
        self.chunk_size = chunk_size
        self.wire_size = wire_size
        self.target = bytearray(wire_size)
        self.num_chunks = max(1, -(-wire_size // chunk_size))
        self._next_chunk = 0
        self._outstanding = 0
        self._failed = False
        self._lock = threading.Lock()
        self._on_done = on_done

    def start(self) -> None:
        window = min(self.num_chunks, 4)
        bounces = self.client.transport.recv_bounce.acquire(window)
        with self._lock:
            for bb in bounces:
                self._arm(bb)

    def _arm(self, bounce) -> None:
        i = self._next_chunk
        if i >= self.num_chunks or self._failed:
            bounce.close()
            if self._outstanding == 0:
                done, self._on_done = self._on_done, None
                if done is not None and not self._failed:
                    done(self.target, None)
            return
        self._next_chunk += 1
        self._outstanding += 1
        start = i * self.chunk_size
        length = min(self.chunk_size, self.wire_size - start)
        alt = AddressLengthTag(bounce.buffer, length, self.base_tag + i)

        def on_rx(tx: Transaction, bounce=bounce, i=i, start=start, length=length):
            with self._lock:
                self._outstanding -= 1
                if tx.status is not TransactionStatus.SUCCESS:
                    first_error = not self._failed
                    self._failed = True
                    bounce.close()
                    if first_error:
                        done, self._on_done = self._on_done, None
                        if done is not None:
                            done(None, tx.error_message or "receive failed")
                    return
                self.target[start:start + length] = bounce.buffer[:length]
                self._arm(bounce)
        self.client.connection.receive(alt, on_rx)


class ShuffleClient:
    """Per-peer fetch driver (RapidsShuffleClient analog)."""

    _tag_seq = itertools.count(1)

    def __init__(self, transport: ShuffleTransport,
                 connection: ClientConnection,
                 received_catalog: ReceivedBufferCatalog,
                 codec_name: str = "none"):
        self.transport = transport
        self.connection = connection
        self.received = received_catalog
        self.codec_name = codec_name
        if codec_name not in ("none", "copy"):
            # fail fast with the registry's ONE well-formed error on a
            # mistyped/unavailable codec conf, instead of erroring deep in
            # decompress after bytes already crossed the wire
            from spark_rapids_tpu.shuffle.codec import get_codec
            get_codec(codec_name, transport.conf)
        self.chunk_size = transport.send_bounce.buffer_size
        conf = transport.conf
        self.max_retries = conf.shuffle_max_retries
        self.backoff_ms = conf.shuffle_retry_backoff_ms
        self.retry_seed = conf.shuffle_faults_seed
        self.verify_checksums = conf.shuffle_checksum_enabled
        self.metrics = transport.metrics

    # ---- protocol --------------------------------------------------------------
    def fetch(self, blocks: List[ShuffleBlockId],
              handler: ShuffleFetchHandler) -> None:
        """Fetch all tables of ``blocks`` from this peer; async — results land
        via handler callbacks."""
        if not blocks:
            handler.start(0, ())
            return
        state = _FetchState(blocks, handler)
        self._request_metadata(state, attempt=0)

    def _request_metadata(self, state: _FetchState, attempt: int) -> None:
        blocks = state.blocks
        req = msg.MetadataRequest(blocks[0].shuffle_id,
                                  blocks[0].partition_id, blocks)

        def on_meta(tx: Transaction):
            if tx.status is not TransactionStatus.SUCCESS:
                self._retry_metadata(
                    state, attempt, tx.error_message or "metadata failed")
                return
            resp = msg.MetadataResponse.from_bytes(tx.response)
            pending = [PendingTransferRequest(b, i, m)
                       for b, i, m in resp.tables]
            # the tracker only lists non-empty blocks, so a requested block the
            # server no longer has is a lost block, not an empty one — NOT
            # transient (no retry): only a map recompute brings it back
            answered = {p.block for p in pending}
            missing = [b for b in blocks if b not in answered]
            if missing:
                # register EVERY requested block, not just the missing ones:
                # the answered blocks' transfers are never issued either, so
                # the ShuffleFetchFailedError must scope the whole
                # undelivered set for the recompute round to be complete on
                # the first signal
                state.register([(b, 0) for b in blocks])
                state.fail(
                    f"peer {self.connection.peer_executor_id} lost blocks: "
                    f"{missing[:3]}{'...' if len(missing) > 3 else ''}",
                    permanent=True)
                return
            tables = [(p.block, p.table_idx) for p in pending]
            state.register(tables)
            state.handler.start(len(pending), tables)
            for p in pending:
                self._issue_transfer(state, p, attempt=0)
        self.connection.request(msg.REQ_METADATA, req.to_bytes(), on_meta)

    # rung 1 of the failure ladder: transfer-retry triage (deterministic
    # backoff; exhaustion fails the fetch state, which escalates to the
    # driver's recompute rung as ShuffleFetchFailedError)
    @triage_boundary
    def _retry_metadata(self, state: _FetchState, attempt: int,
                        error: str) -> None:
        if attempt >= self.max_retries or state.failed:
            state.register([(b, 0) for b in state.blocks])
            state.fail(error)
            return
        self.metrics[mt.SHUFFLE_RPC_RETRIES].add(1)
        delay = retry.backoff_ms(
            attempt, self.backoff_ms, self.retry_seed,
            key=f"meta:{self.connection.peer_executor_id}")
        retry.call_later(delay,
                         lambda: self._request_metadata(state, attempt + 1))

    def _issue_transfer(self, state: _FetchState, p: PendingTransferRequest,
                        attempt: int) -> None:
        # a FRESH tag range per attempt: chunks of a failed attempt still in
        # flight can never land in a retry's bounce buffers
        base_tag = (next(self._tag_seq) << 16)
        treq = msg.TransferRequest(p.block, p.table_idx, base_tag,
                                   self.chunk_size, self.codec_name)
        # admission control before the server starts pushing chunks
        self.transport.throttle.acquire(p.meta.packed_size)
        released = threading.Event()

        def release_once():
            if not released.is_set():
                released.set()
                self.transport.throttle.release(p.meta.packed_size)

        # rung-1 triage point: a corrupt/failed transfer retries in place
        # with deterministic backoff, or fails the fetch state on
        # exhaustion (escalating to the recompute rung)
        @triage_boundary
        def fail_or_retry(error: str, corrupt: bool = False):
            release_once()
            if corrupt:
                self.metrics[mt.SHUFFLE_CHECKSUM_FAILURES].add(1)
            if attempt >= self.max_retries or state.failed:
                state.fail(error)
                return
            self.metrics[mt.SHUFFLE_TRANSFER_RETRIES].add(1)
            delay = retry.backoff_ms(
                attempt, self.backoff_ms, self.retry_seed,
                key=f"transfer:{p.block}:{p.table_idx}")
            retry.call_later(
                delay, lambda: self._issue_transfer(state, p, attempt + 1))

        def on_transfer_resp(tx: Transaction):
            if tx.status is not TransactionStatus.SUCCESS:
                fail_or_retry(tx.error_message or "transfer failed")
                return
            resp = msg.TransferResponse.from_bytes(tx.response)

            def on_buffer(target: Optional[bytearray], error: Optional[str]):
                if error is not None:
                    fail_or_retry(error)
                    return
                try:
                    wire = bytes(target)
                    if self.verify_checksums:
                        verify_checksum(wire, resp.checksum,
                                        context=f"{p.block} table {p.table_idx}")
                    raw, meta = decompress_batch(wire, resp.meta)
                    # shuffle payload crossed the host (DCN/TCP path): the
                    # in-mesh all_to_all exchange never reaches here
                    mt.TRANSFER_METRICS[mt.TRANSFER_HOST_HOP_BYTES].add(
                        len(raw))
                    rid = self.received.add(raw, meta)
                except ChecksumError as e:
                    fail_or_retry(str(e), corrupt=True)
                    return
                except Exception as e:  # noqa: BLE001
                    fail_or_retry(f"{type(e).__name__}: {e}")
                    return
                release_once()
                state.mark_delivered(p.block, p.table_idx)
                state.handler.batch_received(rid, p.block, p.table_idx)
            BufferReceiveState(self, base_tag, resp.wire_size,
                               self.chunk_size, on_buffer).start()
        self.connection.request(msg.REQ_TRANSFER, treq.to_bytes(),
                                on_transfer_resp)
